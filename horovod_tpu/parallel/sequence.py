"""Sequence / context parallelism: Ulysses all_to_all + ring attention.

The reference stops at the ``alltoall`` primitive users build SP from
(reference: operations.cc:1136-1198; SURVEY.md §5 — no built-in ring
attention).  Long-context is first-class here:

* **Ulysses** (all_to_all SP): inputs sharded over sequence; one all_to_all
  re-shards to head-parallel, full attention runs locally on H/n heads, a
  second all_to_all restores sequence sharding.  Cost: 2 all_to_alls per
  attention; works while n_sp <= n_kv_heads.

* **Ring attention**: k/v blocks rotate around the mesh axis ring via
  `lax.ppermute` (ICI neighbor exchanges) while each chip accumulates its
  queries' attention with an online-softmax (flash-style m/l/o running
  state).  Supports causal masking by block index; sequence length scales
  linearly with chips.

Both are SPMD functions used inside shard_map with the ``sp`` axis, and
slot into models via the ``attn_fn`` hook (models/llama.py, bert.py).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


# ------------------------------------------------------------------- ulysses
def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "sp",
                      causal: bool = True) -> jax.Array:
    """Attention over sequence-sharded q/k/v: [B, S/n, H, D] per chip.

    all_to_all trades the sequence shard for a head shard so every chip
    sees the full sequence for its H/n heads, then trades back."""
    from ..models.layers import causal_attention
    n = lax.psum(1, axis_name)
    H = q.shape[2]
    if H % n != 0:
        raise ValueError(f"heads {H} not divisible by sp axis size {n}")
    # [B, S/n, H, D] -> [B, S, H/n, D]: split heads (axis 2), concat seq (1)
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    o = causal_attention(qh, kh, vh, causal=causal)
    # back: [B, S, H/n, D] -> [B, S/n, H, D]
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


# -------------------------------------------------------------- ring attention
def _block_attend(q, k, v, q_off, k_off, causal: bool,
                  m, l, o):
    """One flash-style accumulation step against a k/v block.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; m/l: [B, H, Sq]; o like q.
    Returns updated (m, l, o).  Softmax statistics kept in fp32."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = q_off + jnp.arange(Sq)
        ki = k_off + jnp.arange(Sk)
        mask = qi[:, None] >= ki[None, :]
        logits = jnp.where(mask[None, None], logits, -1e30)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    # guard fully-masked rows (m_new == -1e30): exp underflows to 0, fine.
    p = jnp.exp(logits - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    o_new = o * alpha.transpose(0, 2, 1)[..., None].astype(o.dtype) + pv
    return m_new, l_new, o_new


def _pvary_missing(t, axis_name):
    """Mark ``t`` varying over ``axis_name`` so fori_loop carry types line
    up when the initial value is device-invariant (newer-JAX vma typing;
    no-op on older JAX)."""
    if not hasattr(lax, "pvary"):
        return t
    axes = ((axis_name,) if isinstance(axis_name, str)
            else tuple(axis_name))
    vma = getattr(jax.typeof(t), "vma", frozenset())
    missing = tuple(a for a in axes if a not in vma)
    return lax.pvary(t, missing) if missing else t


def _flash_ring_step(q, kk, vv, src, idx, block_q, block_k):
    """One ring step through the Pallas flash kernel.

    The k/v block now local started on chip ``src``; relative to this
    chip's q block it is either fully visible (src < idx — plain
    attention), diagonal (src == idx — standard causal), or fully masked
    (src > idx — zero contribution).  Offsets are whole-shard multiples,
    so the three cases are exact and pick the kernel's own causal flag —
    no offset masks needed.  Returns (out [B,S,H,D] in q.dtype,
    lse [B,H,S] fp32) for the logsumexp merge."""
    from ..ops.flash_attention import _flash_forward

    def full(_):
        return _flash_forward(q, kk, vv, False, block_q, block_k)

    def diag(_):
        return _flash_forward(q, kk, vv, True, block_q, block_k)

    def skip(_):
        B, S, H, _D = q.shape
        return (jnp.zeros_like(q),
                jnp.full((B, H, S), -jnp.inf, jnp.float32))

    case = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
    return lax.switch(case, [full, diag, skip], None)


def _lse_merge(o, lse, o2, lse2):
    """Combine two partial attentions over disjoint key sets from their
    (unnormalized-by-each-other) outputs and logsumexps."""
    lse_new = jnp.logaddexp(lse, lse2)
    # clamp the subtrahend so an all-masked (-inf) pair yields weight 0,
    # not exp(nan)
    safe = jnp.maximum(lse_new, -1e30)
    w1 = jnp.exp(lse - safe).transpose(0, 2, 1)[..., None]
    w2 = jnp.exp(lse2 - safe).transpose(0, 2, 1)[..., None]
    return o.astype(jnp.float32) * w1 + o2.astype(jnp.float32) * w2, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention_flash(q, k, v, axis_name: str,
                          block_q: int, block_k: int) -> jax.Array:
    """Causal ring attention with the Pallas flash kernel as the per-step
    block attention.  GQA k/v stay at their Hkv footprint: the kernel
    maps q-head groups onto shared kv heads itself, so the ring moves
    1/rep of the bytes the repeat-based path would.  Differentiable: the
    backward runs its own ring over the flash backward kernels (see
    ``_ring_flash_bwd``)."""
    return _ring_flash_fwd(q, k, v, axis_name, block_q, block_k)[0]


def _ring_flash_fwd(q, k, v, axis_name, block_q, block_k):
    n = int(lax.psum(1, axis_name))
    idx = lax.axis_index(axis_name)
    B, Sq, H, D = q.shape

    o0 = _pvary_missing(jnp.zeros_like(q, dtype=jnp.float32), axis_name)
    lse0 = _pvary_missing(jnp.full((B, H, Sq), -jnp.inf, jnp.float32),
                          axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        o, lse, kk, vv = carry
        src = (idx - step) % n
        o2, lse2 = _flash_ring_step(q, kk, vv, src, idx, block_q, block_k)
        o, lse = _lse_merge(o, lse, o2, lse2)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return o, lse, kk, vv

    o, lse, _, _ = lax.fori_loop(0, n, body, (o0, lse0, k, v))
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, block_q, block_k, res, g):
    """Ring backward: one pass around the ring re-derives every block's
    gradient contribution from the saved GLOBAL lse (the flash backward
    kernels rebuild p = exp(s - lse) blockwise, so partial-key blocks
    yield exactly their share of dq/dk/dv).  dq accumulates locally; the
    dk/dv accumulators TRAVEL WITH their k/v block and arrive home after
    n hops having collected every chip's contribution."""
    from ..ops.flash_attention import _flash_backward

    q, k, v, out, lse, = res
    n = int(lax.psum(1, axis_name))
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block_grads(kk, vv, src):
        def full(_):
            return _flash_backward(q, kk, vv, out, lse, g, False,
                                   block_q, block_k)

        def diag(_):
            return _flash_backward(q, kk, vv, out, lse, g, True,
                                   block_q, block_k)

        def skip(_):
            return (jnp.zeros_like(q), jnp.zeros_like(kk),
                    jnp.zeros_like(vv))

        case = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
        return lax.switch(case, [full, diag, skip], None)

    dq0 = _pvary_missing(jnp.zeros(q.shape, jnp.float32), axis_name)
    dk0 = _pvary_missing(jnp.zeros(k.shape, jnp.float32), axis_name)
    dv0 = _pvary_missing(jnp.zeros(v.shape, jnp.float32), axis_name)

    def body(step, carry):
        dq, kk, vv, dkk, dvv = carry
        src = (idx - step) % n
        dq_b, dk_b, dv_b = block_grads(kk, vv, src)
        dq = dq + dq_b.astype(jnp.float32)
        dkk = dkk + dk_b.astype(jnp.float32)
        dvv = dvv + dv_b.astype(jnp.float32)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        dkk = lax.ppermute(dkk, axis_name, perm)
        dvv = lax.ppermute(dvv, axis_name, perm)
        return dq, kk, vv, dkk, dvv

    dq, _, _, dk, dv = lax.fori_loop(0, n, body, (dq0, k, v, dk0, dv0))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _ring_flash_fwd_rule(q, k, v, axis_name, block_q, block_k):
    out, res = _ring_flash_fwd(q, k, v, axis_name, block_q, block_k)
    return out, res


_ring_attention_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp",
                   causal: bool = True,
                   kernel: str = "xla",
                   block_q: int = 256, block_k: int = 256) -> jax.Array:
    """Ring attention over a sequence-sharded batch: [B, S/n, H, D] per chip.

    k/v blocks travel the ring (ppermute shift +1) for n steps; each chip
    accumulates online-softmax partial attention for its query block.
    ``kernel='flash'`` runs each step's block attention through the
    Pallas flash kernel (causal only; GQA k/v ride the ring unrepeated);
    the default ``'xla'`` path repeats GQA inputs up front."""
    if kernel == "flash":
        if not causal:
            raise NotImplementedError(
                "flash ring path is causal-only (the 3-way block split "
                "relies on it); use kernel='xla' for bidirectional")
        return _ring_attention_flash(q, k, v, axis_name, block_q, block_k)
    if kernel != "xla":
        raise ValueError(f"unknown ring attention kernel {kernel!r}")
    n = int(lax.psum(1, axis_name))
    idx = lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    Sk = k.shape[1]

    m0 = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    # The carries become device-varying inside the loop (they mix with q);
    # mark the initial values varying so the fori_loop types line up.
    if hasattr(lax, "pvary"):
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)

        def _varying(t):
            vma = getattr(jax.typeof(t), "vma", frozenset())
            missing = tuple(a for a in axes if a not in vma)
            return lax.pvary(t, missing) if missing else t
        m0, l0, o0 = _varying(m0), _varying(l0), _varying(o0)
    q_off = idx * Sq
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        m, l, o, kk, vv = carry
        # Block that started on chip (idx - step) mod n is now local.
        src = (idx - step) % n
        k_off = src * Sk
        m, l, o = _block_attend(q, kk, vv, q_off, k_off, causal, m, l, o)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return m, l, o, kk, vv

    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attn_fn(axis_name: str = "sp", causal: bool = True,
                      kernel: str = "xla",
                      block_q: int = 256, block_k: int = 256):
    """attn_fn hook for the model zoo (models/llama.py apply(attn_fn=...));
    ``kernel='flash'`` uses the Pallas kernel per ring step."""
    return functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal, kernel=kernel,
                             block_q=block_q, block_k=block_k)


def make_ulysses_attn_fn(axis_name: str = "sp", causal: bool = True):
    return functools.partial(ulysses_attention, axis_name=axis_name,
                             causal=causal)
