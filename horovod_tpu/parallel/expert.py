"""Expert parallelism: switch-style MoE dispatch over an ``ep`` mesh axis.

Beyond-reference capability (SURVEY §2.3: EP is "NO built-in; same
alltoall primitive" — the reference only offers ``hvd.alltoall`` for
users to build this themselves).  Here it is first-class: a capacity-
bounded top-1 (switch) router builds a static-shape dispatch tensor, and
TWO ``lax.all_to_all`` hops over the ``ep`` axis move tokens to their
expert's chip and back — the canonical TPU MoE data path (einsum-based
dispatch/combine keeps everything on the MXU; static capacity keeps
shapes compile-time constant).

Layout: with E experts over an ep-way axis, each chip owns E/ep experts
and a token shard.  Per shard: route -> dispatch einsum [T,D]x[T,E,C] ->
[E,C,D] -> all_to_all -> expert FFN -> all_to_all back -> combine einsum
weighted by the router gate.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops._compat import shard_map


def init_moe_params(key, dim: int, hidden: int, n_experts: int,
                    dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
    """Router + per-expert FFN weights, experts stacked on axis 0 (the
    axis sharded over ``ep``)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / np.sqrt(dim)
    scale_out = 1.0 / np.sqrt(hidden)
    return {
        "router": (jax.random.normal(k1, (dim, n_experts)) *
                   scale_in).astype(dtype),
        "wi": (jax.random.normal(k2, (n_experts, dim, hidden)) *
               scale_in).astype(dtype),
        "wo": (jax.random.normal(k3, (n_experts, hidden, dim)) *
               scale_out).astype(dtype),
    }


def _route_topk(logits: jnp.ndarray, capacity: int, k: int = 1):
    """Top-k router, capacity-bounded (k=1: Switch; k=2: Mixtral/GShard).

    Returns the [T, E, C] dispatch tensor (0/1), the [T, E, C] COMBINE
    tensor (dispatch weighted by each choice's gate), and the
    load-balancing auxiliary loss (Switch eq. 4 generalized:
    E * sum_e f_e * P_e with f_e the raw pre-capacity fraction of
    routing assignments — 1.0 when balanced, up to E on collapse; the
    raw fraction is used because capacity-masking f_e would clamp the
    hot expert exactly when imbalance is worst).

    Gate convention follows the papers: k=1 uses the raw softmax prob
    (Switch); k>1 renormalizes the selected gates to sum to 1 per token
    (Mixtral).  Capacity slots are granted choice-major (every token's
    1st choice before any 2nd choice — GShard's priority order)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, k)  # [T, k]
    if k > 1:
        gates = top_vals / jnp.maximum(
            top_vals.sum(-1, keepdims=True), 1e-30)
    else:
        gates = top_vals

    # Slot bookkeeping runs in fp32 regardless of logits dtype: bf16
    # cumsum cannot represent integers above 256, so slot positions on
    # a hot expert would collide and sum multiple tokens into one
    # capacity slot.  Only disp/comb are cast back at the end.
    disp = jnp.zeros((T, E, capacity), jnp.float32)
    comb = jnp.zeros((T, E, capacity), jnp.float32)
    raw_total = jnp.zeros((E,), jnp.float32)
    slot_base = jnp.zeros((1, E), jnp.float32)
    gates32 = gates.astype(jnp.float32)
    for j in range(k):
        oh = jax.nn.one_hot(top_idx[:, j], E, dtype=jnp.float32)
        raw_total = raw_total + oh.sum(0)
        # 1-based slot per (token, expert), offset past prior choices'
        # claims so slots never collide across choice ranks.
        position = slot_base + jnp.cumsum(oh, axis=0) * oh
        within = jnp.logical_and(position >= 1, position <= capacity)
        ohk = oh * within
        disp_j = ohk[:, :, None] * jax.nn.one_hot(
            jnp.maximum(position - 1, 0).astype(jnp.int32), capacity,
            dtype=jnp.float32)
        disp = disp + disp_j
        comb = comb + disp_j * gates32[:, j][:, None, None]
        slot_base = slot_base + oh.sum(0, keepdims=True)
    aux = E * jnp.sum((raw_total / (T * k)) *
                      jnp.mean(probs.astype(jnp.float32), axis=0))
    return (disp.astype(logits.dtype), comb.astype(logits.dtype),
            aux.astype(logits.dtype))


def _expert_ffn(wi, wo, x):
    """Per-expert MLP batched over the local experts dim:
    x [El, S, D] -> [El, S, D]."""
    h = jax.nn.gelu(jnp.einsum("esd,edh->esh", x, wi))
    return jnp.einsum("esh,ehd->esd", h, wo)


def make_moe_fn(mesh: Mesh, n_experts: int,
                capacity_factor: float = 1.25,
                axis: str = "ep",
                experts_per_token: int = 1) -> Callable:
    """Build ``apply(params, x) -> (y, aux_loss)`` where ``x`` is
    [T, D] tokens (sharded over ``axis``) and ``params`` comes from
    :func:`init_moe_params` (experts sharded over ``axis``).

    ``experts_per_token``: 1 = Switch (raw-prob gate), 2 = Mixtral-style
    top-2 with renormalized gates.  Capacity scales with it:
    ``ceil(T * k * capacity_factor / E)`` slots per expert.

    Differentiable end-to-end; ``aux_loss`` is the Switch load-balancing
    term (mean over shards), to be added to the task loss scaled by the
    caller.
    """
    ep = mesh.shape[axis]
    if n_experts % ep:
        raise ValueError(f"n_experts={n_experts} not divisible by "
                         f"{axis}={ep}")
    e_local = n_experts // ep

    @partial(shard_map, mesh=mesh,
             in_specs=({"router": P(), "wi": P(axis), "wo": P(axis)},
                       P(axis)),
             out_specs=(P(axis), P()),
             check_vma=False)
    def _inner(params, x):
        T = x.shape[0]  # local token count
        capacity = int(np.ceil(T * experts_per_token * capacity_factor /
                               n_experts))
        logits = x @ params["router"]
        disp, comb, aux = _route_topk(logits, capacity,
                                      k=experts_per_token)

        # [T,D] x [T,E,C] -> [E,C,D]: tokens in their expert's slot.
        xd = jnp.einsum("td,tec->ecd", x, disp)
        # Ship slots to the owning chips: split E into [ep, e_local] and
        # trade the ep dim for the token-source dim.
        xd = xd.reshape(ep, e_local, capacity, xd.shape[-1])
        xd = lax.all_to_all(xd, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        # Now [ep(source), e_local, C, D]: merge source chips into the
        # expert's working set (transpose first — a bare reshape would
        # interleave experts across source chunks).
        d = xd.shape[-1]
        xw = xd.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)
        yw = _expert_ffn(params["wi"], params["wo"], xw)
        # Send results home (inverse all_to_all).
        yd = yw.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
        yd = lax.all_to_all(yd, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        yd = yd.reshape(n_experts, capacity, yd.shape[-1])
        # Combine back to token order, weighted per choice by the gate.
        y = jnp.einsum("ecd,tec->td", yd, comb)
        return y, lax.pmean(aux, axis)

    def apply(params, x):
        if x.shape[0] % ep:
            raise ValueError(
                f"token count {x.shape[0]} not divisible by {axis}={ep}")
        return _inner(params, x)

    return apply


def moe_shardings(mesh: Mesh, params: Any, axis: str = "ep"):
    """NamedShardings for init_moe_params output: experts over ``ep``,
    router replicated."""
    return {
        "router": NamedSharding(mesh, P()),
        "wi": NamedSharding(mesh, P(axis)),
        "wo": NamedSharding(mesh, P(axis)),
    }


def moe_dense_reference(params, x, n_experts: int, capacity: int,
                        experts_per_token: int = 1):
    """Single-device reference with IDENTICAL routing math (for tests):
    every token goes through its routed expert(s) unless over capacity."""
    logits = x @ params["router"]
    disp, comb, aux = _route_topk(logits, capacity, k=experts_per_token)
    y_all = jnp.einsum("td,edh->teh", x, params["wi"])
    y_all = jax.nn.gelu(y_all)
    y_all = jnp.einsum("teh,ehd->ted", y_all, params["wo"])
    sel = comb.sum(-1)  # [T, E] per-(token,expert) combine weight
    y = jnp.einsum("ted,te->td", y_all, sel)
    return y, aux


__all__ = ["make_moe_fn", "init_moe_params", "moe_shardings",
           "moe_dense_reference"]
