"""Expert parallelism: switch-style MoE dispatch over an ``ep`` mesh axis.

Beyond-reference capability (SURVEY §2.3: EP is "NO built-in; same
alltoall primitive" — the reference only offers ``hvd.alltoall`` for
users to build this themselves).  Here it is first-class: a capacity-
bounded top-1 (switch) router builds a static-shape dispatch tensor, and
TWO ``lax.all_to_all`` hops over the ``ep`` axis move tokens to their
expert's chip and back — the canonical TPU MoE data path (einsum-based
dispatch/combine keeps everything on the MXU; static capacity keeps
shapes compile-time constant).

Layout: with E experts over an ep-way axis, each chip owns E/ep experts
and a token shard.  Per shard: route -> dispatch einsum [T,D]x[T,E,C] ->
[E,C,D] -> all_to_all -> expert FFN -> all_to_all back -> combine einsum
weighted by the router gate.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops._compat import shard_map


def init_moe_params(key, dim: int, hidden: int, n_experts: int,
                    dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
    """Router + per-expert FFN weights, experts stacked on axis 0 (the
    axis sharded over ``ep``)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / np.sqrt(dim)
    scale_out = 1.0 / np.sqrt(hidden)
    return {
        "router": (jax.random.normal(k1, (dim, n_experts)) *
                   scale_in).astype(dtype),
        "wi": (jax.random.normal(k2, (n_experts, dim, hidden)) *
               scale_in).astype(dtype),
        "wo": (jax.random.normal(k3, (n_experts, hidden, dim)) *
               scale_out).astype(dtype),
    }


def _route_top1(logits: jnp.ndarray, capacity: int):
    """Switch router: per-token best expert, capacity-bounded.

    Returns the [T, E, C] dispatch tensor (0/1), the [T] combine gate
    (softmax prob, zeroed for dropped tokens), and the load-balancing
    auxiliary loss (Switch Transformer eq. 4: E * sum_e f_e * P_e with
    f_e the raw pre-capacity token fraction — 1.0 when balanced, up to E
    on collapse)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    raw_onehot = jax.nn.one_hot(expert, E, dtype=logits.dtype)
    # Aux from the RAW routing assignment (pre-capacity): Switch eq. 4,
    # alpha * E * sum_e f_e * P_e — equals 1 under perfect balance and
    # grows toward E as the router collapses.  Masking f_e by capacity
    # would clamp the hot expert's fraction exactly when imbalance is
    # worst, neutering the regularizer.
    aux = E * jnp.sum(jnp.mean(raw_onehot, axis=0) *
                      jnp.mean(probs, axis=0))
    position = jnp.cumsum(raw_onehot, axis=0) * raw_onehot  # 1-based
    within = position <= capacity
    onehot = raw_onehot * within
    disp = onehot[:, :, None] * jax.nn.one_hot(
        jnp.maximum(position - 1, 0).astype(jnp.int32), capacity,
        dtype=logits.dtype)
    gate = gate * onehot.sum(-1)  # dropped tokens contribute nothing
    return disp, gate, aux


def _expert_ffn(wi, wo, x):
    """Per-expert MLP batched over the local experts dim:
    x [El, S, D] -> [El, S, D]."""
    h = jax.nn.gelu(jnp.einsum("esd,edh->esh", x, wi))
    return jnp.einsum("esh,ehd->esd", h, wo)


def make_moe_fn(mesh: Mesh, n_experts: int,
                capacity_factor: float = 1.25,
                axis: str = "ep") -> Callable:
    """Build ``apply(params, x) -> (y, aux_loss)`` where ``x`` is
    [T, D] tokens (sharded over ``axis``) and ``params`` comes from
    :func:`init_moe_params` (experts sharded over ``axis``).

    Differentiable end-to-end; ``aux_loss`` is the Switch load-balancing
    term (mean over shards), to be added to the task loss scaled by the
    caller.
    """
    ep = mesh.shape[axis]
    if n_experts % ep:
        raise ValueError(f"n_experts={n_experts} not divisible by "
                         f"{axis}={ep}")
    e_local = n_experts // ep

    @partial(shard_map, mesh=mesh,
             in_specs=({"router": P(), "wi": P(axis), "wo": P(axis)},
                       P(axis)),
             out_specs=(P(axis), P()),
             check_vma=False)
    def _inner(params, x):
        T = x.shape[0]  # local token count
        capacity = int(np.ceil(T * capacity_factor / n_experts))
        logits = x @ params["router"]
        disp, gate, aux = _route_top1(logits, capacity)

        # [T,D] x [T,E,C] -> [E,C,D]: tokens in their expert's slot.
        xd = jnp.einsum("td,tec->ecd", x, disp)
        # Ship slots to the owning chips: split E into [ep, e_local] and
        # trade the ep dim for the token-source dim.
        xd = xd.reshape(ep, e_local, capacity, xd.shape[-1])
        xd = lax.all_to_all(xd, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        # Now [ep(source), e_local, C, D]: merge source chips into the
        # expert's working set (transpose first — a bare reshape would
        # interleave experts across source chunks).
        d = xd.shape[-1]
        xw = xd.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)
        yw = _expert_ffn(params["wi"], params["wo"], xw)
        # Send results home (inverse all_to_all).
        yd = yw.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
        yd = lax.all_to_all(yd, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        yd = yd.reshape(n_experts, capacity, yd.shape[-1])
        # Combine back to token order, weighted by the gate.
        y = jnp.einsum("ecd,tec->td", yd, disp) * gate[:, None]
        return y, lax.pmean(aux, axis)

    def apply(params, x):
        if x.shape[0] % ep:
            raise ValueError(
                f"token count {x.shape[0]} not divisible by {axis}={ep}")
        return _inner(params, x)

    return apply


def moe_shardings(mesh: Mesh, params: Any, axis: str = "ep"):
    """NamedShardings for init_moe_params output: experts over ``ep``,
    router replicated."""
    return {
        "router": NamedSharding(mesh, P()),
        "wi": NamedSharding(mesh, P(axis)),
        "wo": NamedSharding(mesh, P(axis)),
    }


def moe_dense_reference(params, x, n_experts: int, capacity: int):
    """Single-device reference with IDENTICAL routing math (for tests):
    every token goes through its routed expert unless over capacity."""
    logits = x @ params["router"]
    disp, gate, aux = _route_top1(logits, capacity)
    y_all = jnp.einsum("td,edh->teh", x, params["wi"])
    y_all = jax.nn.gelu(y_all)
    y_all = jnp.einsum("teh,ehd->ted", y_all, params["wo"])
    sel = disp.sum(-1)  # [T, E] 0/1 kept-assignment
    y = jnp.einsum("ted,te->td", y_all, sel) * gate[:, None]
    return y, aux


__all__ = ["make_moe_fn", "init_moe_params", "moe_shardings",
           "moe_dense_reference"]
