"""ZeRO-1 weight-update sharding for the data-parallel path.

The technique of "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv:2004.13336, retrieved in PAPERS.md): in
plain data parallelism every chip redundantly applies the SAME optimizer
update and holds the FULL optimizer state.  Sharding the update instead:

    grads --reduce_scatter-->  1/n per chip
    optimizer.update on the shard (state lives at 1/n)
    updates --all_gather-->    full update, applied to replicated params

communicates the same bytes as one allreduce (RS + AG == AR) while
cutting optimizer-state HBM by n and update FLOPs by n — the lever that
makes Adam-class optimizers affordable at scale.  This is the
data-parallel midpoint between :mod:`.data_parallel` (everything
replicated) and :mod:`.fsdp` (params sharded too / ZeRO-3).

Works with any optax transformation whose state is elementwise over the
parameters (sgd/momentum/adam/adamw/...): the whole pytree is flattened
to one fp32 vector, padded to a multiple of the axis size, and the shard
geometry is static — XLA sees fixed-shape RS/AG collectives riding ICI.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.reduce_op import ReduceOp, Average
from ..ops._compat import shard_map
from .hierarchical import resolve_axis


def _single_axis(axis_name, mesh: Mesh) -> str:
    axis = resolve_axis(axis_name, mesh)
    if isinstance(axis, tuple):
        if len(axis) != 1:
            raise ValueError(
                "zero-1 update sharding shards over ONE mesh axis; got "
                f"{axis} (flatten the mesh or pick a single axis)")
        axis = axis[0]
    return axis


def _flat_size(params: Any) -> int:
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(params))


def _flatten(tree: Any) -> jnp.ndarray:
    """One fp32 vector for the whole pytree (stock ravel; the fp32 cast
    first keeps the update math full-precision for bf16 params)."""
    from jax.flatten_util import ravel_pytree
    flat, _ = ravel_pytree(jax.tree_util.tree_map(
        lambda l: l.astype(jnp.float32), tree))
    return flat


def _unflatten_like(flat: jnp.ndarray, tree: Any) -> Any:
    """Inverse of :func:`_flatten` against ``tree``'s structure, casting
    each leaf back to ITS dtype (ravel_pytree's unravel wants the ravel
    dtype back, so the cast stays explicit here)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    off = 0
    for l in leaves:
        n = int(np.prod(l.shape))
        out.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def init_sharded_opt_state(optimizer: optax.GradientTransformation,
                           params: Any, mesh: Mesh,
                           axis_name="hvd") -> Any:
    """Optimizer state over the flat parameter shards: leaf layout is
    ``[n, padded/n, ...]`` with dim 0 sharded over the axis, so each chip
    materializes state for exactly 1/n of the parameters."""
    axis = _single_axis(axis_name, mesh)
    n = int(mesh.shape[axis])
    total = _flat_size(params)
    padded = -(-total // n) * n

    def init(params):
        flat = jnp.pad(_flatten(params), (0, padded - total))
        shards = flat.reshape(n, padded // n)
        return jax.vmap(optimizer.init)(shards)

    # out_shardings: each chip WRITES only its 1/n block — materializing
    # the full state replicated first would OOM exactly the large-model
    # regime this module exists for.
    shapes = jax.eval_shape(init, params)
    out_shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(axis)), shapes)
    return jax.jit(init, out_shardings=out_shardings)(params)


def make_zero1_train_step(loss_fn: Callable,
                          optimizer: optax.GradientTransformation,
                          mesh: Mesh,
                          axis_name="hvd",
                          op: ReduceOp = Average,
                          donate=None,
                          remat: bool = False) -> Callable:
    """Build ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` with the weight update sharded across ``axis_name``.

    ``opt_state`` comes from :func:`init_sharded_opt_state`; ``batch`` is
    sharded over the axis like :func:`..data_parallel.make_train_step`'s.
    Numerics match the replicated-update step exactly (same mean
    gradient, same elementwise update) — only WHERE the update runs
    changes.
    """
    if op != Average:
        raise ValueError("zero-1 update sharding reduces with Average "
                         "(gradient mean); prescale for other semantics")
    axis = _single_axis(axis_name, mesh)
    n = int(mesh.shape[axis])
    fn = jax.checkpoint(loss_fn) if remat else loss_fn
    from .data_parallel import _resolve_donate
    donate = _resolve_donate(donate)

    def body(params, opt_state, batch):
        loss, grads = jax.value_and_grad(fn)(params, batch)
        total = _flat_size(params)
        padded = -(-total // n) * n
        shard_len = padded // n
        gflat = jnp.pad(_flatten(grads), (0, padded - total))
        # sum-reduce + scatter my shard: [n, L/n] -> [1, L/n] per chip
        gshard = lax.psum_scatter(gflat.reshape(n, shard_len), axis,
                                  scatter_dimension=0, tiled=True)
        gshard = gshard.reshape(shard_len) / n
        # my slice of the flattened params (adamw's decoupled weight
        # decay needs them); params are replicated so this is a local
        # static-size slice
        pflat = jnp.pad(_flatten(params), (0, padded - total))
        pshard = lax.dynamic_slice_in_dim(
            pflat, lax.axis_index(axis) * shard_len, shard_len)
        # the local state block carries the [1, ...] sharded leading dim
        state_local = jax.tree_util.tree_map(lambda x: x[0], opt_state)
        updates, state_local = optimizer.update(gshard, state_local,
                                                pshard)
        opt_state = jax.tree_util.tree_map(lambda x: x[None], state_local)
        # rebuild the full update: [L/n] -> [L]
        ufull = lax.all_gather(updates, axis, axis=0, tiled=True)
        params = optax.apply_updates(
            params, _unflatten_like(ufull[:total], params))
        return params, opt_state, lax.pmean(loss, axis)

    def step(params, opt_state, batch):
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=(P(), P(axis), P()),
            check_vma=False)(params, opt_state, batch)

    # donate the old params/opt_state buffers so XLA updates in place
    # (the same knob-driven default as data_parallel.make_train_step)
    return jax.jit(step, donate_argnums=(0, 1) if donate else ())
