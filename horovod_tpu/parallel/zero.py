"""ZeRO-1 weight-update sharding for the data-parallel path.

The technique of "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv:2004.13336, retrieved in PAPERS.md): in
plain data parallelism every chip redundantly applies the SAME optimizer
update and holds the FULL optimizer state.  Sharding the update instead:

    grads --reduce_scatter-->  1/n per chip
    optimizer.update on the shard (state lives at 1/n)
    updates --all_gather-->    full update, applied to replicated params

communicates the same bytes as one allreduce (RS + AG == AR) while
cutting optimizer-state HBM by n and update FLOPs by n — the lever that
makes Adam-class optimizers affordable at scale.  This is the
data-parallel midpoint between :mod:`.data_parallel` (everything
replicated) and :mod:`.fsdp` (params sharded too / ZeRO-3).

Works with any optax transformation whose state is elementwise over the
parameters (sgd/momentum/adam/adamw/...): the whole pytree is flattened
to one fp32 vector, padded to a multiple of the axis size, and the shard
geometry is static — XLA sees fixed-shape RS/AG collectives riding ICI.

Two step shapes (``interleaved=`` on both the state init and the step
builder — state layouts differ, so the flag is kwarg-gated and must
match):

  * **monolithic** (default): one flat vector, one RS, one sharded
    update, one AG — the whole chain serialized on the critical path.
  * **bucket-interleaved** (the overlap plane, ops/overlap.py): the
    flat vector is split along the fusion-bucket plan (plan-cache keyed
    like the gradient sync), and the chain becomes a software pipeline —
    bucket *b*'s sharded optimizer update runs while bucket *b+1*'s
    reduce_scatter is in flight, in reverse-priority issue order
    (overlap.priority_order: last buckets first, so the next step's
    first-needed params finish their all_gather last and freshest).
    The paper behind this module (arXiv:2004.13336 §4) motivates exactly
    this software pipelining of the RS -> update -> AG chain.  Per
    element the same math runs in the same order across the axis, so
    results are bit-near the monolithic path (tests/test_overlap.py).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.reduce_op import ReduceOp, Average
from ..ops._compat import shard_map
from .hierarchical import resolve_axis


def _single_axis(axis_name, mesh: Mesh) -> str:
    axis = resolve_axis(axis_name, mesh)
    if isinstance(axis, tuple):
        if len(axis) != 1:
            raise ValueError(
                "zero-1 update sharding shards over ONE mesh axis; got "
                f"{axis} (flatten the mesh or pick a single axis)")
        axis = axis[0]
    return axis


def _flat_size(params: Any) -> int:
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(params))


def _flatten(tree: Any) -> jnp.ndarray:
    """One fp32 vector for the whole pytree (stock ravel; the fp32 cast
    first keeps the update math full-precision for bf16 params)."""
    from jax.flatten_util import ravel_pytree
    flat, _ = ravel_pytree(jax.tree_util.tree_map(
        lambda l: l.astype(jnp.float32), tree))
    return flat


def _unflatten_like(flat: jnp.ndarray, tree: Any) -> Any:
    """Inverse of :func:`_flatten` against ``tree``'s structure, casting
    each leaf back to ITS dtype (ravel_pytree's unravel wants the ravel
    dtype back, so the cast stays explicit here)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    off = 0
    for l in leaves:
        n = int(np.prod(l.shape))
        out.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _bucket_plan(params: Any, threshold_bytes: Any):
    """Fusion-bucket plan over the fp32-flattened parameter leaves,
    through the runtime's BucketPlanCache when initialized — the
    interleaved pipeline's bucket split and its (reversed) issue order
    are pure functions of this plan, so identical (shapes, threshold)
    signatures reuse both."""
    leaves = jax.tree_util.tree_leaves(params)
    shapes = [tuple(l.shape) for l in leaves]
    # update math is fp32 regardless of storage dtype (see _flatten)
    dtypes = [jnp.float32] * len(leaves)
    from .. import runtime as _rt
    if threshold_bytes is None:
        from ..optimizer import DEFAULT_FUSION_BYTES
        threshold_bytes = (_rt.get().fusion_threshold()
                           if _rt.is_initialized() else DEFAULT_FUSION_BYTES)
    if _rt.is_initialized():
        return _rt.get().plan_cache.get(shapes, dtypes, threshold_bytes)
    from ..ops.fusion import make_plan
    return make_plan(shapes, dtypes, threshold_bytes)


def _f32_leaves(tree: Any):
    return [l.astype(jnp.float32)
            for l in jax.tree_util.tree_leaves(tree)]


def _pack_padded(leaves, bucket, n: int) -> jnp.ndarray:
    """One bucket's leaves as a flat fp32 vector padded to a multiple of
    the axis size (static shapes; the pad is the per-bucket analog of the
    monolithic path's tail pad)."""
    from ..ops.fusion import pack_bucket
    flat = pack_bucket(leaves, bucket)
    total = flat.shape[0]
    padded = -(-total // n) * n
    return jnp.pad(flat, (0, padded - total))


def init_sharded_opt_state(optimizer: optax.GradientTransformation,
                           params: Any, mesh: Mesh,
                           axis_name="hvd",
                           interleaved: bool = False,
                           fusion_threshold_bytes: Any = None) -> Any:
    """Optimizer state over the flat parameter shards: leaf layout is
    ``[n, padded/n, ...]`` with dim 0 sharded over the axis, so each chip
    materializes state for exactly 1/n of the parameters.

    ``interleaved=True`` returns the bucket-interleaved layout instead —
    a tuple with one such sharded block PER FUSION BUCKET (plan order) —
    and must pair with ``make_zero1_train_step(..., interleaved=True)``:
    the layouts differ structurally, which is why the flag is a kwarg
    and never an env knob (state inited one way must not meet a step
    compiled the other way).  Per parameter the stored VALUES are
    identical in both layouts — only the element -> chip mapping moves.
    """
    axis = _single_axis(axis_name, mesh)
    n = int(mesh.shape[axis])

    if interleaved:
        plan = _bucket_plan(params, fusion_threshold_bytes)

        def init(params):
            leaves = _f32_leaves(params)
            out = []
            for b in plan.buckets:
                flat = _pack_padded(leaves, b, n)
                out.append(jax.vmap(optimizer.init)(
                    flat.reshape(n, flat.shape[0] // n)))
            return tuple(out)
    else:
        total = _flat_size(params)
        padded = -(-total // n) * n

        def init(params):
            flat = jnp.pad(_flatten(params), (0, padded - total))
            shards = flat.reshape(n, padded // n)
            return jax.vmap(optimizer.init)(shards)

    # out_shardings: each chip WRITES only its 1/n block — materializing
    # the full state replicated first would OOM exactly the large-model
    # regime this module exists for.
    shapes = jax.eval_shape(init, params)
    out_shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(axis)), shapes)
    return jax.jit(init, out_shardings=out_shardings)(params)


def make_zero1_train_step(loss_fn: Callable,
                          optimizer: optax.GradientTransformation,
                          mesh: Mesh,
                          axis_name="hvd",
                          op: ReduceOp = Average,
                          donate=None,
                          remat: bool = False,
                          interleaved: bool = False,
                          fusion_threshold_bytes: Any = None) -> Callable:
    """Build ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` with the weight update sharded across ``axis_name``.

    ``opt_state`` comes from :func:`init_sharded_opt_state` (same
    ``interleaved`` flag — the layouts must match); ``batch`` is
    sharded over the axis like :func:`..data_parallel.make_train_step`'s.
    Numerics match the replicated-update step exactly (same mean
    gradient, same elementwise update) — only WHERE the update runs
    changes.  ``interleaved=True`` runs the bucket-interleaved pipeline
    (module docstring): same per-element math, scheduled so bucket b's
    sharded update overlaps bucket b+1's in-flight reduce_scatter.
    """
    if op != Average:
        raise ValueError("zero-1 update sharding reduces with Average "
                         "(gradient mean); prescale for other semantics")
    axis = _single_axis(axis_name, mesh)
    n = int(mesh.shape[axis])
    fn = jax.checkpoint(loss_fn) if remat else loss_fn
    from .data_parallel import _resolve_donate
    donate = _resolve_donate(donate)

    if interleaved:
        return _make_interleaved_step(fn, optimizer, mesh, axis, n,
                                      donate, fusion_threshold_bytes)

    def body(params, opt_state, batch):
        loss, grads = jax.value_and_grad(fn)(params, batch)
        total = _flat_size(params)
        padded = -(-total // n) * n
        shard_len = padded // n
        gflat = jnp.pad(_flatten(grads), (0, padded - total))
        # sum-reduce + scatter my shard: [n, L/n] -> [1, L/n] per chip
        gshard = lax.psum_scatter(gflat.reshape(n, shard_len), axis,
                                  scatter_dimension=0, tiled=True)
        gshard = gshard.reshape(shard_len) / n
        # my slice of the flattened params (adamw's decoupled weight
        # decay needs them); params are replicated so this is a local
        # static-size slice
        pflat = jnp.pad(_flatten(params), (0, padded - total))
        pshard = lax.dynamic_slice_in_dim(
            pflat, lax.axis_index(axis) * shard_len, shard_len)
        # the local state block carries the [1, ...] sharded leading dim
        state_local = jax.tree_util.tree_map(lambda x: x[0], opt_state)
        updates, state_local = optimizer.update(gshard, state_local,
                                                pshard)
        opt_state = jax.tree_util.tree_map(lambda x: x[None], state_local)
        # rebuild the full update: [L/n] -> [L]
        ufull = lax.all_gather(updates, axis, axis=0, tiled=True)
        params = optax.apply_updates(
            params, _unflatten_like(ufull[:total], params))
        return params, opt_state, lax.pmean(loss, axis)

    def step(params, opt_state, batch):
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=(P(), P(axis), P()),
            check_vma=False)(params, opt_state, batch)

    # donate the old params/opt_state buffers so XLA updates in place
    # (the same knob-driven default as data_parallel.make_train_step)
    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def _make_interleaved_step(fn: Callable,
                           optimizer: optax.GradientTransformation,
                           mesh: Mesh, axis: str, n: int, donate: bool,
                           fusion_threshold_bytes: Any) -> Callable:
    """The bucket-interleaved ZeRO-1 pipeline (overlap plane).

    Per bucket the chain is exactly the monolithic path's —
    psum_scatter, /n, sharded elementwise update on the local state
    block, all_gather — but issued as a software pipeline over the
    fusion plan's buckets in reverse-priority order: the reduce_scatter
    of the NEXT bucket goes into the program before the current bucket's
    update + all_gather, giving a latency-hiding scheduler a sharded
    optimizer update to run under every in-flight RS.  The element ->
    chip mapping changes (per-bucket shard boundaries instead of one
    global split) but every element sees the same reduction over the
    same axis and the same elementwise update — bit-near the monolithic
    result by construction."""
    from ..ops.fusion import unpack_bucket
    from ..ops.overlap import priority_order, record_overlap
    from ..ops.wire import modeled_wire_bytes

    def body(params, opt_state, batch):
        plan = _bucket_plan(params, fusion_threshold_bytes)
        order = priority_order(plan)
        nb = plan.num_buckets
        loss, grads = jax.value_and_grad(fn)(params, batch)
        gleaves_raw, treedef = jax.tree_util.tree_flatten(grads)
        gleaves = [l.astype(jnp.float32) for l in gleaves_raw]
        pleaves = _f32_leaves(params)
        my = lax.axis_index(axis)

        # Analytical overlap split (trace time): every bucket moves
        # RS+AG == one ring allreduce of its elements; the pipeline
        # leaves the first-issued RS and the last-issued update+AG
        # exposed (half a bucket's traffic each), everything between
        # runs under an in-flight neighbor.
        per_bucket = [modeled_wire_bytes(sum(b.sizes), 4, "none",
                                         {"flat": n})["bottleneck"]
                      for b in plan.buckets]
        total_bytes = float(sum(per_bucket))
        exposed = (total_bytes if nb <= 1 else
                   0.5 * (per_bucket[order[0]] + per_bucket[order[-1]]))
        record_overlap(total_bytes, exposed, plane="zero1")
        # Tracing plane: the interleaved pipeline's issue order as trace-
        # time instants (once per compile), one per bucket — position j
        # issues bucket order[j]'s RS under bucket order[j-1]'s update+AG
        # (docs/timeline.md).
        from ..utils.timeline import trace_instant as _ti
        for j, bi in enumerate(order):
            _ti("zero1", "zero1.bucket.issue",
                args={"bucket": int(bi), "position": j,
                      "nbytes": int(sum(plan.buckets[bi].sizes)) * 4})

        def reduce_scatter(bi: int) -> jnp.ndarray:
            flat = _pack_padded(gleaves, plan.buckets[bi], n)
            shard_len = flat.shape[0] // n
            gshard = lax.psum_scatter(flat.reshape(n, shard_len), axis,
                                      scatter_dimension=0, tiled=True)
            return gshard.reshape(shard_len) / n

        def update_and_gather(bi: int, gshard: jnp.ndarray):
            shard_len = gshard.shape[0]
            pflat = _pack_padded(pleaves, plan.buckets[bi], n)
            pshard = lax.dynamic_slice_in_dim(pflat, my * shard_len,
                                              shard_len)
            state_local = jax.tree_util.tree_map(lambda x: x[0],
                                                 opt_state[bi])
            updates, state_local = optimizer.update(gshard, state_local,
                                                    pshard)
            new_state = jax.tree_util.tree_map(lambda x: x[None],
                                               state_local)
            return lax.all_gather(updates, axis, axis=0,
                                  tiled=True), new_state

        # One-slot software pipeline in reverse-priority issue order:
        # RS(order[j+1]) enters the program before update+AG(order[j]).
        new_states = [None] * nb
        ufulls = [None] * nb
        inflight = reduce_scatter(order[0])
        for j in range(nb):
            nxt = reduce_scatter(order[j + 1]) if j + 1 < nb else None
            ufull, st = update_and_gather(order[j], inflight)
            ufulls[order[j]], new_states[order[j]] = ufull, st
            inflight = nxt

        out = [None] * plan.num_leaves
        for bi, b in enumerate(plan.buckets):
            unpack_bucket(ufulls[bi][:sum(b.sizes)], b, out)
        updates_tree = jax.tree_util.tree_unflatten(
            treedef, [u.astype(l.dtype)
                      for u, l in zip(out, gleaves_raw)])
        params = optax.apply_updates(params, updates_tree)
        return params, tuple(new_states), lax.pmean(loss, axis)

    def step(params, opt_state, batch):
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=(P(), P(axis), P()),
            check_vma=False)(params, opt_state, batch)

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())
