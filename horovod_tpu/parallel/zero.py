"""ZeRO weight-update sharding for the data-parallel path, levels 1-3.

The technique of "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv:2004.13336, retrieved in PAPERS.md) plus
the ZeRO line of work: in plain data parallelism every chip redundantly
holds the FULL parameters, gradients and optimizer state and applies the
SAME update.  Sharding along the existing fusion-bucket plan removes the
redundancy one entity at a time (``zero_level``, docs/zero.md):

  level 1   optimizer state sharded 1/n: per bucket the chain is
            grads --reduce_scatter--> 1/n, sharded elementwise update,
            updates --all_gather--> applied to replicated params.
            RS + AG == one allreduce in wire bytes, state HBM / n.
  level 2   + gradient shards: each bucket's gradient shard stays
            resident after its reduce_scatter, and with
            ``backward_passes_per_step = k > 1`` accumulation happens ON
            the 1/n shard — no full gradient accumulator is ever
            materialized, and the per-microbatch grad all_gather that
            level 1 needs to keep its full accumulator disappears
            (strictly FEWER wire bytes than level 1 at k > 1).
  level 3   + parameter shards: params live between steps as per-bucket
            fp32 shards (1/n per chip, ``shard_zero3_params``) and the
            step all-gathers each bucket's params just-in-time at step
            start — plan order (first-needed buckets first), an
            ``ag_prefetch``-deep issue window (HOROVOD_ZERO_AG_PREFETCH;
            the overlap plane's latency-hiding discipline) — then frees
            the gathered full bucket after its leaves are consumed.  The
            update applies to the local shard; no update all_gather.

Wire-policy composition (ops/wire.py): the reduce_scatter leg carries
the per-bucket wire format under the ONE-SHOT codec model — each rank's
contribution is encoded once before the scatter (``wire.wire_roundtrip``)
so the EF-SGD residual ``x - C(x)`` is exactly compensable — with EF
residuals stored per bucket INSIDE the sharded state (rank-local rows of
a ``[n, bucket]`` array, so elastic resharding re-derives them with their
buckets).  The all-gather legs (updates at level <= 2, params at level 3)
stay exact: their payload is master state with no error-feedback channel,
and an exact AG is what makes the levels bit-near comparable.

Schedule contract (what the equivalence matrix proves,
tests/test_zero.py): the bucket-interleaved chain syncs EVERY microbatch
at every level — the uniform schedule under which levels 1/2/3 compute
identical per-element values for any wire format x EF x k, because
all_gather-then-slice is the identity.  The legacy monolithic level-1
chain (``interleaved=False``: one flat vector, accumulate-then-sync,
no wire formats) remains as the anchor the bucketed chain is proven
against.  Reverse-priority issue order for the gradient legs
(overlap.priority_order: backprop produces the tail buckets' gradients
first), plan order for the level-3 param gathers (the forward consumes
the head buckets first — last-needed buckets gathered last).

Relationship to :mod:`.fsdp` (ONE ZeRO-3 story, two schedulers): this
module is the EXPLICITLY-scheduled ZeRO-3 — shard_map collectives the
chain places itself, composing with wire formats, the overlap pipeline
and the per-bucket trace markers; ``fsdp.py`` is the COMPILER-scheduled
realization — sharding annotations from which GSPMD materializes the
same allgather-on-use / reduce-scatter-on-gradient pattern.  Same
memory math (``perf/costmodel.zero_memory_bytes`` prices both), pick by
control: explicit knobs here, compiler freedom there (docs/zero.md).

Cost-model closure (docs/profiling.md): the trace-time byte/memory
gauges this module sets (``hvd_zero_*``, ``hvd_overlap_*[plane=zeroN]``)
are computed FROM ``perf/costmodel.zero_comm_bytes`` — the same function
``hvd.perf_report()``'s per-level what-if table and the ledger's
predicted step use — so prediction and trace agree by construction and
the ledger measures their drift against the wall clock.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.reduce_op import ReduceOp, Average
from ..ops._compat import shard_map
from .hierarchical import resolve_axis

ZERO_LEVELS = (1, 2, 3)


class _ZeroEFBlock(NamedTuple):
    """One bucket's sharded state when error feedback is on: the vmapped
    inner optimizer state (``[n, bucket/n, ...]``, dim 0 over the axis)
    plus the EF residual as rank-local rows of a ``[n, bucket]`` array —
    each rank's row is ITS one-shot encode error for this bucket, riding
    the same sharded out_specs as the state so reshard/elastic handle it
    with the bucket."""
    inner: Any
    residual: jnp.ndarray


# ------------------------------------------------------------ knob surface
def validate_zero_knobs(knobs) -> None:
    """Fail loudly AT INIT on invalid ZeRO knob values (consumed by
    hvd.init, the overlap/wire validation pattern — docs/zero.md)."""
    from ..ops.overlap import MAX_OVERLAP_DEPTH
    lvl = int(knobs["HOROVOD_ZERO_LEVEL"])
    if lvl not in (0,) + ZERO_LEVELS:
        raise ValueError(
            f"HOROVOD_ZERO_LEVEL={lvl} invalid; the weight-update "
            "sharding level must be 0 (off), 1, 2 or 3 (docs/zero.md)")
    pre = int(knobs["HOROVOD_ZERO_AG_PREFETCH"])
    if not 1 <= pre <= MAX_OVERLAP_DEPTH:
        raise ValueError(
            f"HOROVOD_ZERO_AG_PREFETCH={pre} invalid; the ZeRO-3 param "
            f"all-gather prefetch depth must be in [1, "
            f"{MAX_OVERLAP_DEPTH}] (docs/zero.md)")


def resolve_zero_level(level: Optional[int] = None) -> int:
    """Live ZeRO level: kwarg > HOROVOD_ZERO_LEVEL knob (env-live via
    ``current``).  0 = off (plain data parallel)."""
    if level is None:
        from ..common.knobs import current
        level = int(current("HOROVOD_ZERO_LEVEL"))
    level = int(level)
    if level not in (0,) + ZERO_LEVELS:
        raise ValueError(
            f"zero level {level} invalid; must be 0, 1, 2 or 3 "
            "(HOROVOD_ZERO_LEVEL, docs/zero.md)")
    return level


def resolve_ag_prefetch(depth: Optional[int] = None) -> int:
    """Live ZeRO-3 param all-gather prefetch depth: kwarg > tuned bandit
    arm (Runtime.zero_ag_prefetch — the overlap-depth arm covers it) >
    HOROVOD_ZERO_AG_PREFETCH knob."""
    from ..ops.overlap import MAX_OVERLAP_DEPTH
    if depth is None:
        from .. import runtime as _rt
        if _rt.is_initialized():
            depth = _rt.get().zero_ag_prefetch()
        else:
            from ..common.knobs import current
            depth = int(current("HOROVOD_ZERO_AG_PREFETCH"))
    depth = int(depth)
    if not 1 <= depth <= MAX_OVERLAP_DEPTH:
        raise ValueError(
            f"zero AG prefetch depth {depth} out of range "
            f"[1, {MAX_OVERLAP_DEPTH}] (docs/zero.md)")
    return depth


def _resolve_wire_policy(wire_policy):
    """Kwarg > runtime's live policy (bandit-refined) > knob — the
    data_parallel resolution order, so the zero chain composes with the
    global wire plane without new knobs."""
    if wire_policy is not None:
        if callable(wire_policy):
            return wire_policy
        from ..ops.wire import validate_policy_name
        return validate_policy_name(wire_policy)
    from .. import runtime as _rt
    if _rt.is_initialized():
        return _rt.get().wire_policy()
    from ..common.knobs import current
    from ..ops.wire import validate_policy_name
    return validate_policy_name(current("HOROVOD_WIRE_POLICY"))


def _resolve_ef(error_feedback: Optional[bool]) -> bool:
    """EF request: kwarg > HOROVOD_WIRE_EF knob.  Env-default activation
    is safe HERE (unlike distributed_optimizer) because zero state always
    comes from this module's own init — init and step resolve the same
    way and the step validates the layout structurally regardless."""
    if error_feedback is not None:
        return bool(error_feedback)
    from ..common.knobs import current
    return bool(current("HOROVOD_WIRE_EF"))


# --------------------------------------------------------------- internals
def _single_axis(axis_name, mesh: Mesh) -> str:
    axis = resolve_axis(axis_name, mesh)
    if isinstance(axis, tuple):
        if len(axis) != 1:
            raise ValueError(
                "zero update sharding shards over ONE mesh axis; got "
                f"{axis} (flatten the mesh or pick a single axis)")
        axis = axis[0]
    return axis


def _flat_size(params: Any) -> int:
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(params))


def _flatten(tree: Any) -> jnp.ndarray:
    """One fp32 vector for the whole pytree (stock ravel; the fp32 cast
    first keeps the update math full-precision for bf16 params)."""
    flat, _ = ravel_pytree(jax.tree_util.tree_map(
        lambda l: l.astype(jnp.float32), tree))
    return flat


def _unflatten_like(flat: jnp.ndarray, tree: Any) -> Any:
    """Inverse of :func:`_flatten` against ``tree``'s structure, casting
    each leaf back to ITS dtype (ravel_pytree's unravel wants the ravel
    dtype back, so the cast stays explicit here)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    off = 0
    for l in leaves:
        n = int(np.prod(l.shape))
        out.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _bucket_plan(params: Any, threshold_bytes: Any):
    """Fusion-bucket plan over the fp32-flattened parameter leaves,
    through the runtime's BucketPlanCache when initialized — the
    interleaved chain's bucket split, its issue orders and the level-3
    shard geometry are pure functions of this plan, so identical
    (shapes, threshold) signatures reuse all of them, and an
    elastic/chaos reset re-derives the geometry for the new world size
    simply by rebuilding the step against the new mesh."""
    leaves = jax.tree_util.tree_leaves(params)
    shapes = [tuple(l.shape) for l in leaves]
    # update math is fp32 regardless of storage dtype (see _flatten)
    dtypes = [jnp.float32] * len(leaves)
    from .. import runtime as _rt
    if threshold_bytes is None:
        from ..optimizer import DEFAULT_FUSION_BYTES
        threshold_bytes = (_rt.get().fusion_threshold()
                           if _rt.is_initialized() else DEFAULT_FUSION_BYTES)
    if _rt.is_initialized():
        return _rt.get().plan_cache.get(shapes, dtypes, threshold_bytes)
    from ..ops.fusion import make_plan
    return make_plan(shapes, dtypes, threshold_bytes)


def _f32_leaves(tree: Any):
    return [l.astype(jnp.float32)
            for l in jax.tree_util.tree_leaves(tree)]


def _pack_padded(leaves, bucket, n: int) -> jnp.ndarray:
    """One bucket's leaves as a flat fp32 vector padded to a multiple of
    the axis size (static shapes; the pad is the per-bucket analog of the
    monolithic path's tail pad)."""
    from ..ops.fusion import pack_bucket_padded
    return pack_bucket_padded(leaves, bucket, n)


def _padded_len(nelems: int, n: int) -> int:
    return -(-nelems // n) * n


def _zero_formats(plan, policy, axis: str, n: int) -> List[str]:
    """Per-bucket RS-leg wire formats, via the wire plane's plan_formats
    with EXPLICIT axis sizes — so the state init (outside shard_map) and
    the traced step resolve identical formats and agree on the EF
    layout."""
    from ..ops import wire as _wire
    return _wire.plan_formats(plan, _wire.get_policy(policy), axis,
                              ReduceOp.AVERAGE, axis_sizes={"flat": n})


def _expected_state(optimizer, plan, n: int, ef: bool):
    """Abstract (shape/dtype) pytree of the bucket-interleaved state —
    what init produces and what the step validates against."""
    blocks = []
    for b in plan.buckets:
        L = _padded_len(sum(b.sizes), n)
        inner = jax.eval_shape(
            jax.vmap(optimizer.init),
            jax.ShapeDtypeStruct((n, L // n), jnp.float32))
        if ef:
            blocks.append(_ZeroEFBlock(
                inner=inner,
                residual=jax.ShapeDtypeStruct((n, L), jnp.float32)))
        else:
            blocks.append(inner)
    return tuple(blocks)


def _check_state_layout(opt_state, expected, what: str) -> None:
    """Structural validation of the passed opt_state against the layout
    this step builder compiles for — structure AND leaf shapes, so a
    state inited ``interleaved=True`` consumed by a monolithic step (or
    vice versa, or EF-on state meeting an EF-off step, or a stale world
    size after an elastic reset) raises here instead of mis-slicing."""
    exp_def = jax.tree_util.tree_structure(expected)
    got_def = jax.tree_util.tree_structure(opt_state)
    ok = exp_def == got_def
    if ok:
        for e, g in zip(jax.tree_util.tree_leaves(expected),
                        jax.tree_util.tree_leaves(opt_state)):
            if tuple(e.shape) != tuple(jnp.shape(g)):
                ok = False
                break
    if not ok:
        raise ValueError(
            f"zero opt_state layout mismatch for the {what} step: the "
            "`interleaved`, `zero_level`, wire/EF settings and world "
            "size of init_sharded_opt_state/init_zero_state and the "
            "step builder must match — e.g. state inited with "
            "interleaved=True must not be consumed by a monolithic "
            f"(interleaved=False) step builder (docs/zero.md).  "
            f"Expected {exp_def} with shapes "
            f"{[tuple(l.shape) for l in jax.tree_util.tree_leaves(expected)]}; "
            f"got {got_def} with shapes "
            f"{[tuple(jnp.shape(l)) for l in jax.tree_util.tree_leaves(opt_state)]}")


# ----------------------------------------------------- trace-time recording
def _record_zero_trace(plan, order, formats, level: int, n: int, k: int,
                       depth: int, ef: bool, opt_state,
                       param_bytes_full: int) -> None:
    """Trace-time observability for one compiled zero chain: the
    hvd_zero_* gauges (analytical per-rank residency), the
    hvd_overlap_*[plane=zeroN] exposed/overlapped byte split computed
    FROM perf/costmodel.zero_comm_bytes (prediction == trace model by
    construction), and the zero.bucket.{ag,rs,free} schedule markers in
    the merged timeline (docs/zero.md, docs/timeline.md)."""
    from ..ops.overlap import record_overlap
    from ..perf import costmodel as _cm
    from ..utils import metrics as M
    from ..utils.timeline import trace_instant

    padded = [_padded_len(sum(b.sizes), n) for b in plan.buckets]
    per_bucket = [
        _cm.zero_comm_bytes(L, n, level, k=k,
                            wire_format=formats[bi])["total_bytes"]
        for bi, L in enumerate(padded)]
    total = float(sum(per_bucket))
    # Pipeline split convention of the interleaved chain (the zero1 model
    # since PR 4): the first-issued and last-issued buckets' traffic
    # halves sit exposed at the pipeline ends; everything between runs
    # under an in-flight neighbor.
    exposed = (total if plan.num_buckets <= 1 else
               0.5 * (per_bucket[order[0]] + per_bucket[order[-1]]))
    record_overlap(total, exposed, plane=f"zero{level}")

    elems = sum(padded)
    state_bytes = sum(
        int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(opt_state))
    M.ZERO_LEVEL.set(level)
    M.ZERO_AG_PREFETCH.set(depth if level == 3 else 0)
    M.ZERO_SHARDED_BYTES.set(
        param_bytes_full // n if level == 3 else param_bytes_full,
        kind="params")
    M.ZERO_SHARDED_BYTES.set(
        elems * 4 // n if level >= 2 else elems * 4, kind="grads")
    # called from inside shard_map: the body's opt_state view is the
    # LOCAL [1, ...] block, so its bytes are already per-rank.
    M.ZERO_SHARDED_BYTES.set(state_bytes, kind="opt_state")
    M.ZERO_SHARDED_BYTES.set(elems * 4 if ef else 0, kind="ef_residual")

    if level == 3:
        for j, bi in enumerate(range(plan.num_buckets)):  # plan order
            trace_instant("zero", "zero.bucket.ag",
                          args={"bucket": int(bi), "position": j,
                                "level": level, "prefetch": depth,
                                "nbytes": int(padded[bi]) * 4})
            trace_instant("zero", "zero.bucket.free",
                          args={"bucket": int(bi), "level": level,
                                "nbytes": int(padded[bi]) * 4})
    for j, bi in enumerate(order):
        trace_instant("zero", "zero.bucket.rs",
                      args={"bucket": int(bi), "position": j,
                            "level": level, "format": formats[bi],
                            "k": k, "nbytes": int(padded[bi]) * 4})


# ----------------------------------------------------------------- init API
def init_sharded_opt_state(optimizer: optax.GradientTransformation,
                           params: Any, mesh: Mesh,
                           axis_name="hvd",
                           interleaved: bool = False,
                           fusion_threshold_bytes: Any = None,
                           zero_level: int = 1,
                           wire_policy=None,
                           error_feedback: Optional[bool] = None) -> Any:
    """Optimizer state over the flat parameter shards: leaf layout is
    ``[n, padded/n, ...]`` with dim 0 sharded over the axis, so each chip
    materializes state for exactly 1/n of the parameters.

    ``interleaved=True`` returns the bucket-interleaved layout instead —
    a tuple with one such sharded block PER FUSION BUCKET (plan order) —
    and must pair with a step built ``interleaved=True``: the layouts
    differ structurally, which is why the flag is a kwarg and never an
    env knob, and why the step builders validate the layout they are
    handed (a mismatch raises, never mis-slices).  Per parameter the
    stored VALUES are identical in both layouts — only the element ->
    chip mapping moves.  Levels 2 and 3 share level 1's state layout
    (the gradient shard is intra-step, the param shards live separately
    via :func:`shard_zero3_params`); when a lossy wire format is active
    with EF, each bucket's block gains its sharded residual
    (:class:`_ZeroEFBlock`).
    """
    level = resolve_zero_level(zero_level)
    if level == 0:
        raise ValueError(
            "zero_level=0 is plain data parallelism — init the inner "
            "optimizer directly (docs/zero.md)")
    if level >= 2 and not interleaved:
        raise ValueError(
            f"zero_level={level} is bucket-interleaved by construction; "
            "pass interleaved=True (docs/zero.md)")
    axis = _single_axis(axis_name, mesh)
    n = int(mesh.shape[axis])

    if interleaved:
        plan = _bucket_plan(params, fusion_threshold_bytes)
        formats = _zero_formats(plan, _resolve_wire_policy(wire_policy),
                                axis, n)
        from ..ops.wire import is_lossy
        ef = _resolve_ef(error_feedback) and any(
            is_lossy(f) for f in formats)

        def init(params):
            leaves = _f32_leaves(params)
            out = []
            for b in plan.buckets:
                flat = _pack_padded(leaves, b, n)
                inner = jax.vmap(optimizer.init)(
                    flat.reshape(n, flat.shape[0] // n))
                if ef:
                    out.append(_ZeroEFBlock(
                        inner=inner,
                        residual=jnp.zeros((n, flat.shape[0]),
                                           jnp.float32)))
                else:
                    out.append(inner)
            return tuple(out)
    else:
        if wire_policy is not None and wire_policy != "none":
            raise ValueError(
                "the monolithic zero chain carries no wire formats; use "
                "interleaved=True for per-bucket wire policies "
                "(docs/zero.md)")
        total = _flat_size(params)
        padded = -(-total // n) * n

        def init(params):
            flat = jnp.pad(_flatten(params), (0, padded - total))
            shards = flat.reshape(n, padded // n)
            return jax.vmap(optimizer.init)(shards)

    # out_shardings: each chip WRITES only its 1/n block — materializing
    # the full state replicated first would OOM exactly the large-model
    # regime this module exists for.
    shapes = jax.eval_shape(init, params)
    out_shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(axis)), shapes)
    return jax.jit(init, out_shardings=out_shardings)(params)


def init_zero_state(optimizer: optax.GradientTransformation,
                    params: Any, mesh: Mesh, axis_name="hvd",
                    zero_level: Optional[int] = None,
                    wire_policy=None,
                    error_feedback: Optional[bool] = None,
                    fusion_threshold_bytes: Any = None) -> Any:
    """The level-aware spelling of :func:`init_sharded_opt_state`:
    ``zero_level`` defaults to the HOROVOD_ZERO_LEVEL knob and the
    layout is bucket-interleaved (the chain's construction).  Level 3
    params are sharded separately via :func:`shard_zero3_params`."""
    return init_sharded_opt_state(
        optimizer, params, mesh, axis_name=axis_name, interleaved=True,
        fusion_threshold_bytes=fusion_threshold_bytes,
        zero_level=resolve_zero_level(zero_level),
        wire_policy=wire_policy, error_feedback=error_feedback)


# ------------------------------------------------------- level-3 param API
def shard_zero3_params(params: Any, mesh: Mesh, axis_name="hvd",
                       fusion_threshold_bytes: Any = None) -> Any:
    """Shard a replicated param tree into the level-3 resident layout:
    one ``[n, padded/n]`` fp32 array per fusion bucket, dim 0 over the
    axis — each chip keeps 1/n of every bucket (the update master copy;
    fp32 regardless of storage dtype, like the monolithic chain's update
    math).  Geometry is a pure function of (plan, n), so an elastic
    reset re-derives it for the new world size by re-running
    gather -> shard."""
    axis = _single_axis(axis_name, mesh)
    n = int(mesh.shape[axis])
    plan = _bucket_plan(params, fusion_threshold_bytes)

    def shard(params):
        leaves = _f32_leaves(params)
        return tuple(_pack_padded(leaves, b, n).reshape(n, -1)
                     for b in plan.buckets)

    shapes = jax.eval_shape(shard, params)
    out_shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(axis)), shapes)
    return jax.jit(shard, out_shardings=out_shardings)(params)


def gather_zero3_params(pshards: Any, params_template: Any, mesh: Mesh,
                        axis_name="hvd",
                        fusion_threshold_bytes: Any = None) -> Any:
    """Reassemble the full (replicated) param tree from the level-3
    bucket shards — for eval, checkpointing and elastic resharding
    (gather at the old world size, :func:`shard_zero3_params` at the
    new).  ``params_template`` supplies shapes/dtypes (arrays or
    ShapeDtypeStructs)."""
    from ..ops.fusion import unpack_bucket
    plan = _bucket_plan(params_template, fusion_threshold_bytes)
    tleaves, treedef = jax.tree_util.tree_flatten(params_template)

    def gather(pshards):
        out: List[Optional[jnp.ndarray]] = [None] * plan.num_leaves
        for bi, b in enumerate(plan.buckets):
            unpack_bucket(pshards[bi].reshape(-1)[:sum(b.sizes)], b, out)
        return jax.tree_util.tree_unflatten(
            treedef, [l.astype(t.dtype) for l, t in zip(out, tleaves)])

    repl = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P()),
        jax.eval_shape(gather, pshards))
    return jax.jit(gather, out_shardings=repl)(pshards)


# ------------------------------------------------------------- step builders
def make_zero_train_step(loss_fn: Callable,
                         optimizer: optax.GradientTransformation,
                         mesh: Mesh,
                         axis_name="hvd",
                         op: ReduceOp = Average,
                         donate=None,
                         remat: bool = False,
                         zero_level: Optional[int] = None,
                         interleaved: Optional[bool] = None,
                         wire_policy=None,
                         error_feedback: Optional[bool] = None,
                         backward_passes_per_step: int = 1,
                         ag_prefetch: Optional[int] = None,
                         fusion_threshold_bytes: Any = None,
                         params_template: Any = None) -> Callable:
    """Build the ZeRO train step for ``zero_level`` (module docstring).

    Levels 1/2: ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` with params replicated.  Level 3: ``step(param_shards,
    opt_state, batch) -> (param_shards, opt_state, loss)`` where
    ``param_shards`` comes from :func:`shard_zero3_params` and
    ``params_template`` (shapes/dtypes) is required to derive the bucket
    plan.  ``opt_state`` comes from :func:`init_zero_state` /
    :func:`init_sharded_opt_state` built under the SAME level/wire/EF
    settings — the step validates the layout structurally and raises on
    mismatch.  With ``backward_passes_per_step = k > 1`` the batch
    leaves carry a leading ``k`` axis and the chain syncs every
    microbatch (levels 2/3 accumulate on the 1/n shard).  Numerics are
    level-invariant: the equivalence matrix (tests/test_zero.py) pins
    params AND per-element optax state equal across level x wire format
    x EF x k.
    """
    level = resolve_zero_level(zero_level)
    if level == 0:
        raise ValueError(
            "zero_level=0 is plain data parallelism — use "
            "parallel.data_parallel.make_train_step (docs/zero.md)")
    if op != Average:
        raise ValueError("zero update sharding reduces with Average "
                         "(gradient mean); prescale for other semantics")
    if level >= 2 and interleaved is False:
        raise ValueError(
            f"zero_level={level} is bucket-interleaved by construction "
            "(the gradient/param shards ARE per-fusion-bucket slices); "
            "interleaved=False exists only for the legacy level-1 "
            "monolithic chain (docs/zero.md)")
    axis = _single_axis(axis_name, mesh)
    n = int(mesh.shape[axis])
    fn = jax.checkpoint(loss_fn) if remat else loss_fn
    from .data_parallel import _resolve_donate
    donate = _resolve_donate(donate)
    k = int(backward_passes_per_step)
    if k < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    if not (interleaved if interleaved is not None else True):
        return _make_monolithic_step(fn, optimizer, mesh, axis, n, donate,
                                     k, wire_policy, error_feedback)
    return _make_bucketed_step(fn, optimizer, mesh, axis, n, donate,
                               level, k, wire_policy, error_feedback,
                               ag_prefetch, fusion_threshold_bytes,
                               params_template)


def make_zero1_train_step(loss_fn: Callable,
                          optimizer: optax.GradientTransformation,
                          mesh: Mesh,
                          axis_name="hvd",
                          op: ReduceOp = Average,
                          donate=None,
                          remat: bool = False,
                          interleaved: bool = False,
                          fusion_threshold_bytes: Any = None) -> Callable:
    """Level-1 compat spelling (pre-level API): monolithic by default,
    bucket-interleaved with ``interleaved=True``.  New code uses
    :func:`make_zero_train_step`."""
    return make_zero_train_step(
        loss_fn, optimizer, mesh, axis_name=axis_name, op=op,
        donate=donate, remat=remat, zero_level=1,
        interleaved=bool(interleaved),
        fusion_threshold_bytes=fusion_threshold_bytes)


def _make_monolithic_step(fn: Callable,
                          optimizer: optax.GradientTransformation,
                          mesh: Mesh, axis: str, n: int, donate: bool,
                          k: int, wire_policy,
                          error_feedback: Optional[bool]) -> Callable:
    """The legacy level-1 chain: ONE flat fp32 vector, one RS, one
    sharded update, one AG — the anchor the bucketed chain's equivalence
    matrix is pinned against.  Carries no wire formats (nothing is
    bucketed to decide per) and takes one batch per step."""
    if k != 1:
        raise ValueError(
            "the monolithic zero chain takes one batch per step "
            "(backward_passes_per_step=1); microbatched steps ride the "
            "bucket-interleaved chain (interleaved=True, docs/zero.md)")
    if wire_policy is not None and wire_policy != "none":
        raise ValueError(
            "the monolithic zero chain carries no wire formats; use "
            "interleaved=True for per-bucket wire policies "
            "(docs/zero.md)")
    if error_feedback:
        raise ValueError(
            "error feedback needs a lossy wire format, which the "
            "monolithic zero chain does not carry (docs/zero.md)")

    def body(params, opt_state, batch):
        loss, grads = jax.value_and_grad(fn)(params, batch)
        total = _flat_size(params)
        padded = -(-total // n) * n
        shard_len = padded // n
        gflat = jnp.pad(_flatten(grads), (0, padded - total))
        # sum-reduce + scatter my shard: [n, L/n] -> [1, L/n] per chip
        gshard = lax.psum_scatter(gflat.reshape(n, shard_len), axis,
                                  scatter_dimension=0, tiled=True)
        gshard = gshard.reshape(shard_len) / n
        # my slice of the flattened params (adamw's decoupled weight
        # decay needs them); params are replicated so this is a local
        # static-size slice
        pflat = jnp.pad(_flatten(params), (0, padded - total))
        pshard = lax.dynamic_slice_in_dim(
            pflat, lax.axis_index(axis) * shard_len, shard_len)
        # the local state block carries the [1, ...] sharded leading dim
        state_local = jax.tree_util.tree_map(lambda x: x[0], opt_state)
        updates, state_local = optimizer.update(gshard, state_local,
                                                pshard)
        opt_state = jax.tree_util.tree_map(lambda x: x[None], state_local)
        # rebuild the full update: [L/n] -> [L]
        ufull = lax.all_gather(updates, axis, axis=0, tiled=True)
        params = optax.apply_updates(
            params, _unflatten_like(ufull[:total], params))
        return params, opt_state, lax.pmean(loss, axis)

    expected_cache: dict = {}

    def step(params, opt_state, batch):
        exp = expected_cache.get("state")
        if exp is None:
            padded = _padded_len(_flat_size(params), n)
            exp = expected_cache["state"] = jax.eval_shape(
                jax.vmap(optimizer.init),
                jax.ShapeDtypeStruct((n, padded // n), jnp.float32))
        _check_state_layout(opt_state, exp, "monolithic")
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=(P(), P(axis), P()),
            check_vma=False)(params, opt_state, batch)

    # donate the old params/opt_state buffers so XLA updates in place
    # (the same knob-driven default as data_parallel.make_train_step)
    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def _make_bucketed_step(fn: Callable,
                        optimizer: optax.GradientTransformation,
                        mesh: Mesh, axis: str, n: int, donate: bool,
                        level: int, k: int, wire_policy,
                        error_feedback: Optional[bool],
                        ag_prefetch: Optional[int],
                        fusion_threshold_bytes: Any,
                        params_template: Any) -> Callable:
    """The bucket-interleaved ZeRO chain, levels 1-3 (module docstring).

    Per fusion bucket and microbatch the gradient leg is: pack padded ->
    (+ EF residual) -> one-shot wire encode -> psum_scatter -> /n, in
    reverse-priority issue order.  Level 1 all-gathers each microbatch's
    shard back to keep the FULL synced-gradient accumulator resident
    (its defining redundancy — and exactly the wire bytes level 2
    deletes); levels 2/3 accumulate the 1/n shard.  The epilogue runs
    the sharded elementwise update per bucket and either all-gathers the
    updates onto replicated params (levels 1/2) or applies them to the
    resident param shard (level 3, whose step START gathered the full
    params bucket-by-bucket in plan order under the ag_prefetch
    window)."""
    from ..ops import wire as _wire
    from ..ops.fusion import unpack_bucket
    from ..ops.overlap import priority_order

    if level == 3 and params_template is None:
        raise ValueError(
            "zero_level=3 keeps params sharded between steps, so the "
            "step builder needs params_template (a pytree of arrays or "
            "ShapeDtypeStructs matching the model) to derive the bucket "
            "plan and leaf layout (docs/zero.md)")

    policy = _resolve_wire_policy(wire_policy)
    ef_requested = _resolve_ef(error_feedback)

    if level == 3:
        tleaves, treedef = jax.tree_util.tree_flatten(params_template)
        param_bytes_full = sum(
            int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
            for l in tleaves)

    def body(params_in, opt_state, batch):
        if level == 3:
            plan = _bucket_plan(params_template, fusion_threshold_bytes)
        else:
            plan = _bucket_plan(params_in, fusion_threshold_bytes)
        order = priority_order(plan)
        nb = plan.num_buckets
        formats = _zero_formats(plan, policy, axis, n)
        ef = ef_requested and any(_wire.is_lossy(f) for f in formats)
        depth = resolve_ag_prefetch(ag_prefetch) if level == 3 else 0
        pbytes = (param_bytes_full if level == 3 else sum(
            int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
            for l in jax.tree_util.tree_leaves(params_in)))
        _record_zero_trace(plan, order, formats, level, n, k, depth, ef,
                           opt_state, pbytes)
        my = lax.axis_index(axis)

        # ---- level 3: materialize full params from the resident bucket
        # shards, plan order (the forward consumes bucket 0's leaves
        # first), ag_prefetch-deep issue window: AG(bucket j+depth) is
        # issued before bucket j's unpack so a latency-hiding scheduler
        # overlays the gathers with the unpack/forward consumption; the
        # gathered flat bucket has no uses after its leaves unpack, so
        # XLA frees it behind the step (zero.bucket.free).
        if level == 3:
            def ag(bi):
                return lax.all_gather(params_in[bi][0], axis, axis=0,
                                      tiled=True)
            gathered = {j: ag(j) for j in range(min(depth, nb))}
            full: List[Optional[jnp.ndarray]] = [None] * plan.num_leaves
            for j in range(nb):
                if j + depth < nb:
                    gathered[j + depth] = ag(j + depth)
                b = plan.buckets[j]
                unpack_bucket(gathered.pop(j)[:sum(b.sizes)], b, full)
            params = jax.tree_util.tree_unflatten(
                treedef, [l.astype(t.dtype)
                          for l, t in zip(full, tleaves)])
            pleaves_raw = None
        else:
            params = params_in
            pleaves_raw, ptreedef = jax.tree_util.tree_flatten(params)
            pleaves_f32 = [l.astype(jnp.float32) for l in pleaves_raw]

        inner_states = [opt_state[bi].inner if ef else opt_state[bi]
                        for bi in range(nb)]
        res = ([opt_state[bi].residual[0] for bi in range(nb)]
               if ef else None)

        # ---- per-microbatch gradient legs (reverse-priority order:
        # backprop produces the tail buckets' gradients first)
        mbs = ([batch] if k == 1 else
               [jax.tree_util.tree_map(lambda x, _i=i: x[_i], batch)
                for i in range(k)])
        acc: List[Optional[jnp.ndarray]] = [None] * nb
        losses = []
        for mb in mbs:
            loss, grads = jax.value_and_grad(fn)(params, mb)
            losses.append(lax.pmean(loss, axis))
            gleaves = [l.astype(jnp.float32)
                       for l in jax.tree_util.tree_leaves(grads)]
            for bi in order:
                b = plan.buckets[bi]
                flat = _pack_padded(gleaves, b, n)
                if ef:
                    flat = flat + res[bi]
                enc = _wire.wire_roundtrip(flat, formats[bi])
                if ef and _wire.is_lossy(formats[bi]):
                    res[bi] = flat - enc
                shard_len = flat.shape[0] // n
                gshard = lax.psum_scatter(
                    enc.reshape(n, shard_len), axis,
                    scatter_dimension=0, tiled=True)
                gshard = gshard.reshape(shard_len) / n
                if level == 1 and k > 1:
                    # full synced-gradient accumulator (the level-1
                    # redundancy): gather the shard back every microbatch
                    contrib = lax.all_gather(gshard, axis, axis=0,
                                             tiled=True)
                else:
                    contrib = gshard
                acc[bi] = contrib if acc[bi] is None else acc[bi] + contrib

        # ---- epilogue: sharded update per bucket (priority order),
        # then AG(updates) onto replicated params (levels 1/2) or a
        # local shard apply (level 3).
        new_blocks: List[Any] = [None] * nb
        ufulls: List[Optional[jnp.ndarray]] = [None] * nb
        new_pshards: List[Optional[jnp.ndarray]] = [None] * nb
        for bi in order:
            b = plan.buckets[bi]
            if level == 1 and k > 1:
                shard_len = acc[bi].shape[0] // n
                gshard = lax.dynamic_slice_in_dim(
                    acc[bi], my * shard_len, shard_len) / k
            else:
                shard_len = acc[bi].shape[0]
                gshard = acc[bi] / k
            if level == 3:
                pshard = params_in[bi][0]
            else:
                pflat = _pack_padded(pleaves_f32, b, n)
                pshard = lax.dynamic_slice_in_dim(
                    pflat, my * shard_len, shard_len)
            state_local = jax.tree_util.tree_map(lambda x: x[0],
                                                 inner_states[bi])
            updates, state_local = optimizer.update(gshard, state_local,
                                                    pshard)
            inner_new = jax.tree_util.tree_map(lambda x: x[None],
                                               state_local)
            new_blocks[bi] = (_ZeroEFBlock(inner=inner_new,
                                           residual=res[bi][None])
                              if ef else inner_new)
            if level == 3:
                new_pshards[bi] = (pshard + updates)[None]
            else:
                ufulls[bi] = lax.all_gather(updates, axis, axis=0,
                                            tiled=True)

        loss = jnp.mean(jnp.stack(losses))
        if level == 3:
            return tuple(new_pshards), tuple(new_blocks), loss
        out: List[Optional[jnp.ndarray]] = [None] * plan.num_leaves
        for bi, b in enumerate(plan.buckets):
            unpack_bucket(ufulls[bi][:sum(b.sizes)], b, out)
        updates_tree = jax.tree_util.tree_unflatten(
            ptreedef, [u.astype(l.dtype)
                       for u, l in zip(out, pleaves_raw)])
        params = optax.apply_updates(params_in, updates_tree)
        return params, tuple(new_blocks), loss

    batch_spec = P(axis) if k == 1 else P(None, axis)
    param_spec = P(axis) if level == 3 else P()
    jitted = jax.jit(
        shard_map(body, mesh=mesh,
                  in_specs=(param_spec, P(axis), batch_spec),
                  out_specs=(param_spec, P(axis), P()),
                  check_vma=False),
        donate_argnums=(0, 1) if donate else ())

    expected_cache: dict = {}

    def step(params, opt_state, batch):
        exp = expected_cache.get("state")
        if exp is None:
            plan = _bucket_plan(params_template if level == 3 else params,
                                fusion_threshold_bytes)
            formats = _zero_formats(plan, policy, axis, n)
            ef = ef_requested and any(_wire.is_lossy(f) for f in formats)
            exp = expected_cache["state"] = _expected_state(
                optimizer, plan, n, ef)
        _check_state_layout(opt_state, exp,
                            f"bucket-interleaved level-{level}")
        return jitted(params, opt_state, batch)

    return step
