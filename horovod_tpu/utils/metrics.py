"""Metrics plane: Counter/Gauge/Histogram registry + Prometheus exposition.

The reference ships a timeline and a stall inspector but no *metrics*; a
production job needs latency distributions and fleet-wide counters (the
telemetry that adaptive systems like Adasum presuppose, arxiv 2006.02924).
This module is the process-global registry every layer records into:

  * native controller counters/histograms imported from the C++ core
    (``csrc/c_api.cc`` ``hvd_core_metrics``) via :func:`import_core_metrics`,
  * eager collectives + fusion planning (``ops/collectives.py``,
    ``ops/fusion.py``), the stall inspector and the torch negotiated path,
  * elastic driver/worker lifecycle events (``elastic/driver.py``,
    ``elastic/state.py``).

Exposition: each worker periodically PUTs a JSON :func:`MetricsRegistry.
snapshot` to the rendezvous KV (``MetricsPublisher``); the rendezvous HTTP
server's ``/metrics`` route renders the fleet-wide Prometheus text view
(``runner/http_server.py``), and the launcher prints a rank-0 end-of-run
straggler report (:func:`straggler_report`).

Deliberately stdlib-only with no package-relative imports at module level,
so the CI exposition linter (``scripts/check_metrics_format.py``) can load
this file standalone, the way ``bench.py`` loads ``utils/probe.py``.

Histogram buckets are power-of-2 microseconds (expressed in seconds),
matching the native core's fixed-bucket layout so native histograms import
loss-free (csrc/controller.h LatencyHistogram).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

SNAPSHOT_VERSION = 1

# Power-of-2 µs upper bounds in seconds: bucket b counts observations
# <= 2^b µs; the native core uses the identical layout (28 buckets,
# ~134 s ceiling) so its histograms map 1:1.
NATIVE_BUCKETS = 28
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    (1 << b) * 1e-6 for b in range(NATIVE_BUCKETS))


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def to_family(self) -> Dict[str, Any]:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic counter; ``set_total`` imports an externally-accumulated
    value (native core counters) instead of re-counting it."""

    kind = "counter"

    def __init__(self, name: str, help: str):
        super().__init__(name, help)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, value: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def set_total(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def to_family(self) -> Dict[str, Any]:
        with self._lock:
            samples = [{"labels": dict(k), "value": v}
                       for k, v in sorted(self._values.items())]
        if not samples:
            samples = [{"labels": {}, "value": 0.0}]
        return {"kind": self.kind, "help": self.help, "samples": samples}


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)


class Histogram(_Metric):
    """Fixed-bound histogram (power-of-2 µs by default)."""

    kind = "histogram"

    def __init__(self, name: str, help: str,
                 bounds: Tuple[float, ...] = BUCKET_BOUNDS):
        super().__init__(name, help)
        self.bounds = tuple(bounds)
        self._series: Dict[Tuple[Tuple[str, str], ...], Dict[str, Any]] = {}

    def _get(self, key):
        s = self._series.get(key)
        if s is None:
            s = {"counts": [0] * len(self.bounds), "sum": 0.0, "count": 0}
            self._series[key] = s
        return s

    def observe(self, value: float, **labels: str) -> None:
        with self._lock:
            s = self._get(_label_key(labels))
            b = 0
            while b < len(self.bounds) - 1 and value > self.bounds[b]:
                b += 1
            s["counts"][b] += 1
            s["sum"] += float(value)
            s["count"] += 1

    def set_native(self, counts: List[int], total_sum: float, count: int,
                   **labels: str) -> None:
        """Replace a series with an externally-accumulated (native core)
        histogram; counts are per-bucket, already in this bound layout."""
        with self._lock:
            s = self._get(_label_key(labels))
            padded = list(counts)[:len(self.bounds)]
            padded += [0] * (len(self.bounds) - len(padded))
            s["counts"] = [int(c) for c in padded]
            s["sum"] = float(total_sum)
            s["count"] = int(count)

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """Upper-bound estimate of the q-quantile from the buckets."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if not s or not s["count"]:
                return None
            target = q * s["count"]
            cum = 0
            for c, bound in zip(s["counts"], self.bounds):
                cum += c
                if cum >= target:
                    return bound
            return self.bounds[-1]

    def to_family(self) -> Dict[str, Any]:
        with self._lock:
            samples = [{"labels": dict(k), "counts": list(s["counts"]),
                        "sum": s["sum"], "count": s["count"]}
                       for k, s in sorted(self._series.items())]
        if not samples:
            samples = [{"labels": {}, "counts": [0] * len(self.bounds),
                        "sum": 0.0, "count": 0}]
        return {"kind": self.kind, "help": self.help,
                "bounds": list(self.bounds), "samples": samples}


class MetricsRegistry:
    """Named metric families, get-or-create, order-preserving."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, cls, name: str, help: str, **kw) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or type(m) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                return m
            m = cls(name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str) -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str) -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name: str, help: str,
                  bounds: Tuple[float, ...] = BUCKET_BOUNDS) -> Histogram:
        return self._register(Histogram, name, help, bounds=bounds)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able snapshot: the wire format workers PUT to the KV."""
        with self._lock:
            metrics = list(self._metrics.items())
        return {"version": SNAPSHOT_VERSION, "time": time.time(),
                "families": {name: m.to_family() for name, m in metrics}}


REGISTRY = MetricsRegistry()


# --------------------------------------------------------- standard families
# Declared centrally so every process (worker AND driver) exposes the same
# family set — a fleet /metrics view always spans all four layers even when
# a layer recorded nothing yet (zero-valued families, not absent ones).

# Layer 1: native controller (imported from csrc via hvd_core_metrics).
CONTROLLER_CYCLES = REGISTRY.counter(
    "hvd_controller_cycles_total", "Controller negotiation cycles run.")
CONTROLLER_CACHE_HITS = REGISTRY.counter(
    "hvd_controller_cache_hits_total",
    "Requests served via the response-cache bit-vector fast path.")
CONTROLLER_CACHE_MISSES = REGISTRY.counter(
    "hvd_controller_cache_misses_total",
    "Requests that took the full gather negotiation path.")
CONTROLLER_STALL_WARNINGS = REGISTRY.counter(
    "hvd_controller_stall_warnings_total",
    "Native stall-inspector warnings (ranks disagreeing about a tensor).")
CONTROLLER_RESPONSES = REGISTRY.counter(
    "hvd_controller_responses_total", "Negotiated responses emitted.")
CONTROLLER_CACHED_RESPONSES = REGISTRY.counter(
    "hvd_controller_cached_responses_total",
    "Responses reconstructed from the replicated cache.")
CONTROLLER_BYTES_GATHERED = REGISTRY.counter(
    "hvd_controller_bytes_gathered_total",
    "Outbound gather-frame coordination bytes.")
CONTROLLER_BYTES_BROADCAST = REGISTRY.counter(
    "hvd_controller_bytes_broadcast_total",
    "Broadcast-frame coordination bytes seen by this rank.")
CONTROLLER_BYTES_REDUCED = REGISTRY.counter(
    "hvd_controller_bytes_reduced_total",
    "Payload bytes of negotiated reduce-class collectives.")
CONTROLLER_TENSORS = REGISTRY.counter(
    "hvd_controller_tensors_negotiated_total",
    "Tensors carried by OK responses (tensors/cycle numerator).")
CONTROLLER_FUSED_BATCHES = REGISTRY.counter(
    "hvd_controller_fused_batches_total",
    "Fused response batches executed.")
CONTROLLER_FUSED_BYTES = REGISTRY.counter(
    "hvd_controller_fused_batch_bytes_total",
    "Total payload bytes across fused response batches.")
CONTROLLER_FILL_RATIO = REGISTRY.gauge(
    "hvd_controller_fusion_fill_ratio",
    "Mean fused-batch bytes / fusion threshold (fusion buffer fill).")
CONTROLLER_BYPASS_CYCLES = REGISTRY.counter(
    "hvd_controller_bypass_cycles_total",
    "Steady-state replay rounds served from the locked plan epoch with "
    "ZERO controller transport round trips (docs/tensor-fusion.md).")
CONTROLLER_EPOCH_LOCKS = REGISTRY.counter(
    "hvd_controller_epoch_locks_total",
    "Plan-epoch locks applied (rank 0 saw HOROVOD_BYPASS_STABLE_CYCLES "
    "identical negotiated steps and broadcast the lock).")
CONTROLLER_EPOCH_INVALIDATIONS = REGISTRY.counter(
    "hvd_controller_epoch_invalidations_total",
    "Plan-epoch breaks (new/missing tensor, JOIN, shutdown, remote "
    "break) — each falls back to full negotiation.")
TRANSPORT_RECONNECTS = REGISTRY.counter(
    "hvd_transport_reconnects_total",
    "Controller TCP reconnects that succeeded (resync handshake done).")
TRANSPORT_RECONNECT_FAILURES = REGISTRY.counter(
    "hvd_transport_reconnect_failures_total",
    "Controller TCP reconnect attempts that exhausted the retry budget.")
TRANSPORT_FRAMES_RESENT = REGISTRY.counter(
    "hvd_transport_frames_resent_total",
    "Coordination frames retransmitted after a connection break.")
TRANSPORT_FRAMES_DROPPED = REGISTRY.counter(
    "hvd_transport_frames_dropped_total",
    "Coordination frames dropped by chaos injection.")
TRANSPORT_FRAMES_COALESCED = REGISTRY.counter(
    "hvd_transport_frames_coalesced_total",
    "Coordination frames that shared one vectored write with a sibling "
    "(resync ack+replay batches — coalesced frame IO).")
TRANSPORT_COALESCED_BYTES = REGISTRY.counter(
    "hvd_transport_coalesced_bytes_total",
    "Bytes sent through the vectored (writev/sendmsg) frame path — one "
    "syscall per peer per cycle, no header/payload assembly copy.")
CHAOS_FAULTS_NATIVE = REGISTRY.counter(
    "hvd_chaos_faults_native_total",
    "Faults the native transport injector fired (csrc chaos plane).")
CHAOS_INJECTIONS = REGISTRY.counter(
    "hvd_chaos_injections_total",
    "Faults the Python chaos injector fired, by kind "
    "(kill/stall/kv_blackout/crash_commit).")
CONTROLLER_CYCLE_TIME = REGISTRY.histogram(
    "hvd_controller_cycle_time_seconds",
    "Controller RunCycle wall time (native power-of-2 µs buckets).")
CONTROLLER_NEGOTIATION_AGE = REGISTRY.histogram(
    "hvd_controller_negotiation_age_seconds",
    "Rank-0 per-tensor age from first submission to global readiness.")
# Watch plane, native leg (csrc/window.h; docs/watch.md): trailing-window
# rates differentiated inside the core against its epoch-stamped
# snapshot ring — no scraper clock in the math.  Imported from
# hvd_core_metrics_window by metrics_snapshot().
CONTROLLER_CYCLE_RATE = REGISTRY.gauge(
    "hvd_controller_cycle_rate",
    "Controller cycles per second over the trailing window, computed "
    "natively from the core's snapshot ring (hvd_core_metrics_window).")
CONTROLLER_BYTES_REDUCED_RATE = REGISTRY.gauge(
    "hvd_controller_bytes_reduced_rate",
    "Reduced payload bytes per second over the trailing window "
    "(native windowed rate, csrc/window.h).")
TRANSPORT_RECONNECTS_RATE = REGISTRY.gauge(
    "hvd_transport_reconnects_rate",
    "Controller TCP reconnects per MINUTE over the trailing window "
    "(native windowed rate — the flapping-transport detector's input).")
CONTROLLER_BYPASS_FRACTION = REGISTRY.gauge(
    "hvd_controller_bypass_fraction",
    "Fraction of the trailing window's negotiation rounds served from "
    "the locked plan epoch (bypass / (bypass + full cycles)) — the live "
    "steady-state health of the PR-9 fast path.")

# Layer 2: collectives + fusion planning (Python data-plane).
COLLECTIVE_OPS = REGISTRY.counter(
    "hvd_collective_ops_total", "Eager collective calls by op kind.")
COLLECTIVE_BYTES = REGISTRY.counter(
    "hvd_collective_bytes_total", "Eager collective payload bytes by op.")
COLLECTIVE_LATENCY = REGISTRY.histogram(
    "hvd_collective_latency_seconds",
    "Host-side latency of one eager collective call by op.")
FUSION_BUCKET_BYTES = REGISTRY.histogram(
    "hvd_fusion_bucket_bytes",
    "Planned fusion bucket sizes in bytes.",
    bounds=tuple(float(1 << b) for b in range(NATIVE_BUCKETS)))
FUSION_FLUSHES = REGISTRY.counter(
    "hvd_fusion_bucket_flush_total",
    "Fusion buckets closed, by reason (threshold/filled/tail).")
PLAN_CACHE_HITS = REGISTRY.counter(
    "hvd_fusion_plan_cache_hits_total", "Bucket-plan cache hits.")
PLAN_CACHE_MISSES = REGISTRY.counter(
    "hvd_fusion_plan_cache_misses_total", "Bucket-plan cache misses.")
# Wire-policy plane (ops/wire.py).  Decisions happen at TRACE time (one
# compiled program syncs the same buckets every step), so these count per
# trace, like the fusion-planning families above; multiply by steps for
# volume.  docs/tensor-fusion.md#wire-policies.
WIRE_BUCKETS = REGISTRY.counter(
    "hvd_wire_buckets_total",
    "Fusion buckets routed by the wire-policy plane, by chosen format.")
WIRE_BYTES_SAVED = REGISTRY.counter(
    "hvd_wire_bytes_saved_total",
    "Modeled wire bytes saved per compiled step vs the uncompressed "
    "format, by chosen format (bottleneck-fabric model, ops/wire.py).")
WIRE_RESIDUAL_NORM = REGISTRY.gauge(
    "hvd_wire_residual_norm",
    "L2 norm of the error-feedback residual, by bucket index (host-side "
    "report: optimizer.wire_residual_report).")
# Overlap plane (ops/overlap.py).  Set at TRACE time from the analytical
# byte model, like the wire families above: 'exposed' bytes are sync
# traffic issued with no concurrent compute to hide behind (the flush
# tail of the microbatch pipeline; the pipeline ends of the interleaved
# ZeRO chain), by plane (microbatch/zero1/zero2/zero3).
# docs/overlap.md, docs/zero.md.
OVERLAP_EXPOSED_BYTES = REGISTRY.gauge(
    "hvd_overlap_exposed_bytes",
    "Modeled sync bytes left on the critical path (not overlapped with "
    "compute) per compiled step, by plane (ops/overlap.py byte model).")
OVERLAP_FRACTION = REGISTRY.gauge(
    "hvd_overlap_overlapped_fraction",
    "Fraction of modeled sync bytes issued concurrently with compute "
    "per compiled step, by plane (1 - exposed/total; ops/overlap.py).")
# ZeRO weight-update sharding (parallel/zero.py; docs/zero.md).  Set at
# TRACE time like the overlap families: the level/prefetch of the last
# compiled zero chain and the ANALYTICAL per-rank residency of each
# state kind under it (the docs/zero.md memory model, priced by
# perf/costmodel.zero_memory_bytes).
ZERO_LEVEL = REGISTRY.gauge(
    "hvd_zero_level",
    "ZeRO weight-update sharding level of the last traced zero chain "
    "(1 = optimizer state sharded 1/n, 2 = + resident gradient shards, "
    "3 = + parameter shards; parallel/zero.py).")
ZERO_SHARDED_BYTES = REGISTRY.gauge(
    "hvd_zero_sharded_bytes",
    "Modeled per-rank resident bytes under the active ZeRO level, by "
    "kind (params/grads/opt_state/ef_residual) — the analytical memory "
    "model of docs/zero.md, set at trace time.")
ZERO_AG_PREFETCH = REGISTRY.gauge(
    "hvd_zero_ag_prefetch_depth",
    "ZeRO-3 parameter all-gather prefetch depth of the last traced "
    "zero chain (0 below level 3; HOROVOD_ZERO_AG_PREFETCH).")
# 3D layout solver (parallel/layout.py + perf/costmodel.solve_layout;
# docs/parallelism.md).  Set when a layout solve runs — at init under
# HOROVOD_LAYOUT=auto and on every perf_report() with a configured
# layout model — from the ANALYTICAL candidate table, like the ZeRO
# families above.
LAYOUT_CANDIDATES = REGISTRY.gauge(
    "hvd_layout_candidates",
    "Candidate (dp, tp, pp, zero_level, wire, overlap_depth) rows the "
    "layout solver enumerated for the topology in its last solve "
    "(perf/costmodel.solve_layout; docs/parallelism.md).")
LAYOUT_CHOSEN_RANK = REGISTRY.gauge(
    "hvd_layout_chosen_rank",
    "Rank (1 = fastest fitting candidate) of the layout the last solve "
    "selected — > 1 means HOROVOD_TP/HOROVOD_PP constraints or the "
    "memory cap displaced the unconstrained winner.")
LAYOUT_PREDICTED_STEP = REGISTRY.gauge(
    "hvd_layout_predicted_step_seconds",
    "Cost-model predicted step time of the chosen layout (roofline "
    "compute + TP/PP/ZeRO comm + pipeline bubble; the ledger bounds "
    "its drift against measured steps like the ZeRO table).")

# Serving plane (serve/engine.py; docs/serving.md).  SLO telemetry for
# the continuous-batching engine: latency distributions per REQUEST
# (ttft = submit->first token including queue wait; tpot = per-token
# decode latency after the first token) and per-tick utilization gauges.
# Rides the same publisher/exposition path as training, so /metrics and
# the straggler machinery answer serving questions for free.
SERVE_TTFT = REGISTRY.histogram(
    "hvd_serve_ttft_seconds",
    "Serving time-to-first-token per request: submit (queue entry) to "
    "the first generated token, including queue wait and prefill.")
SERVE_TPOT = REGISTRY.histogram(
    "hvd_serve_tpot_seconds",
    "Serving time-per-output-token per request: mean decode-step "
    "latency after the first token (requests with >= 2 tokens).")
SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "hvd_serve_queue_depth",
    "Requests waiting for a serving slot (admitted = out of the queue).")
SERVE_BATCH_FILL = REGISTRY.gauge(
    "hvd_serve_batch_fill",
    "Fraction of the max_batch_tokens admission budget the last engine "
    "tick actually processed (continuous-batching utilization).")
SERVE_REQUESTS = REGISTRY.counter(
    "hvd_serve_requests_total",
    "Serving requests by outcome (completed / eos / rejected).")
SERVE_TOKENS = REGISTRY.counter(
    "hvd_serve_tokens_total",
    "Tokens processed by the serving engine, by phase "
    "(prefill = prompt tokens cached, decode = tokens generated).")
# Fault-tolerant serving (serve/journal.py, docs/serving.md): journaled
# requests re-admitted after a fleet reset, watermark load sheds, and
# graceful drains — the robustness half of the serving SLO story.
SERVE_REDRIVES = REGISTRY.counter(
    "hvd_serve_redrives_total",
    "Journaled requests re-admitted and deterministically replayed "
    "past their emitted token prefix after a serving-fleet reset.")
SERVE_SHEDS = REGISTRY.counter(
    "hvd_serve_sheds_total",
    "Requests rejected by watermark load shedding (429 + Retry-After "
    "derived from measured TPOT x queue depth).")
SERVE_DRAINS = REGISTRY.counter(
    "hvd_serve_drains_total",
    "Graceful drains initiated via POST /admin/drain (admission stops, "
    "in-flight requests finish, the fleet exits 0).")
SERVE_JOURNAL_DEPTH = REGISTRY.gauge(
    "hvd_serve_journal_depth",
    "Accepted requests journaled for redrive and not yet finished "
    "(what a fleet reset would have to replay right now).")
# Serving raw speed (serve/engine.py; docs/serving.md#raw-speed): the
# prefix-cache / chunked-prefill / speculative-decoding telemetry —
# the rates behind 'is the fast path actually firing on this traffic'.
SERVE_PREFIX_HITS = REGISTRY.counter(
    "hvd_serve_prefix_hits_total",
    "Admissions whose prompt hit the radix prefix cache (>= 1 token "
    "served from already-resident KV blocks instead of recomputed).")
SERVE_PREFIX_BLOCKS_SHARED = REGISTRY.counter(
    "hvd_serve_prefix_blocks_shared_total",
    "Whole KV blocks mapped refcounted from the prefix cache at "
    "admission (prefill work avoided, block reservation shrunk).")
SERVE_PREFILL_CHUNKS = REGISTRY.counter(
    "hvd_serve_prefill_chunks_total",
    "Prefill chunks processed (prompts split across ticks at "
    "HOROVOD_SERVE_PREFILL_CHUNK inside the mixed-step token budget).")
SERVE_SPEC_DRAFTED = REGISTRY.counter(
    "hvd_serve_spec_drafted_tokens_total",
    "Tokens drafted by n-gram/prompt-lookup speculative decoding and "
    "submitted to the multi-token greedy verify step.")
SERVE_SPEC_ACCEPTED = REGISTRY.counter(
    "hvd_serve_spec_accepted_tokens_total",
    "Drafted tokens the greedy verify step accepted (emitted output "
    "stays bit-identical to plain greedy; the ratio to drafted is the "
    "accept rate).")
# Control-plane scale-out (runner/kvshard.py, serve/stream.py;
# docs/control-plane.md): per-shard rendezvous-KV traffic/outage
# accounting and the direct token stream that took the hottest serve
# path off KV polling.
KV_SHARD_REQUESTS = REGISTRY.counter(
    "hvd_kv_shard_requests_total",
    "Rendezvous-KV requests handled per shard server (labeled "
    "shard=index; counted by the driver's shard accept loops — only "
    "emitted when HOROVOD_KV_SHARDS > 1).")
KV_SHARD_UNAVAILABLE = REGISTRY.counter(
    "hvd_kv_shard_unavailable_total",
    "Transient KV-op failures against a shard (labeled shard=index; "
    "counted client-side per attempt, so a backoff riding a dark shard "
    "is visible while every other shard's traffic proceeds).")
SERVE_STREAM_DIRECT_TOKENS = REGISTRY.counter(
    "hvd_serve_stream_direct_tokens_total",
    "Tokens delivered over rank 0's persistent direct stream (POST "
    "/serve/stream) instead of serve_out KV PUTs + router polling; "
    "counted at the router's ingest, where client delivery is assured.")
# Replicated serving tier (serve/replica.py, serve/engine.py;
# docs/serving.md#replicated-tier): router-side placement accounting
# across replica fleets, the prefill->decode disaggregation handoff
# flow, and the host-RAM KV spill tier behind the device pool.
ROUTER_ROUTED = REGISTRY.counter(
    "hvd_router_routed_total",
    "Requests placed on a replica fleet by the front-door router "
    "(labeled replica=id) — affinity hits and least-loaded fallbacks "
    "both count; the per-replica split shows traffic balance.")
ROUTER_AFFINITY_HITS = REGISTRY.counter(
    "hvd_router_affinity_hits_total",
    "Requests routed to the replica advertising the longest cached "
    "prefix of their prompt (>= 1 full block matched the replica's "
    "published radix-tree fingerprints).")
ROUTER_AFFINITY_MISSES = REGISTRY.counter(
    "hvd_router_affinity_misses_total",
    "Requests placed least-loaded because no live replica advertised "
    "any prefix of their prompt (or affinity routing is off).")
ROUTER_REDISPATCHES = REGISTRY.counter(
    "hvd_router_redispatches_total",
    "Accepted streams re-dispatched to a surviving replica after their "
    "original fleet went dark mid-request (per-replica journal redrive "
    "driven router-side; emitted prefix suppressed, byte-identical).")
ROUTER_REPLICAS_UP = REGISTRY.gauge(
    "hvd_router_replicas_up",
    "Replica fleets currently live at the router (registered under the "
    "replicas KV scope with a fresh stats heartbeat; dark replicas — "
    "heartbeat older than HOROVOD_SERVE_REPLICA_DEAD_S — excluded).")
SERVE_HANDOFFS = REGISTRY.counter(
    "hvd_serve_handoffs_total",
    "Finished prefills exported by a prefill-role engine for a decode "
    "engine (prompt KV blocks + first sampled token; the request "
    "finishes with reason prefill_done on the prefill side).")
SERVE_IMPORTS = REGISTRY.counter(
    "hvd_serve_imports_total",
    "Prefill handoffs accepted by a decode-role engine (request "
    "installed directly in decode state with imported prompt KV).")
SERVE_SPILLS = REGISTRY.counter(
    "hvd_serve_spill_blocks_total",
    "Cold radix-cache KV blocks migrated from the device pool to the "
    "host-RAM spill tier at eviction instead of being dropped "
    "(HOROVOD_SERVE_SPILL_BLOCKS bounds the tier).")
SERVE_SPILL_RELOADS = REGISTRY.counter(
    "hvd_serve_spill_reload_blocks_total",
    "Spilled KV blocks reloaded into fresh device blocks on a prefix "
    "hit (the spill tier's payoff: a host copy instead of a prefill "
    "recompute).")
# Request-lifecycle tracing plane (serve/trace.py, serve/router.py;
# docs/serving.md#request-lifecycle): per-request SLO attribution —
# each completed request's measured wall time decomposed into
# queue/placement/prefill/handoff/decode/stream components that sum
# exactly to the measurement, plus the serve_trace record accounting.
SERVE_COMPONENT_SECONDS = REGISTRY.histogram(
    "hvd_serve_component_seconds",
    "Per-request lifecycle component durations (labeled component = "
    "queue / placement / prefill / handoff / decode / stream), observed "
    "at stream completion; per request the components sum exactly to "
    "the router-measured wall time (over-attribution rescaled).")
SERVE_TRACE_RECORDS = REGISTRY.counter(
    "hvd_serve_trace_records_total",
    "Per-request trace records written to the serve_trace KV scope "
    "(admission + completion + re-dispatch updates each count once).")
SERVE_TRACE_PRUNED = REGISTRY.counter(
    "hvd_serve_trace_pruned_total",
    "serve_trace records dropped by the bounded-retention prune "
    "(oldest-first once the scope exceeds the retention cap).")
SERVE_TRACE_OVERATTRIBUTION = REGISTRY.gauge(
    "hvd_serve_trace_overattribution_ratio",
    "Last completed request's modeled-components / measured-wall ratio "
    "before the ledger-style rescale (1.0 = the measured hop durations "
    "fit the wall exactly; > 1.0 = clock skew made them overshoot and "
    "they were rescaled to fit — the overshoot stays observable here).")

# Perf-attribution plane (horovod_tpu/perf/; docs/profiling.md).  The
# step-time decomposition ledger records here: measured step times, the
# per-component split (components sum exactly to the measured step), the
# roofline model's self-assessed drift, and the native controller's
# per-op-name aggregates imported from hvd_core_op_stats.
PERF_STEPS = REGISTRY.counter(
    "hvd_perf_steps_total",
    "Train steps recorded by the perf-attribution ledger "
    "(hvd.perf.record_step / timed_step).")
PERF_STEP_TIME = REGISTRY.histogram(
    "hvd_perf_step_time_seconds",
    "Measured wall time of recorded train steps (the quantity the "
    "decomposition components sum to).")
PERF_COMPONENT = REGISTRY.gauge(
    "hvd_perf_component_seconds",
    "Last recorded step's decomposition by component "
    "(compute / exposed_comm / host_input / stall — docs/profiling.md; "
    "the four sum exactly to the measured step time).")
PERF_MODEL_DRIFT = REGISTRY.gauge(
    "hvd_perf_model_drift_ratio",
    "Mean (modeled + measured-input) / measured step-time ratio over "
    "recorded steps: 1.0 = the roofline cost model prices exactly what "
    "the wall clock measures; drift is itself observable.")
PERF_NATIVE_OP_US = REGISTRY.counter(
    "hvd_perf_native_op_us_total",
    "Cumulative enqueue->done latency (µs) of negotiated collectives by "
    "collapsed op name (csrc hvd_core_op_stats — the native leg of the "
    "attribution plane).")
PERF_NATIVE_OP_BYTES = REGISTRY.counter(
    "hvd_perf_native_op_bytes_total",
    "Cumulative payload bytes of negotiated collectives by collapsed "
    "op name (csrc hvd_core_op_stats).")

# Memory plane (horovod_tpu/perf/memstats.py; docs/memory.md): the
# measured fleet memory ledger — device/host residency sampled per rank,
# attributed to planes from known geometry, reconciled against the
# zero_memory_bytes prediction, and watched by the committed mem-* alert
# rules plus the OOM-proximity sentinel.
MEM_BYTES_IN_USE = REGISTRY.gauge(
    "hvd_mem_bytes_in_use",
    "Measured device bytes in use on this rank: device.memory_stats() "
    "bytes_in_use where the backend provides it, else the aggregate "
    "jax.live_arrays() size (CPU-virtual fallback; the sample's "
    "'source' field says which — docs/memory.md#sources).")
MEM_PEAK_BYTES = REGISTRY.gauge(
    "hvd_mem_peak_bytes",
    "Measured peak device bytes (memory_stats peak_bytes_in_use; under "
    "the CPU fallback the running max of sampled bytes_in_use).")
MEM_CAP_BYTES = REGISTRY.gauge(
    "hvd_mem_cap_bytes",
    "Device memory capacity in bytes (memory_stats bytes_limit); 0 when "
    "the backend reports no cap (CPU fallback) — the watermark and "
    "headroom need a nonzero cap.")
MEM_HOST_RSS = REGISTRY.gauge(
    "hvd_mem_host_rss_bytes",
    "Host resident set of this rank's process (/proc/self/status VmRSS) "
    "— the host leg of the ledger, reported beside (never inside) the "
    "device drift ratio.")
MEM_WATERMARK = REGISTRY.gauge(
    "hvd_mem_watermark",
    "bytes_in_use / cap as a fraction (0 when no cap is known); the "
    "committed mem-pressure-high rule and the OOM-proximity sentinel "
    "threshold this against HOROVOD_MEM_HIGH_WATERMARK.")
MEM_PLANE_BYTES = REGISTRY.gauge(
    "hvd_mem_plane_bytes",
    "Geometry-attributed residency by plane (params / grads / opt_state "
    "/ ef_residual from the ZeRO level + bucket plan, kv_pool from the "
    "BlockAllocator, fusion_overlap from threshold x depth, native_core "
    "from hvd_core_mem) — the per-plane side of the measured-vs-"
    "predicted table (docs/memory.md#attribution).")
MEM_MODEL_DRIFT = REGISTRY.gauge(
    "hvd_mem_model_drift_ratio",
    "Measured bytes_in_use over the zero_memory_bytes predicted total "
    "(1.0 = the memory model prices exactly what the device reports; "
    "the PR-14 drift discipline, for bytes-resident instead of "
    "bytes-moved).  The committed mem-model-drift rule watches it.")
MEM_PRESSURE_EVENTS = REGISTRY.counter(
    "hvd_mem_pressure_events_total",
    "OOM-proximity sentinel firings: watermark transitions above "
    "HOROVOD_MEM_HIGH_WATERMARK, each firing once — alert + timeline "
    "instant + flight dump reason 'mem' (docs/memory.md#oom).")
MEM_KV_BLOCKS_USED = REGISTRY.gauge(
    "hvd_mem_kv_blocks_used",
    "Serve KV-cache pool blocks currently allocated (BlockAllocator "
    "occupancy; docs/serving.md) — the observability prerequisite for "
    "host spill.")
MEM_KV_BLOCKS_FREE = REGISTRY.gauge(
    "hvd_mem_kv_blocks_free",
    "Serve KV-cache pool blocks on the free list (the kv-pool-dry "
    "rule's signal rides hvd_mem_kv_util, derived from this).")
MEM_KV_BLOCKS_SHARED = REGISTRY.gauge(
    "hvd_mem_kv_blocks_shared",
    "Serve KV-cache pool blocks with refcount > 1 (prefix-cache / "
    "beam sharing): bytes the used count double-books across "
    "sequences.")
MEM_KV_UTIL = REGISTRY.gauge(
    "hvd_mem_kv_util",
    "Serve KV-cache pool utilization: used / (used + free), in [0, 1]. "
    "Exactly 1.0 only when an ACTIVE pool has no free blocks — the "
    "committed kv-pool-dry rule watches this rather than the free count "
    "because an unset gauge snapshots as 0, which would read as 'dry' "
    "on every non-serving rank.")
MEM_NATIVE_BYTES = REGISTRY.gauge(
    "hvd_mem_native_bytes",
    "Native core footprint by kind (hvd_core_mem, stamped by the cycle "
    "loop: rss / peak_rss / trace_ring / window_ring / response_cache "
    "— csrc's own memory beside the device planes).")

# Watch plane, detection leg (horovod_tpu/watch/; docs/watch.md): the
# declarative rules engine's firing accounting.  Maintained by the
# DRIVER's AlertEngine (the rendezvous server evaluates rules against
# the fleet series store), so these families carry data on the /metrics
# driver row, not on workers.
ALERTS_TOTAL = REGISTRY.counter(
    "hvd_alerts_total",
    "Alert firing transitions by rule and severity (the rules engine's "
    "lifetime incident count; docs/watch.md#rules).")
ALERTS_FIRING = REGISTRY.gauge(
    "hvd_alerts_firing",
    "Currently-firing alert instances by rule (0 = quiet) — the live "
    "pager view of GET /alerts.")
# Watch plane, sentinel leg (watch/sentinel.py; docs/watch.md#sentinels):
# training-quality scalars computed at trace time inside the step
# (grad-norm / nonfinite via psum, SPMD-identical on all ranks) and
# recorded host-side — the model-health families the committed
# sentinel-* default rules watch.
SENTINEL_STEPS = REGISTRY.counter(
    "hvd_sentinel_steps_total",
    "Train steps the sentinel recorded (hvd.sentinel.wrap / record).")
SENTINEL_LOSS = REGISTRY.gauge(
    "hvd_sentinel_loss", "Last recorded training loss (pmean across "
    "ranks when the step passed an axis_name).")
SENTINEL_LOSS_EMA = REGISTRY.gauge(
    "hvd_sentinel_loss_ema",
    "Exponential moving average of the recorded loss (~50-step "
    "horizon) — the divergence baseline.")
SENTINEL_LOSS_DIVERGENCE = REGISTRY.gauge(
    "hvd_sentinel_loss_divergence",
    "Last loss over its EMA (1.0 = on trend); the committed "
    "sentinel-loss-divergence rule thresholds this.")
SENTINEL_GRAD_NORM = REGISTRY.gauge(
    "hvd_sentinel_grad_norm",
    "Global gradient L2 norm of the last recorded step (psum'd square "
    "sums over the finite gradient mass, trace-time).")
SENTINEL_NONFINITE = REGISTRY.counter(
    "hvd_sentinel_nonfinite_total",
    "Training steps with any nonfinite gradient element or loss (each "
    "also triggers an explicit flight dump, reason 'nan' — "
    "docs/watch.md#sentinels).")
SENTINEL_LAST_NONFINITE_STEP = REGISTRY.gauge(
    "hvd_sentinel_last_nonfinite_step",
    "Step number of the most recent nonfinite verdict (-1 = none); the "
    "sentinel-nonfinite alert carries it as context.")

# Layer 3: runtime (stall inspector + topology).
STRAGGLER_SUSPECT = REGISTRY.gauge(
    "hvd_straggler_suspect",
    "Rank the driver's live straggler check currently suspects (-1 = "
    "none): per-rank negotiation-age p99 skew beyond the ratio threshold "
    "every HOROVOD_STRAGGLER_CHECK_SECS (docs/metrics.md).")
RUNTIME_SIZE = REGISTRY.gauge(
    "hvd_runtime_size", "Worker chips in the mesh.")
RUNTIME_LOCAL_SIZE = REGISTRY.gauge(
    "hvd_runtime_local_size", "Chips driven by this process.")
NATIVE_SANITIZER_BUILD = REGISTRY.gauge(
    "hvd_native_sanitizer_build",
    "1 for the sanitizer tag of the loaded native core library "
    "(sanitizer=none|tsan|asan|ubsan, csrc/Makefile SAN modes): the "
    "build-info surface that keeps a 5-20x-slower sanitized library "
    "from silently leaking into a benchmark or production fleet "
    "(docs/static-analysis.md).")
STALL_WARNINGS = REGISTRY.counter(
    "hvd_stall_warnings_total",
    "Python stall-inspector warnings (submitted but not completed).")
STALL_PENDING = REGISTRY.gauge(
    "hvd_stall_pending_tensors",
    "Collectives currently submitted but not completed.")
NEGOTIATION_AGE = REGISTRY.histogram(
    "hvd_negotiation_age_seconds",
    "Per-rank submit-to-completion age of named collectives (the "
    "straggler report's source: a slow rank drags every peer's ages up).")

# Layer 4: elastic lifecycle.
WORKER_EXITS = REGISTRY.counter(
    "hvd_worker_exits_total",
    "Worker process exits observed by the launcher/elastic driver, by "
    "cause (clean / error:N / signal:NAME / stall / heartbeat-lost / "
    "terminated — the postmortem plane's exit taxonomy, "
    "docs/postmortem.md).")
ELASTIC_RESETS = REGISTRY.counter(
    "hvd_elastic_reset_rounds_total", "Elastic reset rounds started.")
ELASTIC_FAILURES = REGISTRY.counter(
    "hvd_elastic_worker_failures_total", "Worker processes that failed.")
ELASTIC_HOSTS_ADDED = REGISTRY.counter(
    "hvd_elastic_hosts_added_total", "Hosts added by discovery.")
ELASTIC_HOSTS_REMOVED = REGISTRY.counter(
    "hvd_elastic_hosts_removed_total",
    "Hosts removed by discovery or blacklisting.")
ELASTIC_ROUND_DURATION = REGISTRY.histogram(
    "hvd_elastic_round_duration_seconds",
    "Wall time of one elastic round (spawn to reset/finish).")
ELASTIC_COMMITS = REGISTRY.counter(
    "hvd_elastic_commits_total", "Elastic state commits.")
ELASTIC_COMMIT_DURATION = REGISTRY.histogram(
    "hvd_elastic_commit_duration_seconds",
    "Wall time of one elastic state commit.")
ELASTIC_RESTORES = REGISTRY.counter(
    "hvd_elastic_restores_total", "Elastic state restores after reset.")


def import_core_metrics(native: Dict[str, Any]) -> None:
    """Map one native-core metrics dict (CoordinationCore.metrics()) onto
    the controller families.  Native values are cumulative, so they are
    imported with set_total/set_native rather than re-counted."""
    c = native.get("counters", {})
    CONTROLLER_CYCLES.set_total(c.get("cycles", 0))
    CONTROLLER_CACHE_HITS.set_total(c.get("cache_hits", 0))
    CONTROLLER_CACHE_MISSES.set_total(c.get("cache_misses", 0))
    CONTROLLER_STALL_WARNINGS.set_total(c.get("stall_warnings", 0))
    CONTROLLER_RESPONSES.set_total(c.get("responses", 0))
    CONTROLLER_CACHED_RESPONSES.set_total(c.get("cached_responses", 0))
    CONTROLLER_BYTES_GATHERED.set_total(c.get("bytes_gathered", 0))
    CONTROLLER_BYTES_BROADCAST.set_total(c.get("bytes_broadcast", 0))
    CONTROLLER_BYTES_REDUCED.set_total(c.get("bytes_reduced", 0))
    CONTROLLER_TENSORS.set_total(c.get("tensors_negotiated", 0))
    CONTROLLER_FUSED_BATCHES.set_total(c.get("fused_batches", 0))
    CONTROLLER_FUSED_BYTES.set_total(c.get("fused_batch_bytes", 0))
    CONTROLLER_BYPASS_CYCLES.set_total(c.get("bypass_cycles", 0))
    CONTROLLER_EPOCH_LOCKS.set_total(c.get("epoch_locks", 0))
    CONTROLLER_EPOCH_INVALIDATIONS.set_total(
        c.get("epoch_invalidations", 0))
    TRANSPORT_RECONNECTS.set_total(c.get("transport_reconnects", 0))
    TRANSPORT_RECONNECT_FAILURES.set_total(
        c.get("transport_reconnect_failures", 0))
    TRANSPORT_FRAMES_RESENT.set_total(c.get("transport_frames_resent", 0))
    TRANSPORT_FRAMES_DROPPED.set_total(c.get("transport_frames_dropped", 0))
    TRANSPORT_FRAMES_COALESCED.set_total(
        c.get("transport_frames_coalesced", 0))
    TRANSPORT_COALESCED_BYTES.set_total(
        c.get("transport_coalesced_bytes", 0))
    CHAOS_FAULTS_NATIVE.set_total(c.get("chaos_faults_injected", 0))
    batches = c.get("fused_batches", 0)
    threshold = c.get("fusion_threshold_bytes", 0)
    if batches and threshold:
        CONTROLLER_FILL_RATIO.set(
            c.get("fused_batch_bytes", 0) / (batches * threshold))
    for hname, metric in (("cycle_time_us", CONTROLLER_CYCLE_TIME),
                          ("negotiation_age_us", CONTROLLER_NEGOTIATION_AGE)):
        h = native.get("histograms", {}).get(hname)
        if h:
            metric.set_native(h["buckets"], h["sum"] * 1e-6, h["count"])


def import_window_rates(window: Dict[str, Any]) -> None:
    """Map one native windowed-rates dict (CoordinationCore.
    metrics_window()) onto the hvd_*_rate gauges.  The rates were
    differentiated inside the core against its own steady clock
    (csrc/window.h), so this is a straight copy."""
    CONTROLLER_CYCLE_RATE.set(window.get("cycle_rate", 0.0))
    CONTROLLER_BYTES_REDUCED_RATE.set(
        window.get("bytes_reduced_rate", 0.0))
    TRANSPORT_RECONNECTS_RATE.set(window.get("reconnect_rate", 0.0))
    CONTROLLER_BYPASS_FRACTION.set(window.get("bypass_fraction", 0.0))


# --------------------------------------------------------------- exposition
def _render_family(lines: List[str], name: str, fam: Dict[str, Any],
                   extra_labels: Dict[str, str]) -> None:
    for s in fam["samples"]:
        labels = dict(s.get("labels", {}))
        labels.update(extra_labels)
        if fam["kind"] == "histogram":
            cum = 0
            base = {k: v for k, v in labels.items()}
            for c, bound in zip(s["counts"], fam["bounds"]):
                cum += c
                lab = dict(base)
                lab["le"] = repr(float(bound))
                lines.append(f"{name}_bucket{_fmt_labels(lab)} {cum}")
            lab = dict(base)
            lab["le"] = "+Inf"
            lines.append(f"{name}_bucket{_fmt_labels(lab)} {s['count']}")
            lines.append(f"{name}_sum{_fmt_labels(base)} "
                         f"{_fmt_value(s['sum'])}")
            lines.append(f"{name}_count{_fmt_labels(base)} {s['count']}")
        else:
            lines.append(f"{name}{_fmt_labels(labels)} "
                         f"{_fmt_value(s['value'])}")


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(snapshots: List[Tuple[Dict[str, str], Dict[str, Any]]]
                      ) -> str:
    """Prometheus text format (v0.0.4) from [(extra_labels, snapshot)].

    Families are merged by name across snapshots; each snapshot's samples
    carry its extra labels (e.g. ``rank="1"``), so one scrape shows the
    whole fleet."""
    order: List[str] = []
    merged: Dict[str, List[Tuple[Dict[str, str], Dict[str, Any]]]] = {}
    for extra, snap in snapshots:
        for name, fam in snap.get("families", {}).items():
            if name not in merged:
                merged[name] = []
                order.append(name)
            merged[name].append((extra, fam))
    lines: List[str] = []
    for name in order:
        first = merged[name][0][1]
        lines.append(f"# HELP {name} {first['help']}")
        lines.append(f"# TYPE {name} {first['kind']}")
        for extra, fam in merged[name]:
            _render_family(lines, name, fam, extra)
    return "\n".join(lines) + "\n"


def lint_exposition(text: str) -> List[str]:
    """Pure-Python promtool-style check of Prometheus text format.

    Returns a list of violations (empty = clean).  Covers the drift CI
    must catch: TYPE/HELP pairing, sample↔family consistency, histogram
    +Inf/_sum/_count completeness, numeric values, and duplicate series."""
    import re
    errors: List[str] = []
    typed: Dict[str, str] = {}
    seen_series = set()
    hist_state: Dict[str, Dict[str, bool]] = {}
    name_rx = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    sample_rx = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
    label_rx = re.compile(
        r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 4 or not name_rx.match(parts[2]):
                errors.append(f"line {i}: malformed HELP")
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                errors.append(f"line {i}: malformed TYPE")
                continue
            if parts[2] in typed:
                errors.append(f"line {i}: duplicate TYPE for {parts[2]}")
            typed[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = sample_rx.match(line)
        if not m:
            errors.append(f"line {i}: unparseable sample: {line!r}")
            continue
        name, labelstr, value = m.group(1), m.group(2) or "", m.group(3)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in typed and \
                    typed[name[:-len(suffix)]] == "histogram":
                base = name[:-len(suffix)]
        if base not in typed:
            errors.append(f"line {i}: sample {name} has no TYPE declaration")
            continue
        if typed[base] == "histogram":
            st = hist_state.setdefault(base, {})
            if name.endswith("_bucket") and 'le="+Inf"' in labelstr:
                st["inf"] = True
            if name.endswith("_sum"):
                st["sum"] = True
            if name.endswith("_count"):
                st["count"] = True
            if name == base:
                errors.append(
                    f"line {i}: bare sample for histogram {base}")
        if labelstr:
            for pair in _split_labels(labelstr[1:-1]):
                if pair and not label_rx.match(pair):
                    errors.append(f"line {i}: malformed label {pair!r}")
        if value not in ("+Inf", "-Inf", "NaN"):
            try:
                float(value)
            except ValueError:
                errors.append(f"line {i}: non-numeric value {value!r}")
        key = (name, labelstr)
        if key in seen_series:
            errors.append(f"line {i}: duplicate series {name}{labelstr}")
        seen_series.add(key)
    for base, st in hist_state.items():
        for part in ("inf", "sum", "count"):
            if not st.get(part):
                errors.append(f"histogram {base} missing "
                              f"{'+Inf bucket' if part == 'inf' else '_' + part}")
    return errors


def _split_labels(inner: str) -> List[str]:
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, cur, in_q, esc = [], "", False, False
    for ch in inner:
        if esc:
            cur += ch
            esc = False
        elif ch == "\\":
            cur += ch
            esc = True
        elif ch == '"':
            cur += ch
            in_q = not in_q
        elif ch == "," and not in_q:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        parts.append(cur)
    return parts


# ---------------------------------------------------------------- publisher
class MetricsPublisher:
    """Background thread PUT-ing periodic snapshots to the rendezvous KV
    (scope ``metrics``, key ``rank.N``) so the driver's ``/metrics`` route
    serves a fleet-wide view.  A final publish happens on close() so the
    end-of-run straggler report sees complete histograms."""

    SCOPE = "metrics"

    def __init__(self, addr: str, port: int, rank: int,
                 snapshot_fn: Callable[[], Dict[str, Any]],
                 interval: float = 5.0):
        self.addr = addr
        self.port = int(port)
        self.rank = int(rank)
        self.interval = max(0.1, float(interval))
        self._snapshot_fn = snapshot_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.addr and self.port:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def publish_now(self, retries: int = 3) -> bool:
        if not (self.addr and self.port):
            return False
        try:
            snap = self._snapshot_fn()
            snap["rank"] = self.rank
            body = json.dumps(snap).encode()
            # Sharded KV (docs/control-plane.md): the metrics scope may
            # live on a shard server, not the primary — resolve per
            # publish (stdlib-only module, routing logic included).
            from ..runner.http_client import resolve_kv_addr
            addr, port, _ = resolve_kv_addr(self.addr, self.port,
                                            self.SCOPE)
            url = (f"http://{addr}:{port}/{self.SCOPE}/"
                   f"rank.{self.rank}")
            # Bounded retry (stdlib-only by design — see module docstring;
            # runner/http_client.put_kv carries the canonical schedule): a
            # transient refusal must not lose the FINAL close() publish,
            # which is what the straggler report reads.
            delay = 0.1
            for attempt in range(retries + 1):
                try:
                    req = urllib.request.Request(url, data=body,
                                                 method="PUT")
                    with urllib.request.urlopen(req, timeout=5):
                        pass
                    return True
                except Exception:
                    if attempt >= retries:
                        raise
                    time.sleep(delay)
                    delay = min(delay * 2, 1.0)
            return True
        except Exception:
            return False  # metrics must never take the job down

    def _loop(self) -> None:
        self.publish_now()
        while not self._stop.wait(self.interval):
            self.publish_now()

    def close(self) -> None:
        self._stop.set()
        self.publish_now()


# --------------------------------------------------------- straggler report
def _fmt_seconds(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    if v < 1e-3:
        return f"{v * 1e6:.0f}us"
    if v < 1.0:
        return f"{v * 1e3:.1f}ms"
    return f"{v:.2f}s"


def _hist_quantile(fam: Dict[str, Any], q: float) -> Optional[float]:
    """q-quantile (bucket upper bound) over ALL of a family's series."""
    bounds = fam.get("bounds", [])
    counts = [0] * len(bounds)
    total = 0
    for s in fam.get("samples", []):
        for i, c in enumerate(s.get("counts", [])[:len(bounds)]):
            counts[i] += c
        total += s.get("count", 0)
    if not total:
        return None
    target = q * total
    cum = 0
    for c, bound in zip(counts, bounds):
        cum += c
        if cum >= target:
            return float(bound)
    return float(bounds[-1]) if bounds else None


def _hist_count(fam: Dict[str, Any]) -> int:
    return sum(s.get("count", 0) for s in fam.get("samples", []))


def _age_rows(snapshots: Dict[int, Dict[str, Any]],
              family: str = "hvd_negotiation_age_seconds"
              ) -> List[Tuple[int, Optional[float], Optional[float], int]]:
    """Per-rank (rank, p50, p99, n) negotiation-age quantiles from
    harvested snapshots — the shared source of the end-of-run straggler
    report and the live in-run check (StragglerMonitor)."""
    rows = []
    for rank in sorted(snapshots):
        fam = snapshots[rank].get("families", {}).get(family)
        if not fam or not _hist_count(fam):
            # eager ages absent (pure SPMD run): fall back to the native
            # controller's negotiation ages, recorded on rank 0 only
            fam = snapshots[rank].get("families", {}).get(
                "hvd_controller_negotiation_age_seconds")
        if not fam or not _hist_count(fam):
            continue
        rows.append((rank, _hist_quantile(fam, 0.5),
                     _hist_quantile(fam, 0.99), _hist_count(fam)))
    return rows


def detect_straggler(snapshots: Dict[int, Dict[str, Any]],
                     skew_ratio: float = 4.0,
                     floor_seconds: float = 1e-3) -> Optional[Dict[str, Any]]:
    """Live straggler verdict from one round of fleet snapshots: the rank
    whose negotiation-age p99 exceeds ``skew_ratio`` times the median of
    its peers' p99s (and an absolute floor, so µs-level jitter on an idle
    fleet never names anyone).  The default ratio is 4x because quantile
    estimates come from power-of-2 buckets — adjacent buckets differ by
    exactly 2x, so a 2x threshold would fire on quantization noise.
    None when no rank stands out or fewer than two ranks have data —
    detection needs a peer baseline.

    The comparison itself lives in the watch plane
    (``horovod_tpu.watch.rules.straggler_verdict``): the committed
    ``straggler-suspect`` default rule thresholds the SAME skew over the
    fleet series store, so the live monitor, the end-of-run report path
    and the alert rule are ONE detection path (docs/watch.md)."""
    rows = {r: p99 for r, _, p99, _ in _age_rows(snapshots)
            if p99 is not None}
    from horovod_tpu.watch.rules import straggler_verdict
    return straggler_verdict(rows, skew_ratio=skew_ratio,
                             floor_seconds=floor_seconds)


class StragglerMonitor:
    """Driver-side periodic straggler check (the in-run promotion of the
    end-of-run report): every ``interval`` seconds it re-reads the fleet's
    metric snapshots, logs a warning naming the suspect rank and sets the
    ``hvd_straggler_suspect`` gauge (-1 when nobody stands out).  Runs on
    the launcher, which owns the rendezvous KV the workers publish into
    (runner/launch.py)."""

    def __init__(self, snapshots_fn: Callable[[], Dict[int, Dict[str, Any]]],
                 interval: float, skew_ratio: float = 4.0,
                 log_fn: Optional[Callable[[str], None]] = None):
        self._snapshots_fn = snapshots_fn
        self.interval = max(0.1, float(interval))
        self.skew_ratio = float(skew_ratio)
        self._log = log_fn or (lambda msg: print(msg, flush=True))
        self._last_suspect: Optional[int] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def check_once(self) -> Optional[Dict[str, Any]]:
        try:
            verdict = detect_straggler(self._snapshots_fn(),
                                       skew_ratio=self.skew_ratio)
        except Exception:
            return None  # telemetry must never take the launcher down
        if verdict is None:
            STRAGGLER_SUSPECT.set(-1)
            self._last_suspect = None
            return None
        STRAGGLER_SUSPECT.set(verdict["rank"])
        if verdict["rank"] != self._last_suspect:  # warn on transitions,
            self._last_suspect = verdict["rank"]   # not every period
            self._log(
                f"[hvd] straggler suspect: rank {verdict['rank']} "
                f"(negotiation-age p99 {_fmt_seconds(verdict['p99'])} vs "
                f"peer median {_fmt_seconds(verdict['peer_median_p99'])})")
        return verdict

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.check_once()

    def stop(self) -> None:
        self._stop.set()


def straggler_report(snapshots: Dict[int, Dict[str, Any]],
                     family: str = "hvd_negotiation_age_seconds") -> str:
    """Rank-0 end-of-run report: per-rank negotiation-age p50/p99, naming
    the slowest rank (the fleet-level extension of the stall inspector —
    it tells you WHO was late, not only that someone was).

    ``snapshots`` maps rank -> snapshot dict (MetricsRegistry.snapshot()
    shape, as harvested from the rendezvous KV)."""
    rows = _age_rows(snapshots, family)
    if not rows:
        return ""
    slowest = max(rows, key=lambda r: (r[2] or 0.0, r[1] or 0.0))
    lines = ["[hvd] straggler report (negotiation age, per rank):"]
    for rank, p50, p99, n in rows:
        lines.append(f"  rank {rank}: p50={_fmt_seconds(p50)} "
                     f"p99={_fmt_seconds(p99)} (n={n})")
    lines.append(f"  slowest: rank {slowest[0]} "
                 f"(p99 {_fmt_seconds(slowest[2])})")
    return "\n".join(lines)
