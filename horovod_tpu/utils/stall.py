"""Stall inspector: the deadlock watchdog.

The reference's coordinator warns when some ranks submitted a tensor and
others haven't for >60 s, and can shut the job down after a second threshold
(reference: horovod/common/stall_inspector.{h,cc}; knobs
HOROVOD_STALL_CHECK_TIME_SECONDS / HOROVOD_STALL_SHUTDOWN_TIME_SECONDS,
stall_inspector.h:70-82; wired into the controller at controller.cc:126-135).

In SPMD mode whole-program collectives can't partially stall, but the eager
path (and multi-host rendezvous) can: a submitted-but-never-completed op
means a peer process died or diverged.  This inspector tracks
submit/complete pairs and raises/warns on the same thresholds.
"""

from __future__ import annotations

import time
from typing import Dict

from ..common import hvdlogging as log
from ..common.exceptions import StallError
from . import metrics as _metrics


class StallInspector:
    """Runs its periodic check on a daemon thread — the submitting thread is
    blocked inside the hung collective when a stall actually happens, so it
    cannot run the check itself (the reference's check runs on the C++
    background coordination thread for the same reason).

    The watcher thread can't raise into the blocked thread; past the
    shutdown threshold it logs FATAL and hard-exits the process, matching
    the reference's stall-shutdown behavior."""

    def __init__(self, warn_seconds: int = 60, shutdown_seconds: int = 0,
                 poll_interval: float = 1.0, hard_exit: bool = True):
        import threading
        self.warn_seconds = warn_seconds
        self.shutdown_seconds = shutdown_seconds
        self.hard_exit = hard_exit
        self._pending: Dict[str, float] = {}
        self._warned: Dict[str, bool] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._watch_loop, args=(poll_interval,), daemon=True)
        self._thread.start()

    def _watch_loop(self, interval: float) -> None:
        import os
        while not self._stop.wait(interval):
            try:
                self.check()
            except StallError as e:
                log.error("stall shutdown: %s", e)
                if self.hard_exit:
                    os._exit(42)

    def close(self) -> None:
        self._stop.set()

    def record_submit(self, name: str) -> None:
        with self._lock:
            self._pending.setdefault(name, time.monotonic())

    def record_complete(self, name: str) -> None:
        # Chaos straggler hook: a stall event with point "complete" slows
        # this rank between collective completion and its completion
        # record — the slow-host straggler mode (late D2H, GC pause).
        # Peers are NOT dragged along (the collective itself already
        # finished), so the inflated ages attribute to the injected rank,
        # which is what the straggler report must name (docs/chaos.md).
        from .. import chaos
        chaos.maybe_stall("complete")
        with self._lock:
            submitted = self._pending.pop(name, None)
            self._warned.pop(name, None)
        if submitted is not None:
            # Completion age feeds the per-rank negotiation-age histogram
            # that the rank-0 straggler report quantizes (docs/metrics.md).
            _metrics.NEGOTIATION_AGE.observe(time.monotonic() - submitted)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def check(self) -> None:
        """Warn/abort on overdue tensors (reference:
        StallInspector::CheckForStalledTensors)."""
        now = time.monotonic()
        with self._lock:
            stalled = [(n, now - t) for n, t in self._pending.items()
                       if now - t > self.warn_seconds]
        for name, age in stalled:
            if not self._warned.get(name):
                _metrics.STALL_WARNINGS.inc()
                log.warning(
                    "One or more tensors were submitted to be reduced/"
                    "gathered but were not completed for %.0f seconds: %s. "
                    "This may indicate a dead or diverged peer process.",
                    age, name)
                self._warned[name] = True
            if self.shutdown_seconds and age > self.shutdown_seconds:
                raise StallError(
                    f"tensor {name} stalled for {age:.0f}s > "
                    f"HOROVOD_STALL_SHUTDOWN_TIME_SECONDS="
                    f"{self.shutdown_seconds}")
