"""Health plane: per-rank heartbeats and driver-side progress supervision.

The metrics plane (utils/metrics.py) answers "how is the run doing" and
the tracing plane (utils/timeline.py) "where did the time go"; this
module answers the liveness half of "why did the run die" — the
postmortem plane's live leg (docs/postmortem.md).  Every rank PUTs a
small heartbeat to the rendezvous KV scope ``health`` (key ``rank.N``)
on the PR-5 aligned fleet clock:

  * ``step`` / ``step_time``: the training loop's progress, recorded by
    :func:`record_step` (the health analog of ``hvd.chaos.step``);
  * native core liveness (``CoordinationCore.health()``): cycle count,
    µs since the last completed cycle, tensor-queue depth, transport
    health — built lock-free in csrc so it answers even mid-wedge;
  * ``pending_collectives``: the stall inspector's submitted-but-not-
    completed count.  This is the attribution key for fleet-wide
    stalls: when every rank freezes, the rank with NOTHING pending is
    the one that stopped feeding the collective everyone else is
    blocked inside.

The rendezvous server renders the scope at ``GET /health`` with
per-rank staleness (runner/http_server.py); the launcher's
:class:`HealthMonitor` turns the same data into heartbeat-lost / stall
verdicts that drive supervision (runner/launch.py --postmortem).

Deliberately stdlib-only with lazy package imports, mirroring
utils/metrics.py, so a heartbeat can never take the job down.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, Optional, Tuple

HEALTH_SCOPE = "health"

_step_lock = threading.Lock()
_last_step: Optional[int] = None
_last_step_time: Optional[float] = None  # local wall seconds


def record_step(step: int) -> None:
    """Training-loop progress hook (``hvd.postmortem.record_step(i)``):
    stamps the heartbeat's ``step``/``step_time`` fields so the driver
    can tell a stalled loop from a dead process.  Cheap enough to call
    every step; optional — without it supervision falls back to
    heartbeat presence and native cycle progress alone."""
    global _last_step, _last_step_time
    with _step_lock:
        _last_step = int(step)
        _last_step_time = time.time()


def last_step() -> Tuple[Optional[int], Optional[float]]:
    with _step_lock:
        return _last_step, _last_step_time


def reset_step() -> None:
    """Test hook: forget recorded progress (module-global state)."""
    global _last_step, _last_step_time
    with _step_lock:
        _last_step = None
        _last_step_time = None


def heartbeat_payload(rank: int, clock: Optional[Any] = None,
                      core: Optional[Any] = None,
                      pending_collectives: Optional[int] = None
                      ) -> Dict[str, Any]:
    """One heartbeat, JSON-able.  ``time``/``step_time`` are wall seconds
    PLUS the measured server offset (utils/clocksync.py) — the aligned
    fleet clock — so the driver compares them against its own wall clock
    directly and postmortem events from different ranks order truthfully.
    """
    import os
    offset = float(getattr(clock, "offset", 0.0) or 0.0) if clock else 0.0
    step, step_time = last_step()
    hb: Dict[str, Any] = {
        "rank": int(rank),
        "pid": os.getpid(),
        "time": time.time() + offset,
        "step": step,
        "step_time": (step_time + offset) if step_time is not None
        else None,
    }
    if pending_collectives is not None:
        hb["pending_collectives"] = int(pending_collectives)
    if core is not None:
        try:
            hb["core"] = core.health()
        except Exception:
            pass  # a closing core must not break the heartbeat
    # Memory plane (perf/memstats.py): the last sampled watermark rides
    # the heartbeat so a SIGKILLed rank's FINAL heartbeat carries the
    # pressure evidence the postmortem `oom` classifier reads
    # (docs/memory.md#oom, docs/postmortem.md#taxonomy).
    try:
        from ..perf.memstats import last_sample
        row = last_sample()
        if row is not None:
            hb["mem"] = {"watermark": row.get("watermark"),
                         "bytes_in_use": row.get("bytes_in_use"),
                         "cap_bytes": row.get("cap_bytes"),
                         "source": row.get("source")}
    except Exception:
        pass  # the memory leg must never break the heartbeat
    return hb


class HeartbeatPublisher:
    """Background thread PUT-ing heartbeats to the rendezvous KV (scope
    ``health``, key ``rank.N``).  Mirrors MetricsPublisher: plain urllib
    with a short bounded retry, daemonized, final publish on close() so
    the postmortem sees the last known state.  Deliberately does NOT go
    through runner/http_client.put_kv — an injected chaos KV blackout
    models an application-level outage and must not sever the liveness
    channel that attributes it."""

    SCOPE = HEALTH_SCOPE

    def __init__(self, addr: str, port: int, rank: int,
                 payload_fn: Callable[[], Dict[str, Any]],
                 interval: float = 1.0):
        self.addr = addr
        self.port = int(port)
        self.rank = int(rank)
        self.interval = max(0.05, float(interval))
        self._payload_fn = payload_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.addr and self.port:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def publish_now(self, retries: int = 2) -> bool:
        if not (self.addr and self.port):
            return False
        try:
            body = json.dumps(self._payload_fn()).encode()
            # Sharded KV (docs/control-plane.md): route to the health
            # scope's owning shard.  Routing only — still not through
            # put_kv, so a chaos blackout cannot sever liveness.
            from ..runner.http_client import resolve_kv_addr
            addr, port, _ = resolve_kv_addr(self.addr, self.port,
                                            self.SCOPE)
            url = (f"http://{addr}:{port}/{self.SCOPE}/"
                   f"rank.{self.rank}")
            delay = 0.1
            for attempt in range(retries + 1):
                try:
                    req = urllib.request.Request(url, data=body,
                                                 method="PUT")
                    with urllib.request.urlopen(req, timeout=5):
                        pass
                    return True
                except Exception:
                    if attempt >= retries:
                        raise
                    time.sleep(delay)
                    delay = min(delay * 2, 0.5)
            return True
        except Exception:
            return False  # liveness reporting must never kill the job

    def _loop(self) -> None:
        self.publish_now()
        while not self._stop.wait(self.interval):
            self.publish_now()

    def close(self) -> None:
        self._stop.set()
        self.publish_now()


# ------------------------------------------------------- driver-side view
def fleet_health(heartbeats: Dict[str, bytes],
                 receipt_times: Dict[str, float],
                 now: Optional[float] = None,
                 stale_after: float = 10.0) -> Dict[str, Any]:
    """Render the ``health`` KV scope as the fleet view ``GET /health``
    serves: rank -> {heartbeat, age_s, stale}.  Staleness uses the
    SERVER's receipt time, not the heartbeat's self-reported clock, so
    a rank with a broken clock still ages honestly."""
    now = time.time() if now is None else now
    ranks: Dict[str, Any] = {}
    for key in sorted(heartbeats):
        if not key.startswith("rank."):
            continue
        try:
            hb = json.loads(heartbeats[key])
        except (ValueError, TypeError):
            continue  # a torn PUT must not 500 the whole view
        rank = str(hb.get("rank", key.split(".", 1)[1]))
        received = receipt_times.get(key)
        age = (now - received) if received is not None else None
        ranks[rank] = {
            "heartbeat": hb,
            "age_s": round(age, 3) if age is not None else None,
            "stale": bool(age is not None and age > stale_after),
        }
    return {"now": now, "stale_after_s": stale_after, "ranks": ranks}


class HealthMonitor:
    """Launcher-side supervision verdicts from the fleet's heartbeats
    (hvdrun --postmortem; docs/postmortem.md).

    Two consumers act on the verdicts: the static launcher SIGABRTs the
    rank and lets the job die with forensics (runner/launch.py), while
    the elastic driver SIGABRTs and then RESETS the fleet — for a
    serving fleet a wedged engine means an elastic restart, not job
    death, and the request journal redrives what was in flight
    (elastic/driver.py; docs/serving.md#fault-tolerance).  The monitor
    is round-scoped there: the driver clears the ``health`` KV scope at
    every reset and builds a fresh monitor, so a dead incarnation's
    stale heartbeats never read as losses.  Serving workers tick
    :func:`record_step` every loop iteration (idle included), so an
    idle fleet looks alive and only a genuinely frozen loop stalls.

    Two failure modes, judged per check against ``timeout`` seconds:

      * **heartbeat-lost** — a rank that heartbeated before has gone
        silent (daemon publisher dead => process dead or unreachable);
      * **stall** — heartbeats keep arriving but recorded progress
        froze fleet-wide.  Attribution: among frozen ranks, suspect the
        ones with ``pending_collectives == 0`` — everyone else is
        blocked INSIDE a collective waiting for them.  When every
        frozen rank is blocked (no such rank), fall back to the oldest
        ``step_time`` only if the WHOLE live fleet froze, since a
        partially-frozen fleet with all suspects blocked points at a
        peer that already exited (the exit record attributes that).
    """

    def __init__(self, snapshots_fn: Callable[[], Dict[str, Any]],
                 timeout: float = 10.0):
        self._snapshots_fn = snapshots_fn  # -> fleet_health() shape
        self.timeout = float(timeout)
        self._seen: set = set()

    def verdicts(self, live_ranks) -> Dict[int, str]:
        """rank -> "heartbeat-lost" | "stall" for live ranks needing
        intervention this check (empty when the fleet looks healthy)."""
        try:
            view = self._snapshots_fn()
        except Exception:
            return {}  # supervision must never take the launcher down
        now = float(view.get("now") or time.time())
        ranks = view.get("ranks", {})
        out: Dict[int, str] = {}
        frozen: Dict[int, Dict[str, Any]] = {}
        for r in live_ranks:
            info = ranks.get(str(r))
            if info is None:
                continue  # never heartbeated: bring-up, not a loss
            self._seen.add(r)
            age = info.get("age_s")
            if age is not None and age > self.timeout:
                out[r] = "heartbeat-lost"
                continue
            hb = info.get("heartbeat", {})
            st = hb.get("step_time")
            if st is not None and now - float(st) > self.timeout:
                frozen[r] = hb
        if frozen:
            idle = [r for r, hb in frozen.items()
                    if hb.get("pending_collectives") == 0]
            if idle:
                for r in idle:
                    out[r] = "stall"
            elif len(frozen) == len(list(live_ranks)):
                oldest = min(frozen,
                             key=lambda r: float(frozen[r]["step_time"]))
                out[oldest] = "stall"
        return out
