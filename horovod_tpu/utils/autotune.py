"""Autotuning of fusion/cycle tunables for the SPMD path.

The reference's ParameterManager runs Bayesian optimization over (fusion
threshold, cycle time) scoring bytes/sec, warms up, samples every N steps,
and logs to HOROVOD_AUTOTUNE_LOG (reference: parameter_manager.{h,cc},
common.h:70-75 knobs).  The native math lives in csrc/optim.cc; this wrapper
feeds it step measurements from the jax training loop and republishes the
tuned fusion threshold to the bucket planner.

Cross-process consistency: every process must hold the SAME threshold or
their bucket plans (and therefore the SPMD programs) diverge.  Like the
reference — rank 0 tunes, then broadcasts (controller.cc:39-53
SynchronizeParameters) — only process 0 runs the optimizer here; tuned
values are broadcast to all processes on every record() until tuning
completes.  record() is therefore collective across processes in multi-host
runs: call it once per step on every process.

For the *eager/controller* path the same machinery runs inside the native
core's cycle loop (csrc/core.cc), enabled by the HOROVOD_AUTOTUNE knob.

Usage (jax SPMD path)::

    hvd.init()                 # HOROVOD_AUTOTUNE=1 in env
    tuner = hvd.autotuner()
    for batch in data:
        with tuner.measure(nbytes=grad_bytes):
            step(...)          # jit'd train step, blocks until ready
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

import numpy as np

from ..common import hvdlogging as log
from ..common.basics import NativeParameterManager


class Autotuner:
    """Feeds step measurements into the native parameter manager and exposes
    the live fusion threshold (reference: ParameterManager::Update).

    With ``policy_arms`` (HOROVOD_WIRE_POLICY=auto), the wire-policy
    dimension joins the search: a deterministic UCB1 bandit
    (csrc/optim.cc ArmBandit) over the arm names, scored like the GP in
    effective bytes/sec.  The categorical axis stays OFF the GP — its RBF
    kernel would invent distances between unrelated policies.  With
    ``depth_arms`` (HOROVOD_OVERLAP on), the overlap pipeline depth
    (ops/overlap.py) is a second arm dimension; when both are present
    the two are searched JOINTLY over the product space (csrc/optim.cc
    ProductBandit — the best depth depends on the policy, since a
    compressed wire shortens exactly the sync the pipeline hides).  The
    chosen arm indices ride the same rank-0 broadcast as the threshold,
    so every process compiles identical SPMD programs."""

    def __init__(self, knobs, process_rank: int = 0, process_size: int = 1,
                 policy_arms=None, depth_arms=None):
        self._process_rank = process_rank
        self._process_size = process_size
        self._threshold = int(knobs["HOROVOD_FUSION_THRESHOLD"])
        self._cycle_ms = float(knobs["HOROVOD_CYCLE_TIME"])
        self._done = False
        self._pm = None
        self._arms = tuple(policy_arms) if policy_arms else ()
        self._depths = tuple(int(d) for d in depth_arms) if depth_arms \
            else ()
        self._policy_arm = 0
        self._depth_arm = 0
        self._bandit = None
        self._bandit_kind = None
        if process_rank == 0:
            self._pm = NativeParameterManager(
                initial_threshold=self._threshold,
                initial_cycle_ms=self._cycle_ms,
                warmup_samples=knobs["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"],
                steps_per_sample=knobs["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"],
                max_samples=knobs["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"],
                gp_noise=knobs["HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"])
            sps = knobs["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"]
            n_pol, n_dep = len(self._arms), len(self._depths)
            if n_pol > 1 and n_dep > 1:
                from ..common.basics import NativeProductBandit
                self._bandit = NativeProductBandit(
                    n_pol, n_dep, steps_per_sample=sps,
                    max_pulls=4 * n_pol * n_dep)
                self._bandit_kind = "product"
            elif n_pol > 1:
                from ..common.basics import NativeArmBandit
                self._bandit = NativeArmBandit(n_pol, steps_per_sample=sps,
                                               max_pulls=4 * n_pol)
                self._bandit_kind = "policy"
            elif n_dep > 1:
                from ..common.basics import NativeArmBandit
                self._bandit = NativeArmBandit(n_dep, steps_per_sample=sps,
                                               max_pulls=4 * n_dep)
                self._bandit_kind = "depth"
        self._log_fh = None
        log_path = knobs["HOROVOD_AUTOTUNE_LOG"]
        if log_path and process_rank == 0:
            fresh = not (os.path.exists(log_path) and
                         os.path.getsize(log_path) > 0)
            self._log_fh = open(log_path, "a")
            if fresh:
                self._log_fh.write(
                    "threshold_bytes,cycle_ms,best_score_bytes_per_s\n")

    @property
    def fusion_threshold(self) -> int:
        return self._threshold

    @property
    def cycle_ms(self) -> float:
        return self._cycle_ms

    @property
    def done(self) -> bool:
        return self._done

    @property
    def best_score(self) -> float:
        return self._pm.best_score if self._pm is not None else 0.0

    @property
    def wire_policy(self) -> Optional[str]:
        """The current wire-policy arm name, or None when the policy
        dimension is not being tuned (consumed by Runtime.wire_policy)."""
        if not self._arms:
            return None
        return self._arms[self._policy_arm]

    @property
    def overlap_depth(self) -> Optional[int]:
        """The current overlap-depth arm value, or None when the depth
        dimension is not being tuned (consumed by
        Runtime.overlap_depth)."""
        if not self._depths:
            return None
        return self._depths[self._depth_arm]

    def _sync(self) -> None:
        """Broadcast (threshold, cycle, done, policy arm, depth arm)
        from process 0 so every process plans identical buckets, wire
        formats AND pipeline depths.  No-op single-process."""
        if self._process_size <= 1:
            return
        from jax.experimental import multihost_utils
        vals = multihost_utils.broadcast_one_to_all(
            np.array([self._threshold, self._cycle_ms,
                      1.0 if self._done else 0.0,
                      float(self._policy_arm),
                      float(self._depth_arm)], np.float64))
        self._threshold = int(vals[0])
        self._cycle_ms = float(vals[1])
        self._done = bool(vals[2])
        self._policy_arm = int(vals[3])
        self._depth_arm = int(vals[4])

    def record(self, nbytes: int, seconds: float) -> bool:
        """Record one step's traffic; returns True when tunables changed
        (threshold, cycle, or wire-policy arm — any of which means the
        caller should re-trace).  Collective across processes while tuning
        is live."""
        if self._done:
            return False
        changed = False
        if self._pm is not None:
            if not self._pm.done:
                changed = self._pm.update(nbytes, seconds)
                self._threshold = self._pm.threshold
                self._cycle_ms = self._pm.cycle_ms
                if changed and self._log_fh:
                    self._log_fh.write(
                        f"{self._threshold},{self._cycle_ms:.3f},"
                        f"{self._pm.best_score:.1f}\n")
                    self._log_fh.flush()
            if self._bandit is not None and not self._bandit.done:
                # Same score the GP sees: logical payload bytes per second
                # — a compressed wire moves the same payload faster, so
                # "effective bytes/sec" rewards the formats that help and
                # punishes quantize/cast overhead that doesn't pay off.
                if self._bandit.update(nbytes / max(seconds, 1e-12)):
                    changed = True
                if self._bandit_kind == "product":
                    self._policy_arm = self._bandit.arm_a
                    self._depth_arm = self._bandit.arm_b
                elif self._bandit_kind == "policy":
                    self._policy_arm = self._bandit.arm
                else:
                    self._depth_arm = self._bandit.arm
            self._done = self._pm.done and (
                self._bandit is None or self._bandit.done)
            if changed:
                log.debug("autotune: threshold=%d cycle=%.2fms policy=%s "
                          "depth=%s done=%s", self._threshold,
                          self._cycle_ms, self.wire_policy,
                          self.overlap_depth, self._done)
        self._sync()
        return changed

    @contextlib.contextmanager
    def measure(self, nbytes: int):
        """Context manager timing one (blocking) training step."""
        t0 = time.monotonic()
        yield
        self.record(nbytes, time.monotonic() - t0)

    def close(self) -> None:
        if self._log_fh:
            self._log_fh.close()
            self._log_fh = None
