"""xprof / JAX-profiler integration — the TPU-native analog of the
reference's NVTX op ranges (reference: horovod/common/nvtx_op_range.h +
operations.cc:1018-1033: every user-facing op opens an NVTX range so
device traces attribute time to the op that launched it).

On TPU the tracer is the JAX profiler (xprof/TensorBoard): ``start`` /
``stop`` wrap a trace session, and ``annotate`` opens a named host range
that xprof correlates with device activity.  The framework's eager
collectives annotate themselves (ops/collectives.py), so a captured
trace shows HOROVOD_ALLREDUCE etc. exactly where the reference would
show its NVTX ranges.  The Chrome-trace Timeline (utils/timeline.py)
remains the lightweight always-on story; this is the deep-dive tool.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator, Optional, Tuple

_active_logdir: Optional[str] = None


def start(logdir: str) -> None:
    """Begin an xprof trace session writing into ``logdir`` (view with
    TensorBoard's profile plugin or xprof)."""
    global _active_logdir
    import jax
    jax.profiler.start_trace(logdir)
    _active_logdir = logdir


def stop() -> None:
    global _active_logdir
    import jax
    try:
        jax.profiler.stop_trace()
    finally:
        # Clear even when stop_trace raises (e.g. the session was already
        # stopped directly through jax.profiler) — a stuck is_active()
        # would block every future session in this process.
        _active_logdir = None


def is_active() -> bool:
    return _active_logdir is not None


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """``with hvd.profiler.trace("/tmp/prof"): step()`` — session-scoped
    capture."""
    start(logdir)
    try:
        yield
    finally:
        stop()


def annotate(name: str):
    """Named range correlated with device activity in the captured trace
    (NVTX-range analog).  Context manager; cheap enough to leave on
    unconditionally — outside a trace session the annotation is a no-op.
    For the decorator form use :func:`annotate_function`."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def annotate_function(fn, name: Optional[str] = None):
    """Decorator form: every call of ``fn`` opens a named range
    (``jax.profiler.annotate_function`` passthrough)."""
    import jax
    return jax.profiler.annotate_function(fn, name=name)


def timed(fn: Callable[[], object],
          name: str = "HOROVOD_EXEC") -> Tuple[object, float]:
    """Run ``fn`` inside a named profiler range and return
    ``(result, duration_us)``.

    The measured-duration bridge between this deep-dive tracer and the
    lightweight timeline: the negotiated dispatch path wraps each
    collective's execution here and feeds the duration into its EXEC
    timeline span (ops/negotiated.py), so the Chrome trace shows how
    long the op actually ran instead of a zero-width begin/end pair —
    and an xprof capture correlates the same range with device activity.
    The annotation is best-effort; the measurement never is."""
    try:
        ctx = annotate(name)
    except Exception:
        ctx = contextlib.nullcontext()  # no jax: keep the measurement
    t0 = time.perf_counter_ns()
    with ctx:
        result = fn()
    return result, (time.perf_counter_ns() - t0) / 1e3


__all__ = ["start", "stop", "trace", "annotate", "annotate_function",
           "is_active", "timed"]
