"""Chrome-trace timeline of collective activity.

The reference writes a Chrome-trace JSON of every tensor's
NEGOTIATE -> QUEUE -> EXEC lifecycle from a dedicated writer thread fed by a
lock-free queue (reference: horovod/common/timeline.{h,cc}; tensors are
modeled as chrome "pids", timeline.cc:244-254; activated by
HOROVOD_TIMELINE, runtime start/stop operations.cc:740-769).

Here the writer thread + queue survive; events come from the eager ops, the
bucketed gradient sync, and (when enabled) cycle markers.  For deep XLA-level
profiling users should additionally use ``jax.profiler`` (xprof) — this
timeline covers the framework-level view the reference's does.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Dict, Optional


class Timeline:
    def __init__(self, path: str, mark_cycles: bool = False):
        self.path = path
        self.mark_cycles = mark_cycles
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._pids: Dict[str, int] = {}
        self._next_pid = 1
        self._start = time.perf_counter_ns()
        self._lock = threading.Lock()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._closed = False
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._writer.start()

    # ------------------------------------------------------------- internals
    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._start) / 1e3

    def _pid(self, tensor_name: str) -> int:
        with self._lock:
            pid = self._pids.get(tensor_name)
            if pid is None:
                pid = self._next_pid
                self._next_pid += 1
                self._pids[tensor_name] = pid
                self._q.put({"name": "process_name", "ph": "M", "pid": pid,
                             "args": {"name": tensor_name}})
            return pid

    def _write_loop(self) -> None:
        while True:
            ev = self._q.get()
            if ev is None:
                break
            if not self._first:
                self._file.write(",\n")
            self._first = False
            self._file.write(json.dumps(ev))
        self._file.write("\n]\n")
        self._file.close()

    # ------------------------------------------------------------ public API
    def begin(self, tensor_name: str, activity: str) -> None:
        """Begin an activity phase for a tensor (B event)."""
        self._q.put({"name": activity, "ph": "B", "pid": self._pid(tensor_name),
                     "tid": 0, "ts": self._now_us()})

    def end(self, tensor_name: str, activity: str) -> None:
        self._q.put({"name": activity, "ph": "E", "pid": self._pid(tensor_name),
                     "tid": 0, "ts": self._now_us()})

    def record_op(self, tensor_name: str, op_type: str, size: int,
                  duration_us: Optional[float] = None) -> None:
        """Complete (X) event for one collective execution."""
        self._q.put({"name": op_type, "ph": "X",
                     "pid": self._pid(tensor_name), "tid": 0,
                     "ts": self._now_us(),
                     "dur": duration_us if duration_us is not None else 1.0,
                     "args": {"size": int(size)}})

    def mark_cycle(self) -> None:
        """Negotiation-cycle tick (reference: HOROVOD_TIMELINE_MARK_CYCLES,
        operations.cc:442-445)."""
        if self.mark_cycles:
            self._q.put({"name": "CYCLE", "ph": "i", "pid": 0, "tid": 0,
                         "ts": self._now_us(), "s": "g"})

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._writer.join(timeout=5)
