"""Distributed Chrome-trace timeline of collective activity.

The reference writes a Chrome-trace JSON of every tensor's
NEGOTIATE -> QUEUE -> EXEC lifecycle from a dedicated writer thread fed by a
lock-free queue (reference: horovod/common/timeline.{h,cc}; tensors are
modeled as chrome "pids", timeline.cc:244-254; activated by
HOROVOD_TIMELINE, runtime start/stop operations.cc:740-769).

This rebuild extends that per-rank view into a *distributed* tracing
plane (the questions that matter in distributed training are cross-rank —
who is the straggler, where does negotiation wait; arxiv 1810.11112):

  * **aligned clock**: event timestamps are wall-clock µs rebased by the
    rank's measured offset against the rendezvous server
    (utils/clocksync.py), so every rank stamps events on ONE fleet epoch;
  * **native spans**: :class:`NativeTraceDrainer` pumps the C++ core's
    span ring (csrc/trace.h, ``hvd_core_trace``) — controller cycle
    phases, transport frames/reconnects, chaos faults — into the same
    writer thread;
  * **fleet merge**: :class:`TimelinePublisher` PUTs compacted chunks to
    the rendezvous KV scope ``timeline`` (mirroring MetricsPublisher);
    :func:`merge_timeline_chunks` renders them as one rank-laned
    Perfetto/Chrome JSON, served at ``GET /timeline`` and written by
    ``hvdrun --timeline-merge out.json``;
  * **crash safety**: the local file is flushed periodically and
    ``close()`` is idempotent, so a killed rank (chaos ``kill@step``)
    leaves a loadable trace — Chrome/Perfetto tolerate the missing
    closing bracket, and :func:`load_trace_events` repairs it for tools.

The local per-rank file stays a plain JSON event array with timestamps
relative to this rank's start (small, diff-friendly, what the existing
tests pin); published chunks carry ABSOLUTE aligned µs, which is what
makes the merged view line up.  For deep XLA-level profiling users should
additionally use ``jax.profiler`` (xprof) — this timeline covers the
framework + coordination view.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

TIMELINE_KV_SCOPE = "timeline"

# Publisher-side cap on buffered-but-unpublished events: a dead publisher
# must cost trace completeness, never memory.
MAX_PENDING_CHUNK_EVENTS = 50000

_NATIVE_LANES = {"c": "controller", "t": "transport", "x": "chaos"}


def collapse_name(name: str) -> str:
    """Collapse auto-generated per-call names to their prefix: each unique
    name allocates a chrome pid + metadata entry forever, so per-call
    unique names would leak memory and bloat the trace."""
    for marker in (".noname.", ".tfneg."):
        if marker in name:
            return name.split(marker)[0]
    return name


class Timeline:
    def __init__(self, path: str, mark_cycles: bool = False,
                 clock: Optional[Any] = None, rank: Optional[int] = None,
                 flush_interval: float = 1.0):
        self.path = path
        self.mark_cycles = mark_cycles
        self.clock = clock  # ClockSync (or anything with .offset/.meta())
        self.rank = rank
        self.flush_interval = max(0.05, float(flush_interval))
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._pids: Dict[str, int] = {}
        self._next_pid = 1
        # Monotonic wall anchor: wall time sampled once, advanced by the
        # perf counter — immune to wall-clock steps mid-run; the clock
        # offset (re-measured by the publisher) is applied per event.
        self._wall0 = time.time()
        self._perf0 = time.perf_counter_ns()
        self._lock = threading.Lock()
        self._chunk: Optional[List[dict]] = None  # enable_publish() arms
        self._chunk_dropped = 0
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._closed = False
        self._epoch_us = self.now_us()  # this rank's local-file zero
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._writer.start()

    # ------------------------------------------------------------- internals
    def now_us(self) -> float:
        """Absolute aligned µs: wall clock + measured server offset."""
        wall = self._wall0 + (time.perf_counter_ns() - self._perf0) / 1e9
        offset = getattr(self.clock, "offset", 0.0) if self.clock else 0.0
        return (wall + offset) * 1e6

    def _pid(self, lane: str) -> int:
        with self._lock:
            pid = self._pids.get(lane)
            if pid is None:
                pid = self._next_pid
                self._next_pid += 1
                self._pids[lane] = pid
                self._q.put({"name": "process_name", "ph": "M", "pid": pid,
                             "args": {"name": lane}})
            return pid

    def _emit(self, lane: str, ev: dict) -> None:
        """Route one event to the local writer (pid-mapped, epoch-relative)
        and, when publishing is armed, to the pending chunk (lane-tagged,
        absolute aligned ts — the mergeable form)."""
        local = dict(ev)
        local["pid"] = self._pid(lane)
        local.setdefault("tid", 0)
        self._q.put(local)
        if self._chunk is not None:
            with self._lock:
                if self._chunk is not None:
                    self._chunk.append(dict(ev, lane=lane))
                    if len(self._chunk) > MAX_PENDING_CHUNK_EVENTS:
                        self._chunk.pop(0)
                        self._chunk_dropped += 1

    def _write_loop(self) -> None:
        last_flush = time.monotonic()
        dirty = False
        while True:
            try:
                ev = self._q.get(timeout=self.flush_interval)
            except queue.Empty:
                ev = False  # idle tick: flush only
            try:
                if ev is None:
                    break
                if ev is not False:
                    out = dict(ev)
                    if "ts" in out:
                        # local file is relative to this rank's start
                        out["ts"] = out["ts"] - self._epoch_us
                    if not self._first:
                        self._file.write(",\n")
                    self._first = False
                    self._file.write(json.dumps(out))
                    dirty = True
                now = time.monotonic()
                if dirty and now - last_flush >= self.flush_interval:
                    # Crash safety: a killed rank keeps everything up to
                    # the last flush; Perfetto/Chrome tolerate the
                    # missing "]" (load_trace_events repairs it).
                    self._file.flush()
                    last_flush = now
                    dirty = False
            except (ValueError, OSError):
                break  # file closed under us (atexit ordering); stop
        try:
            self._file.write("\n]\n")
            self._file.close()
        except (ValueError, OSError):
            pass

    # ------------------------------------------------------------ public API
    def begin(self, tensor_name: str, activity: str,
              ts_us: Optional[float] = None) -> None:
        """Begin an activity phase for a tensor (B event)."""
        self._emit(collapse_name(tensor_name),
                   {"name": activity, "ph": "B",
                    "ts": ts_us if ts_us is not None else self.now_us()})

    def end(self, tensor_name: str, activity: str,
            ts_us: Optional[float] = None) -> None:
        self._emit(collapse_name(tensor_name),
                   {"name": activity, "ph": "E",
                    "ts": ts_us if ts_us is not None else self.now_us()})

    def record_op(self, tensor_name: str, op_type: str, size: int,
                  duration_us: Optional[float] = None,
                  ts_us: Optional[float] = None) -> None:
        """Complete (X) event for one collective execution.

        With ``duration_us`` and no explicit ``ts_us`` the span is
        anchored at its START (now - duration): callers measure latency
        from before dispatch and report at completion, and the span must
        render where the op ran, not after it."""
        dur = duration_us if duration_us is not None else 1.0
        if ts_us is None:
            ts_us = self.now_us()
            if duration_us is not None:
                ts_us -= duration_us
        self._emit(collapse_name(tensor_name),
                   {"name": op_type, "ph": "X", "ts": ts_us, "dur": dur,
                    "args": {"size": int(size)}})

    def record_span(self, lane: str, name: str, duration_us: float,
                    args: Optional[dict] = None,
                    ts_us: Optional[float] = None) -> None:
        """Complete (X) event with arbitrary args on a named lane —
        :meth:`record_op` generalized for non-collective planes (the
        serving engine's per-request NEGOTIATE/PREFILL/DECODE phases
        ride this, args carrying the request id; docs/serving.md).
        Without an explicit ``ts_us`` the span is anchored at its START
        (now - duration), matching record_op's measured-at-completion
        convention."""
        if ts_us is None:
            ts_us = self.now_us() - duration_us
        ev = {"name": name, "ph": "X", "ts": ts_us,
              "dur": float(duration_us)}
        if args:
            ev["args"] = dict(args)
        self._emit(collapse_name(lane), ev)

    def instant(self, lane: str, name: str,
                args: Optional[dict] = None,
                ts_us: Optional[float] = None) -> None:
        """Named instant event on a lane (chaos faults, plane markers)."""
        ev = {"name": name, "ph": "i", "s": "p",
              "ts": ts_us if ts_us is not None else self.now_us()}
        if args:
            ev["args"] = dict(args)
        self._emit(lane, ev)

    def native_event(self, ts_us: float, phase: str, cat: str, name: str,
                     arg: int) -> None:
        """One csrc TraceRing event, already rebased to absolute aligned
        µs by the drainer.  Lanes follow the category: controller cycle
        phases, transport frames, chaos faults."""
        lane = _NATIVE_LANES.get(cat, "native")
        if phase == "i":
            self.instant(lane, name, args={"arg": int(arg)}, ts_us=ts_us)
        else:
            ev = {"name": name, "ph": phase, "ts": ts_us}
            if arg:
                ev["args"] = {"arg": int(arg)}
            self._emit(lane, ev)

    def mark_cycle(self) -> None:
        """Negotiation-cycle tick (reference: HOROVOD_TIMELINE_MARK_CYCLES,
        operations.cc:442-445)."""
        if self.mark_cycles:
            self.instant("controller", "CYCLE")

    # ------------------------------------------------------------ publishing
    def enable_publish(self) -> None:
        """Arm the chunk buffer consumed by :class:`TimelinePublisher`."""
        with self._lock:
            if self._chunk is None:
                self._chunk = []

    def drain_chunk(self) -> List[dict]:
        """Consume buffered lane-tagged events (absolute aligned ts)."""
        with self._lock:
            if not self._chunk:
                return []
            out, self._chunk = self._chunk, []
            return out

    def clock_meta(self) -> dict:
        if self.clock is not None and hasattr(self.clock, "meta"):
            return self.clock.meta()
        return {"offset": 0.0, "uncertainty": None, "synced": False}

    def flush(self) -> None:
        """Best-effort synchronous flush of events already queued (the
        writer thread also flushes on its own cadence)."""
        deadline = time.monotonic() + 2.0
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)
        try:
            self._file.flush()
        except (ValueError, OSError):
            pass

    def close(self) -> None:
        """Idempotent; safe under any atexit ordering (a second close, a
        close after the writer died, a close racing interpreter teardown
        all no-op rather than raise)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._q.put(None)
            self._writer.join(timeout=5)
        except (RuntimeError, ValueError, OSError):
            pass


# ------------------------------------------------------------ module helpers
def trace_instant(lane: str, name: str, args: Optional[dict] = None) -> None:
    """Emit an instant on the active runtime's timeline; no-op without an
    initialized runtime or an active timeline.  The one-line hook the
    plane modules (ops/wire.py, ops/overlap.py, parallel/zero.py, chaos)
    call without owning timeline plumbing."""
    try:
        from .. import runtime as _rt
        if not _rt.is_initialized():
            return
        tl = _rt.get().timeline
        if tl is not None:
            tl.instant(lane, name, args=args)
    except Exception:
        pass  # tracing must never take the job down


def load_trace_events(path: str) -> List[dict]:
    """Load a timeline file, tolerating the truncation a killed rank
    leaves (no closing bracket, possibly a torn last line) — the repair
    Chrome/Perfetto apply implicitly, made explicit for tools/tests."""
    with open(path) as f:
        text = f.read().strip()
    try:
        return json.loads(text)
    except ValueError:
        pass
    body = text[1:] if text.startswith("[") else text
    events: List[dict] = []
    for line in body.splitlines():
        line = line.strip().rstrip(",")
        if not line or line in ("]",):
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            continue  # torn tail line from the kill
    return events


# pid namespacing for replica fleets: replica 0 keeps pid == rank (the
# single-fleet byte-compat contract), replica K's rank N renders at
# K * _REPLICA_PID_STRIDE + N — disjoint for any realistic fleet size.
_REPLICA_PID_STRIDE = 10000


def merge_timeline_chunks(items: Dict[str, bytes]) -> dict:
    """Render KV scope ``timeline`` chunks as one Chrome/Perfetto JSON
    object: each (replica, rank) becomes a pid process lane — bare
    ``rank N`` for replica 0 (single-fleet byte-compat),
    ``replica{K}.rank{N}`` for replica K's chunks (docs/timeline.md) —
    each event lane a tid within it, all timestamps on the shared
    aligned epoch normalized to the earliest event.  Per-rank clock
    offset/uncertainty ride the metadata so readers know how much
    cross-rank skew to trust."""
    per_rank: Dict[Tuple[int, int], List[dict]] = {}
    clocks: Dict[Tuple[int, int], dict] = {}
    for key in sorted(items):
        try:
            chunk = json.loads(items[key])
        except (ValueError, TypeError):
            continue  # a torn PUT must not break the whole merge
        r = int(chunk.get("rank", -1))
        rep = int(chunk.get("replica", 0) or 0)
        per_rank.setdefault((rep, r), []).extend(chunk.get("events", []))
        if isinstance(chunk.get("clock"), dict):
            clocks[(rep, r)] = chunk["clock"]
    all_ts = [ev["ts"] for evs in per_rank.values() for ev in evs
              if isinstance(ev.get("ts"), (int, float))]
    t0 = min(all_ts) if all_ts else 0.0
    meta_events: List[dict] = []
    events: List[dict] = []
    for rep, r in sorted(per_rank):
        pid = r if rep == 0 else rep * _REPLICA_PID_STRIDE + r
        lane = f"rank {r}" if rep == 0 else f"replica{rep}.rank{r}"
        meta_events.append({"name": "process_name", "ph": "M", "pid": pid,
                            "args": {"name": lane}})
        if (rep, r) in clocks:
            meta_events.append({"name": "clock_sync", "ph": "M",
                                "pid": pid, "args": clocks[(rep, r)]})
        tids: Dict[str, int] = {}
        for ev in per_rank[(rep, r)]:
            ev_lane = str(ev.get("lane", "misc"))
            tid = tids.get(ev_lane)
            if tid is None:
                tid = len(tids)
                tids[ev_lane] = tid
                meta_events.append({"name": "thread_name", "ph": "M",
                                    "pid": pid, "tid": tid,
                                    "args": {"name": ev_lane}})
            out = {k: v for k, v in ev.items() if k != "lane"}
            out["pid"] = pid
            out["tid"] = tid
            if isinstance(out.get("ts"), (int, float)):
                out["ts"] = out["ts"] - t0
            events.append(out)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": meta_events + events,
            "metadata": {"epoch_us": t0,
                         "clock_sync": {
                             (str(r) if rep == 0 else f"r{rep}.{r}"): c
                             for (rep, r), c in sorted(clocks.items())}}}


# --------------------------------------------------------------- publishing
class TimelinePublisher:
    """Background thread PUT-ing compacted trace chunks to the rendezvous
    KV (scope ``timeline``, key ``rank.N.SEQ``) so the driver can serve
    ``GET /timeline`` and write ``--timeline-merge``.  Mirrors
    MetricsPublisher (utils/metrics.py); additionally re-measures the
    clock offset each publish so alignment tracks drift.  A final publish
    happens on close() so the merge sees the tail of the run."""

    SCOPE = TIMELINE_KV_SCOPE

    def __init__(self, addr: str, port: int, rank: int, timeline: Timeline,
                 interval: float = 5.0, clock: Optional[Any] = None,
                 replica: int = 0):
        self.addr = addr
        self.port = int(port)
        self.rank = int(rank)
        # Replica-fleet lane namespacing (docs/timeline.md): nonzero
        # replica ids stamp the chunks so merge_timeline_chunks renders
        # replica{K}.rank{N} process lanes instead of colliding pids.
        self.replica = int(replica)
        self.interval = max(0.1, float(interval))
        self.timeline = timeline
        self.clock = clock
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        timeline.enable_publish()
        if self.addr and self.port:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def publish_now(self) -> bool:
        if not (self.addr and self.port):
            return False
        try:
            if self.clock is not None:
                self.clock.measure()  # periodic drift re-measurement
            events = self.timeline.drain_chunk()
            if not events:
                return True
            chunk = {"rank": self.rank, "seq": self._seq,
                     "clock": self.timeline.clock_meta(),
                     "events": events}
            key = f"rank.{self.rank}.{self._seq:06d}"
            if self.replica:
                chunk["replica"] = self.replica
                key = f"r{self.replica:02d}.{key}"
            from ..runner.http_client import put_kv
            put_kv(self.addr, self.port, self.SCOPE, key,
                   json.dumps(chunk).encode())
            self._seq += 1
            return True
        except Exception:
            return False  # tracing must never take the job down

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.publish_now()

    def close(self) -> None:
        self._stop.set()
        self.publish_now()


class NativeTraceDrainer:
    """Background pump from the C++ core's span ring into the timeline
    writer thread (csrc/trace.h -> hvd_core_trace -> Timeline).

    Ring timestamps are steady-clock µs since ring construction; each
    drain's header carries ``now_us`` in the same clock, so the drainer
    rebases: ring_epoch = aligned_now - now_us, event = ring_epoch + ts.
    """

    def __init__(self, core: Any, timeline: Timeline,
                 interval: float = 0.5):
        self.core = core
        self.timeline = timeline
        self.interval = max(0.05, float(interval))
        self._stop = threading.Event()
        core.trace_enable()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def drain_once(self) -> int:
        try:
            d = self.core.trace_drain()
        except Exception:
            return 0  # core closing; the drainer must not crash teardown
        ring_epoch = self.timeline.now_us() - d["now_us"]
        for ts, phase, cat, name, arg in d["events"]:
            self.timeline.native_event(ring_epoch + ts, phase, cat, name,
                                       arg)
        if d["dropped"]:
            self.timeline.instant("controller", "trace.ring.dropped",
                                  args={"total": d["dropped"]})
        return len(d["events"])

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.drain_once()

    def close(self) -> None:
        """Stop the pump after one final drain (call while the native
        core is still alive)."""
        if self._stop.is_set():
            return
        self._stop.set()
        self.drain_once()
