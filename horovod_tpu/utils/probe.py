"""Fast, killable TPU-backend reachability probe.

A down TPU tunnel makes jax backend init hang for tens of minutes, and
no in-process watchdog can interrupt it (the hang sits inside the PJRT
C API).  A SUBPROCESS can be killed — so the probe initializes the
backend in a child with a hard timeout and reports what it saw.  Used by
``bench.py``'s supervisor and exposed as ``horovod_tpu.probe_backend``
for interactive sessions ("is the tunnel up before I call init()?").
"""

from __future__ import annotations

import json
import subprocess
import sys


def probe_backend(timeout_s: float = 55.0) -> str:
    """Returns '' when an accelerator backend is reachable, else a
    human-readable reason (probe timeout, init error, or cpu-only
    fallback)."""
    code = ("import jax, json, sys; ds = jax.devices(); "
            "print(json.dumps([str(d.platform) for d in ds]))")
    try:
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return (f"TPU backend unreachable: device probe exceeded "
                f"{timeout_s:.0f}s (tunnel likely down)")
    if res.returncode != 0:
        tail = (res.stderr or "").strip().splitlines()[-3:]
        detail = " | ".join(tail) if tail else "no stderr (killed?)"
        return (f"TPU backend probe failed (rc={res.returncode}): "
                f"{detail}")
    try:
        platforms = json.loads((res.stdout or "").strip().splitlines()[-1])
    except (ValueError, IndexError):
        return "TPU backend probe printed no platform list"
    if all(p == "cpu" for p in platforms):
        # A mis-registered plugin silently falls back to CPU; callers
        # that expect hardware should treat this as unhealthy.
        return f"TPU expected but jax only sees platforms {platforms}"
    return ""
