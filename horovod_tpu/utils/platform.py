"""Deterministic CPU-platform forcing for smoke modes and tests.

On TPU images a site customization force-registers the hardware backend
by updating the ``jax_platforms`` *config*, which takes precedence over
the ``JAX_PLATFORMS`` environment variable.  A script that only sets the
env var therefore still lands on the hardware backend — and with the
device tunnel down, backend init blocks for many minutes with no
interruptible point (round-3 post-mortem: a 900 s example-test timeout).

``force_cpu()`` sets BOTH the env var (inherited by spawned workers,
rescued by ``Runtime.__init__``) and the jax config (wins in THIS
process even against site customization).  Call it before any other
jax-touching import (keras, flax, ...).

Reference analog: the reference pins devices per process via
``CUDA_VISIBLE_DEVICES`` at spawn time (horovod/runner/gloo_run.py);
on TPU the equivalent per-process pinning must go through jax's config
because env alone does not bind the backend.
"""

from __future__ import annotations

import os


def force_cpu(virtual_chips: int | None = None) -> None:
    """Force this process (and spawned children) onto the CPU backend.

    ``virtual_chips`` additionally requests N virtual CPU devices via
    XLA's host-platform device-count flag (the smoke-mode mesh every
    example uses); an existing device-count flag in ``XLA_FLAGS`` wins,
    so launcher-provided settings are never clobbered.

    Safe to call multiple times; raises RuntimeError if a non-CPU
    backend was already initialized (the caller ran too late to be a
    CPU-only process — surfacing that beats hanging on a dead tunnel).
    """
    if virtual_chips:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{virtual_chips}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    # For THIS process the config update below is what binds the backend
    # (site customization already ran at interpreter start); popping the
    # customization's trigger var protects CHILD processes, which would
    # otherwise re-register the hardware backend at their own start.
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    if jax.config.jax_platforms != "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception as e:  # backends already initialized
            raise RuntimeError(
                "force_cpu() called after a non-cpu jax backend "
                "initialized; call it before any jax-touching import"
            ) from e


def apply_env_platform() -> None:
    """Make the jax config match an EXPLICIT ``JAX_PLATFORMS`` env var.

    Spawned workers inherit the parent's env but not its jax config; on
    an image whose site customization pins the config to hardware, the
    inherited env var alone is dead weight.  Task-entry shims (spark
    runner, launcher exec paths) call this BEFORE unpickling the user
    fn, because unpickling imports the fn's module — which may import
    keras/flax and initialize the wrong backend.  No-op when the env var
    is unset (hardware runs stay untouched).
    """
    plat = os.environ.get("JAX_PLATFORMS", "")
    if not plat:
        return
    import jax

    if jax.config.jax_platforms != plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass  # backends already up; Runtime.__init__ will warn
