"""horovod_tpu.utils subpackage."""
