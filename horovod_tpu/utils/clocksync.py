"""Cross-rank clock alignment for the distributed tracing plane.

Two ranks' trace events can only be laid side by side if their timestamps
share an epoch; host wall clocks on a pod slice can disagree by
milliseconds to seconds, which is the same order as the events being
traced.  This module runs an NTP-style offset/RTT handshake against the
rendezvous KV server's ``GET /clock`` route (runner/http_server.py) at
``hvd.init`` — and again on every trace-chunk publish (utils/timeline.py
TimelinePublisher), so drift over a long job stays bounded.

The estimator is the classic minimum-RTT filter: each probe yields

    offset_i      = server_time_i - (t0_i + t1_i) / 2
    uncertainty_i = (t1_i - t0_i) / 2          # the RTT half-window

and the probe with the smallest RTT wins — queueing delay only ever
*adds* to RTT, so the fastest exchange is the most symmetric one.  The
measured offset and its uncertainty ride the trace chunks as metadata:
the merged timeline (``GET /timeline``) reports per-rank uncertainty so
a reader knows how much cross-rank skew to trust.

Ranks that cannot reach the server (standalone init, server gone) fall
back to offset 0 with infinite uncertainty — local tracing keeps working,
only the cross-rank alignment claim is withdrawn.
"""

from __future__ import annotations

import math
import time
import urllib.request
from typing import List, Optional, Tuple

# (local send time, server time, local receive time) of one probe.
Sample = Tuple[float, float, float]


def best_offset(samples: List[Sample]) -> Tuple[float, float]:
    """(offset, uncertainty) seconds from probe samples: the minimum-RTT
    sample's midpoint offset, uncertainty = that sample's RTT/2.  Pure
    function so the rebase math is unit-testable with synthetic skew."""
    best: Optional[Tuple[float, float]] = None
    for t0, server, t1 in samples:
        rtt = t1 - t0
        if rtt < 0:
            continue  # clock stepped mid-probe; unusable
        offset = server - (t0 + t1) / 2.0
        if best is None or rtt / 2.0 < best[1]:
            best = (offset, rtt / 2.0)
    if best is None:
        return 0.0, math.inf
    return best


class ClockSync:
    """One rank's live clock-offset estimate against the rendezvous
    server (offset is SERVER minus LOCAL wall seconds: aligned time =
    local + offset)."""

    def __init__(self, addr: str, port: int, samples: int = 5,
                 timeout: float = 2.0, measure_now: bool = True):
        self.addr = addr
        self.port = int(port)
        self.samples = int(samples)
        self.timeout = float(timeout)
        self.offset = 0.0
        self.uncertainty = math.inf
        self.synced = False
        if measure_now:
            self.measure()

    def _probe(self) -> Sample:
        url = f"http://{self.addr}:{self.port}/clock"
        t0 = time.time()
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            server = float(resp.read().decode())
        t1 = time.time()
        return (t0, server, t1)

    def measure(self) -> bool:
        """Re-estimate the offset; False (estimate unchanged) when the
        server is unreachable — alignment is tooling, never a job
        failure."""
        if not (self.addr and self.port):
            return False
        probes: List[Sample] = []
        for _ in range(self.samples):
            try:
                probes.append(self._probe())
            except Exception:
                continue
        if not probes:
            return False
        self.offset, self.uncertainty = best_offset(probes)
        self.synced = True
        return True

    def meta(self) -> dict:
        """JSON-able alignment metadata for trace chunks / merge output."""
        return {"offset": self.offset,
                "uncertainty": (None if math.isinf(self.uncertainty)
                                else self.uncertainty),
                "synced": self.synced}
