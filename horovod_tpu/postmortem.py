"""Postmortem plane: crash forensics for a fleet that died.

PR 1 (metrics) answers "how is the run doing", PR 5 (tracing) "where did
the time go"; this module answers "why did the run die".  Three pieces
cooperate (docs/postmortem.md):

  * csrc/postmortem.cc — the native **flight recorder**: fatal-signal /
    std::terminate handlers (plus an explicit ``hvd_core_flight_dump``)
    write a versioned flight-record file with the trace-ring tail,
    metrics snapshot, tensor-queue/transport state and last-progress
    cycle stamp.  :func:`parse_flight_record` reads it back.
  * utils/health.py — per-rank **heartbeats** on the aligned fleet clock
    (KV scope ``health``, served at ``GET /health``), plus the
    launcher-side :class:`~horovod_tpu.utils.health.HealthMonitor`.
  * this module — the **postmortem.json** builder the launcher runs on
    abnormal exit (:func:`build_postmortem`): per-rank exit taxonomy,
    collected flight records, log tails, condensed final metrics, and
    the fleet-clock-ordered last events, topped by a suspect
    classification.  ``hvdrun doctor`` renders it root-cause-first
    (runner/doctor.py).

The suspect taxonomy is closed — kill / stall / kv_blackout / transport
/ torn_commit / unknown — mirroring the chaos plane's fault kinds
(docs/chaos.md), which is also how it is verified: a chaos-injected
fault must come back out of the postmortem with the right rank and name.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import time
from typing import Any, Dict, List, Optional, Tuple

from .utils.health import record_step  # noqa: F401  (public step hook)

SCHEMA = "hvd-postmortem-v1"
FLIGHT_HEADER = "hvd_flight_v"

# Closed suspect taxonomy (docs/postmortem.md#taxonomy).
SUSPECTS = ("kill", "stall", "kv_blackout", "transport", "torn_commit",
            "oom", "unknown")

# SIGKILL arrives as rc -9 from the launcher's waitpid or as the shell
# convention 128+9 when a wrapper re-reported it.
_SIGKILL_RCS = (-9, 137)


def _mem_watermark(heartbeat: Optional[Dict[str, Any]]) -> Optional[float]:
    """The device-memory watermark the final heartbeat carried (the
    memory plane stamps it, utils/health.py), or None."""
    mem = (heartbeat or {}).get("mem") or {}
    wm = mem.get("watermark")
    try:
        return float(wm) if wm is not None else None
    except (TypeError, ValueError):
        return None


def _pressure_threshold() -> float:
    try:
        from .common.knobs import current
        return float(current("HOROVOD_MEM_HIGH_WATERMARK"))
    except Exception:
        return 0.9  # registry default (common/knobs.py)

# The stall inspector's documented hard-exit status (utils/stall.py).
STALL_SHUTDOWN_EXIT = 42


# ------------------------------------------------------------ flight record
def parse_flight_record(path_or_text: str) -> Dict[str, Any]:
    """Parse a native flight record (csrc/postmortem.cc WriteFlightRecord).

    Accepts a file path or the raw text.  Returns ``{"version", "reason",
    "rank", "size", "now_us", "health": {...}, "metrics": {...},
    "trace": [(ts_us, phase, cat, name, arg), ...], "trace_dropped",
    "complete"}`` — ``complete`` is False when the ``[end]`` marker is
    missing (the write was torn by the crash it was recording).  Unknown
    keys and sections are ignored, mirroring the hvd_core_metrics
    versioning contract."""
    if "\n" not in path_or_text and os.path.exists(path_or_text):
        with open(path_or_text) as f:
            text = f.read()
    else:
        text = path_or_text
    lines = text.splitlines()
    if not lines or not lines[0].startswith(FLIGHT_HEADER):
        raise ValueError(
            f"not a flight record (want '{FLIGHT_HEADER}N' header): "
            f"{lines[:1]!r}")
    out: Dict[str, Any] = {
        "version": int(lines[0].split(FLIGHT_HEADER, 1)[1]),
        "reason": "?", "rank": -1, "size": 0, "now_us": 0,
        "health": {}, "metrics": {}, "trace": [], "trace_dropped": 0,
        "complete": False,
    }
    section = ""
    for line in lines[1:]:
        line = line.strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            section = line[1:-1]
            if section == "end":
                out["complete"] = True
            continue
        parts = line.split()
        if section == "trace":
            if parts[0] == "trace_dropped" and len(parts) == 2:
                out["trace_dropped"] = int(parts[1])
            elif len(parts) >= 5:
                try:
                    out["trace"].append((int(parts[0]), parts[1], parts[2],
                                         parts[3], int(parts[4])))
                except ValueError:
                    continue  # torn tail line from the crash
        elif section in ("health", "metrics"):
            if len(parts) == 2:
                try:
                    out[section][parts[0]] = int(parts[1])
                except ValueError:
                    continue
        elif not section:  # header
            if parts[0] == "reason":
                out["reason"] = line.split(" ", 1)[1] if len(parts) > 1 \
                    else "?"
            elif len(parts) == 2:
                try:
                    out[parts[0]] = int(parts[1])
                except ValueError:
                    continue
    return out


# ------------------------------------------------------------ exit taxonomy
def classify_exit(rc: Optional[int], by_launcher: bool = False,
                  supervision_cause: Optional[str] = None,
                  heartbeat: Optional[Dict[str, Any]] = None) -> str:
    """One worker exit -> taxonomy label.

    ``supervision_cause`` ("stall" / "heartbeat-lost") wins: when the
    launcher itself killed the worker on a verdict, the SIGABRT it died
    of is the cure, not the disease.  ``by_launcher`` marks fail-fast
    terminations of SURVIVORS after another rank failed — collateral,
    never the first failure.  rc 42 is the stall inspector's documented
    hard-exit status (utils/stall.py).

    ``heartbeat`` is the rank's FINAL heartbeat: a SIGKILL/rc-137 exit
    whose heartbeat carried a device-memory watermark at or above the
    pressure threshold classifies as suspected ``oom`` — the kernel's
    OOM killer sends exactly that signal, and the memory plane put the
    evidence on the wire before dying (docs/memory.md#oom)."""
    if supervision_cause:
        return supervision_cause
    if by_launcher:
        return "terminated"
    if rc is None:
        return "unknown"
    if rc == 0:
        return "clean"
    if rc in _SIGKILL_RCS:
        wm = _mem_watermark(heartbeat)
        if wm is not None and wm >= _pressure_threshold():
            return "oom"
    if rc < 0:
        try:
            return f"signal:{_signal.Signals(-rc).name}"
        except ValueError:
            return f"signal:{-rc}"
    if rc == STALL_SHUTDOWN_EXIT:
        return "stall"
    return f"error:{rc}"


_COLLATERAL = ("clean", "terminated")


def _condense_metrics(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """The postmortem-relevant slice of a full metrics snapshot: chaos
    injections by kind, transport resilience counters, stall warnings.
    Full snapshots carry every histogram bucket — too heavy to embed
    per rank in an artifact meant for humans."""
    fams = snapshot.get("families", {})

    def total(name: str) -> float:
        return sum(s.get("value", 0)
                   for s in fams.get(name, {}).get("samples", []))

    chaos: Dict[str, float] = {}
    for s in fams.get("hvd_chaos_injections_total", {}).get("samples", []):
        kind = s.get("labels", {}).get("kind")
        if kind and s.get("value"):
            chaos[kind] = s["value"]
    return {
        "chaos_injections": chaos,
        "chaos_faults_native": total("hvd_chaos_faults_native_total"),
        "transport_reconnects": total("hvd_transport_reconnects_total"),
        "transport_reconnect_failures": total(
            "hvd_transport_reconnect_failures_total"),
        "stall_warnings": total("hvd_stall_warnings_total"),
    }


# -------------------------------------------------------------- suspect
def classify_suspect(info: Dict[str, Any]) -> Tuple[str, List[str]]:
    """(classification, evidence) for ONE rank's collected forensics
    (the ``ranks[r]`` shape build_postmortem assembles).  Precedence runs
    most-specific-first: a torn commit also looks like a kill, a chaos
    kill also exits nonzero — the closed taxonomy keeps the verdict
    deterministic."""
    cls = info.get("exit", {}).get("classification", "unknown")
    tail = (info.get("log_tail") or "").lower()
    fr = info.get("flight_record") or {}
    met = info.get("metrics") or {}
    chaos = met.get("chaos_injections", {})

    if "crash_commit" in tail or "chaos: crashing rank" in tail \
            or chaos.get("crash_commit"):
        return "torn_commit", ["log/metrics show a crash injected inside "
                               "a fastcommit window"]
    if "kv blackout" in tail or "kv_blackout" in tail \
            or chaos.get("kv_blackout"):
        return "kv_blackout", ["log/metrics show rendezvous-KV operations "
                               "failing before the exit"]
    if cls == "oom":
        wm = _mem_watermark(info.get("heartbeat"))
        return "oom", [
            "SIGKILL with the final heartbeat's device-memory watermark "
            f"at {wm:.0%} of the cap — the kernel OOM-killer signature "
            "(docs/memory.md#oom)" if wm is not None else
            "SIGKILL with memory pressure in the final heartbeat"]
    if cls in ("stall", "heartbeat-lost"):
        return "stall", [f"supervision verdict: {cls} beyond the "
                         "heartbeat timeout"]
    if fr.get("metrics", {}).get("transport_reconnect_failures") \
            or fr.get("health", {}).get("transport_healthy") == 0 \
            or "controller transport failure" in tail:
        return "transport", ["flight record / log shows the controller "
                             "transport dead (retry budget exhausted or "
                             "peer gone)"]
    if cls.startswith("signal:") or "chaos: killing rank" in tail \
            or chaos.get("kill"):
        ev = [f"exit classification {cls}"]
        if "chaos: killing rank" in tail or chaos.get("kill"):
            ev.append("chaos injector logged the kill")
        return "kill", ev
    return "unknown", [f"exit classification {cls} matches no known "
                       "failure signature"]


# --------------------------------------------------------------- builder
def _flight_events_wall(rank: int, fr: Dict[str, Any],
                        hb: Optional[Dict[str, Any]],
                        limit: int = 10) -> List[Dict[str, Any]]:
    """Map the flight record's ring-relative trace tail onto the fleet
    clock.  Anchor: the heartbeat carries BOTH the aligned wall time and
    the core's ring clock (``core.now_us``) sampled together, so
    ring_epoch_wall = hb.time - hb.core.now_us/1e6 and every span maps
    to wall seconds.  Without a heartbeat-borne anchor the spans stay
    unmapped (listed in the rank detail, absent from the timeline)."""
    core = (hb or {}).get("core") or {}
    if not fr.get("trace") or not core.get("now_us") or not (hb or {}).get(
            "time"):
        return []
    epoch = float(hb["time"]) - float(core["now_us"]) / 1e6
    out = []
    for ts, phase, cat, name, arg in fr["trace"][-limit:]:
        out.append({"t": epoch + ts / 1e6, "rank": rank, "kind": "span",
                    "name": name, "phase": phase, "cat": cat, "arg": arg})
    return out


def build_postmortem(job: Dict[str, Any],
                     exits: Dict[int, Dict[str, Any]],
                     health_view: Optional[Dict[str, Any]] = None,
                     flight_records: Optional[Dict[int, Dict[str, Any]]]
                     = None,
                     log_tails: Optional[Dict[int, str]] = None,
                     metric_snapshots: Optional[Dict[int, Dict[str, Any]]]
                     = None) -> Dict[str, Any]:
    """Assemble postmortem.json from everything the launcher collected.

    ``exits``: rank -> {"rc", "time" (fleet wall seconds), "by_launcher",
    "cause" (supervision verdict, optional)}.  ``health_view`` is the
    fleet_health() shape; flight records are already parsed dicts.  The
    returned object is self-contained: ``hvdrun doctor`` renders it with
    no access to the dead job."""
    health_ranks = (health_view or {}).get("ranks", {})
    ranks: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    for r in sorted(exits):
        e = exits[r]
        hb_info = health_ranks.get(str(r)) or {}
        hb = hb_info.get("heartbeat")
        classification = classify_exit(e.get("rc"),
                                       bool(e.get("by_launcher")),
                                       e.get("cause"), heartbeat=hb)
        fr = (flight_records or {}).get(r)
        snap = (metric_snapshots or {}).get(r)
        info: Dict[str, Any] = {
            "exit": {"rc": e.get("rc"), "time": e.get("time"),
                     "by_launcher": bool(e.get("by_launcher")),
                     "classification": classification},
            "heartbeat": hb,
            "heartbeat_age_s": hb_info.get("age_s"),
            "flight_record": fr,
            "log_tail": (log_tails or {}).get(r),
            "metrics": _condense_metrics(snap) if snap else None,
        }
        ranks[str(r)] = info
        if e.get("time") is not None:
            events.append({"t": e["time"], "rank": r, "kind": "exit",
                           "name": classification})
        if hb and hb.get("time") is not None:
            events.append({"t": hb["time"], "rank": r, "kind": "heartbeat",
                           "name": f"step={hb.get('step')}"})
        if fr:
            events.extend(_flight_events_wall(r, fr, hb))
    events.sort(key=lambda ev: ev["t"])

    failures = [(info["exit"]["time"], int(r)) for r, info in ranks.items()
                if info["exit"]["classification"] not in _COLLATERAL
                and info["exit"]["time"] is not None]
    first_failure = None
    suspect: Dict[str, Any] = {"rank": None, "classification": "unknown",
                               "evidence": []}
    if failures:
        _, first_rank = min(failures)
        first_failure = {
            "rank": first_rank,
            "time": ranks[str(first_rank)]["exit"]["time"],
            "classification": ranks[str(first_rank)]["exit"]
            ["classification"],
        }
        # OOM suspects by pressure, not by time: the kernel kills the
        # biggest consumer, and exit times race — the rank whose final
        # heartbeat sat highest above the watermark is the one that
        # blew the cap (docs/memory.md#oom).
        oom_ranks = [int(r) for r, info in ranks.items()
                     if info["exit"]["classification"] == "oom"]
        if oom_ranks:
            suspect_rank = max(
                oom_ranks,
                key=lambda r: _mem_watermark(
                    ranks[str(r)].get("heartbeat")) or 0.0)
        else:
            suspect_rank = first_rank
        classification, evidence = classify_suspect(
            ranks[str(suspect_rank)])
        suspect = {"rank": suspect_rank, "classification": classification,
                   "evidence": evidence}
    return {
        "schema": SCHEMA,
        "created": time.time(),
        "job": job,
        "ranks": ranks,
        "first_failure": first_failure,
        "suspect": suspect,
        "events": events,
    }


def write_postmortem(pm: Dict[str, Any], path: str) -> str:
    with open(path, "w") as f:
        json.dump(pm, f, indent=1)
    return path


def load_postmortem(path: str) -> Dict[str, Any]:
    """Load postmortem.json; accepts the file or the directory holding
    it (the hvdrun --postmortem DIR)."""
    if os.path.isdir(path):
        path = os.path.join(path, "postmortem.json")
    with open(path) as f:
        pm = json.load(f)
    if pm.get("schema") != SCHEMA:
        raise ValueError(f"{path}: schema {pm.get('schema')!r} is not "
                         f"{SCHEMA}")
    return pm
