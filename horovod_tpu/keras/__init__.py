"""Keras frontend: data-parallel training with Keras 3 on the JAX backend.

Mirrors the reference's Keras binding (reference: horovod/keras/__init__.py,
horovod/tensorflow/keras/__init__.py, horovod/_keras/__init__.py):

    import horovod_tpu.keras as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(lr * hvd.size()))
    model.compile(optimizer=opt, ...)
    model.fit(..., callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
    ])

TPU-native design: the worker unit is the *process* (one Keras replica per
host process, replicated across its local chips), gradients are synchronized
with the framework's eager fused collectives.  For whole-mesh in-process
data parallelism — the idiomatic single-controller TPU path with no analog
in the reference — :func:`distribution` wires ``hvd.mesh()`` into
``keras.distribution.DataParallel`` so XLA/GSPMD inserts the gradient
reductions; ``DistributedOptimizer`` then passes traced gradients through
untouched (sync already happened inside the compiled step).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np

import horovod_tpu as _hvd
from horovod_tpu import (init, shutdown, is_initialized, rank, size,  # noqa: F401
                         local_rank, local_size, cross_rank, cross_size,
                         mesh, allreduce, allgather, broadcast,
                         broadcast_object, allgather_object, Compression,
                         ReduceOp, Average, Sum, Adasum)
from . import callbacks  # noqa: F401
from . import elastic  # noqa: F401


def _is_traced(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


_wrapped_cache: dict = {}


def _make_distributed_class(base_cls):
    """Build (and cache) a Distributed<Optimizer> subclass of ``base_cls``
    whose ``apply`` allreduces gradients first (reference:
    _keras/__init__.py create_distributed_optimizer: dynamic subclass
    overriding get_gradients/_compute_gradients)."""
    if base_cls in _wrapped_cache:
        return _wrapped_cache[base_cls]

    class _DistributedOptimizer(base_cls):
        _hvd_distributed = True

        def apply(self, grads, trainable_variables=None):
            grads = self._hvd_maybe_allreduce(list(grads))
            if grads is None:  # accumulating a local backward pass
                return
            return super().apply(grads, trainable_variables)

        # ------------------------------------------------- gradient sync
        def _hvd_maybe_allreduce(self, grads):
            if _hvd.size() == 1:
                return grads
            concrete = [g for g in grads if g is not None]
            if concrete and _is_traced(concrete[0]):
                # Inside a jitted train step.  Under an active keras
                # distribution the batch is sharded over the mesh and
                # GSPMD already reduced the gradients — eager sync would
                # double-count.  Within a single process the replica is
                # whole, so skipping is also correct.  But multi-process
                # WITHOUT a distribution would silently train divergent
                # replicas — refuse instead.
                import keras
                if (_hvd.cross_size() > 1
                        and keras.distribution.distribution() is None):
                    raise RuntimeError(
                        "hvd.keras.DistributedOptimizer saw traced "
                        "gradients in a multi-process run with no active "
                        "keras distribution: gradients cannot be "
                        "synchronized from inside the jitted train step. "
                        "Either call keras.distribution.set_distribution("
                        "horovod_tpu.keras.distribution()) before building "
                        "the model, or compile with run_eagerly=True / "
                        "jit_compile=False.")
                return grads
            bps = getattr(self, "_hvd_backward_passes_per_step", 1)
            if bps > 1:
                grads = self._hvd_accumulate(grads)
                if grads is None:
                    return None
            comp = getattr(self, "_hvd_compression", Compression.none)
            idx = [i for i, g in enumerate(grads) if g is not None]
            dense = [grads[i] for i in idx]
            if dense:
                from horovod_tpu.ops.collectives import process_local
                wire, ctxs = zip(*[comp.compress(jax.numpy.asarray(g))
                                   for g in dense])
                # Mark as process-level: a grad dim equal to local_size must
                # not be misread as a per-chip axis.
                reduced = _hvd.grouped_allreduce(
                    [process_local(w) for w in wire],
                    op=getattr(self, "_hvd_op", Average))
            else:
                ctxs, reduced = (), []
            out = list(grads)
            for i, r, c in zip(idx, reduced, ctxs):
                out[i] = comp.decompress(r, c)
            return out

        def _hvd_accumulate(self, grads):
            """Local gradient aggregation over backward_passes_per_step
            calls (reference: tensorflow/gradient_aggregation.py:16,
            torch/optimizer.py backward_passes_per_step)."""
            acc = getattr(self, "_hvd_acc", None)
            if acc is None:
                acc = [None] * len(grads)
            acc = [a if g is None else (g if a is None else a + g)
                   for a, g in zip(acc, grads)]
            self._hvd_counter = getattr(self, "_hvd_counter", 0) + 1
            if self._hvd_counter < self._hvd_backward_passes_per_step:
                self._hvd_acc = acc
                return None
            self._hvd_counter = 0
            self._hvd_acc = None
            n = self._hvd_backward_passes_per_step
            return [None if a is None else a / n for a in acc]

    _DistributedOptimizer.__name__ = "Distributed" + base_cls.__name__
    _wrapped_cache[base_cls] = _DistributedOptimizer
    return _DistributedOptimizer


def DistributedOptimizer(optimizer,
                         name: Optional[str] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = Average):
    """Wrap a Keras optimizer so gradients are averaged across all workers
    before being applied (reference: keras/__init__.py:39
    DistributedOptimizer -> _keras create_distributed_optimizer).

    Returns an instance of a dynamically created subclass of the input
    optimizer's class, rebuilt from its config, so Keras serialization sees
    a regular optimizer.
    """
    cls = _make_distributed_class(optimizer.__class__)
    cfg = optimizer.get_config()
    if name:
        cfg["name"] = name
    dist = cls.from_config(cfg)
    dist._hvd_compression = compression
    dist._hvd_backward_passes_per_step = int(backward_passes_per_step)
    dist._hvd_op = op
    return dist


def sync_trainer_state(model) -> None:
    """Pull live training state back into Keras variables.

    The Keras-JAX trainer purges variable values during an epoch (state
    flows through the jitted step as arrays) and re-fetches from variables
    whenever ``_jax_state_synced`` is set; callbacks must sync before
    reading or writing variables mid-epoch.  No-op outside ``fit``.
    """
    if getattr(model, "_jax_state", None) is not None:
        model.jax_state_sync()


def broadcast_global_variables(model, root_rank: int = 0) -> None:
    """Broadcast model + optimizer variables from ``root_rank`` (reference:
    tensorflow/__init__.py:263 broadcast_global_variables; keras callback
    uses it at batch 0)."""
    sync_trainer_state(model)
    targets = list(getattr(model, "weights", []))
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        targets += list(getattr(opt, "variables", []))
    from horovod_tpu.ops.collectives import process_local
    for v in targets:
        val = np.asarray(v)
        if not np.issubdtype(val.dtype, np.number):
            continue
        out = np.asarray(_hvd.broadcast(process_local(val),
                                        root_rank=root_rank))
        v.assign(out)


def load_model(filepath: str,
               custom_objects: Optional[dict] = None,
               compression=Compression.none,
               backward_passes_per_step: int = 1):
    """Load a Keras model and wrap its optimizer in DistributedOptimizer
    (reference: keras/__init__.py:170 load_model with optimizer wrapping)."""
    import keras
    model = keras.saving.load_model(filepath, custom_objects=custom_objects,
                                    compile=True)
    opt = getattr(model, "optimizer", None)
    if opt is not None and not getattr(opt, "_hvd_distributed", False):
        dist = DistributedOptimizer(
            opt, compression=compression,
            backward_passes_per_step=backward_passes_per_step)
        try:
            model.optimizer = dist
        except AttributeError:
            # Recompile preserving the saved compile config (loss, metrics,
            # loss_weights), swapping only the optimizer.
            cfg = model.get_compile_config() or {}
            cfg["optimizer"] = dist
            model.compile_from_config(cfg)
    return model


def distribution():
    """A ``keras.distribution.DataParallel`` over the framework mesh — the
    idiomatic whole-mesh single-controller TPU path (no reference analog;
    batch sharding + GSPMD gradient psum replace eager allreduce).

    Usage: ``keras.distribution.set_distribution(hvd.keras.distribution())``
    before building the model.
    """
    import keras
    devices = list(mesh().devices.flat)
    return keras.distribution.DataParallel(devices=devices)


__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "mesh",
    "allreduce", "allgather", "broadcast", "broadcast_object",
    "allgather_object",
    "DistributedOptimizer", "broadcast_global_variables", "load_model",
    "distribution", "sync_trainer_state", "callbacks", "elastic",
    "Compression", "ReduceOp", "Average", "Sum", "Adasum",
]

import horovod_tpu as _root  # noqa: E402
for _n in _root.CAPABILITY_EXPORTS:  # one shared parity surface
    globals()[_n] = getattr(_root, _n)
__all__ += list(_root.CAPABILITY_EXPORTS)
del _root, _n
