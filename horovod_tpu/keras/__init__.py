"""horovod_tpu.keras subpackage."""
