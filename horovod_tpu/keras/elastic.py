"""Elastic training support for the Keras frontend.

Mirrors the reference's keras elastic binding (reference:
horovod/tensorflow/keras/elastic.py: KerasState, CommitStateCallback,
UpdateBatchStateCallback, UpdateEpochStateCallback): model weights +
optimizer variables are snapshotted/commit()ed between batches and
broadcast-synced after a rendezvous reset.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import keras

import horovod_tpu as _hvd
from ..elastic.state import State
from ..functions import broadcast_object


class KerasState(State):
    """Elastic state wrapping a Keras model (+ its optimizer) and arbitrary
    scalar attributes like ``epoch``/``batch`` (reference:
    tensorflow/keras/elastic.py KerasState)."""

    def __init__(self, model, optimizer=None, **scalars: Any):
        self.model = model
        self.optimizer = optimizer or getattr(model, "optimizer", None)
        self._weight_snapshot = None
        self._opt_snapshot = None
        super().__init__(**scalars)

    # -- variable access ----------------------------------------------------
    def _opt_values(self):
        if self.optimizer is None:
            return []
        return [np.asarray(v) for v in self.optimizer.variables]

    def _set_opt_values(self, values) -> None:
        if self.optimizer is None:
            return
        for var, val in zip(self.optimizer.variables, values):
            var.assign(val)

    # -- snapshot protocol --------------------------------------------------
    def save(self) -> None:
        from . import sync_trainer_state
        sync_trainer_state(self.model)
        super().save()
        self._weight_snapshot = [np.copy(w) for w in self.model.get_weights()]
        self._opt_snapshot = self._opt_values()

    def restore(self) -> None:
        from . import sync_trainer_state
        sync_trainer_state(self.model)
        super().restore()
        if self._weight_snapshot is not None:
            self.model.set_weights(self._weight_snapshot)
        if self._opt_snapshot is not None:
            self._set_opt_values(self._opt_snapshot)

    def sync(self) -> None:
        """Broadcast weights/optimizer/scalars from rank 0 so rejoining
        workers converge (reference: keras/elastic.py sync via
        broadcast_variables)."""
        from . import broadcast_global_variables
        broadcast_global_variables(self.model, root_rank=0)
        scalars = {f: getattr(self, f) for f in self._fields}
        if scalars and _hvd.size() > 1:
            synced = broadcast_object(scalars, root_rank=0)
            for k, v in synced.items():
                setattr(self, k, v)
        self.save()


class CommitStateCallback(keras.callbacks.Callback):
    """Commit the elastic state every ``batches_per_commit`` batches
    (reference: tensorflow/keras/elastic.py CommitStateCallbackImpl)."""

    def __init__(self, state: KerasState, batches_per_commit: int = 1):
        super().__init__()
        self.state = state
        self.batches_per_commit = max(1, int(batches_per_commit))

    def on_train_batch_end(self, batch, logs=None):
        if (batch + 1) % self.batches_per_commit == 0:
            self.state.commit()

    def on_epoch_end(self, epoch, logs=None):
        self.state.commit()


class UpdateBatchStateCallback(keras.callbacks.Callback):
    """Track the current batch in the state so a restart resumes mid-epoch
    (reference: tensorflow/keras/elastic.py UpdateBatchStateCallbackImpl)."""

    def __init__(self, state: KerasState):
        super().__init__()
        self.state = state

    def on_train_batch_end(self, batch, logs=None):
        self.state.batch = batch + 1

    def on_epoch_end(self, epoch, logs=None):
        self.state.batch = 0


class UpdateEpochStateCallback(keras.callbacks.Callback):
    """Track the current epoch in the state (reference:
    tensorflow/keras/elastic.py UpdateEpochStateCallbackImpl)."""

    def __init__(self, state: KerasState):
        super().__init__()
        self.state = state

    def on_epoch_begin(self, epoch, logs=None):
        self.state.epoch = epoch

    def on_epoch_end(self, epoch, logs=None):
        self.state.epoch = epoch + 1


__all__ = ["KerasState", "CommitStateCallback", "UpdateBatchStateCallback",
           "UpdateEpochStateCallback"]
