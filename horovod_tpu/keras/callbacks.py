"""Keras callbacks for distributed training.

Mirrors the reference's callback set (reference: horovod/_keras/callbacks.py
:23-192, horovod/keras/callbacks.py): broadcast-at-start, metric averaging,
LR scheduling with warmup and momentum correction.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Union

import numpy as np
import keras

import horovod_tpu as _hvd


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast initial model + optimizer state from ``root_rank`` on the
    first batch, after all variables exist (reference:
    _keras/callbacks.py BroadcastGlobalVariablesCallbackImpl: broadcast at
    on_batch_end of batch 0)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_train_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        from . import broadcast_global_variables
        broadcast_global_variables(self.model, root_rank=self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metric logs over all workers (reference:
    _keras/callbacks.py MetricAverageCallbackImpl: allreduce of logs at
    on_epoch_end)."""

    def __init__(self, device: str = ""):
        super().__init__()

    def on_epoch_end(self, epoch, logs=None):
        if not logs or _hvd.size() == 1:
            return
        keys = sorted(k for k, v in logs.items()
                      if isinstance(v, (int, float, np.floating, np.integer)))
        if not keys:
            return
        vec = np.asarray([float(logs[k]) for k in keys], np.float32)
        avg = np.asarray(_hvd.allreduce(vec, op=_hvd.Average))
        for k, v in zip(keys, avg):
            logs[k] = float(v)


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Schedule LR as ``initial_lr * multiplier(epoch)``; per-batch
    fractional epochs when ``steps_per_epoch`` is known (reference:
    _keras/callbacks.py LearningRateScheduleCallbackImpl:23-110).

    With ``momentum_correction``, when the LR changes the optimizer momentum
    is temporarily rescaled by ``new_lr / old_lr`` for the first step at the
    new LR, so the effective velocity stays continuous — the reference
    applies the same correction (reference: _keras/callbacks.py:70-95).
    """

    def __init__(self, initial_lr: float,
                 multiplier: Union[float, Callable[[float], float]],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None):
        super().__init__()
        self.initial_lr = float(initial_lr)
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        self._saved_momentum = None
        self._pending_restore = False
        self._last_lr: Optional[float] = None
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def set_params(self, params):
        super().set_params(params)
        if self.steps_per_epoch is None and params:
            self.steps_per_epoch = params.get("steps")

    # -- lr plumbing --------------------------------------------------------
    def _optimizer(self):
        opt = getattr(self.model, "optimizer", None)
        if opt is None:
            raise ValueError("model has no optimizer; compile() first")
        return opt

    def _get_lr(self) -> float:
        from . import sync_trainer_state
        sync_trainer_state(self.model)
        return float(np.asarray(self._optimizer().learning_rate))

    def _set_lr(self, lr: float) -> None:
        from . import sync_trainer_state
        # Mid-epoch the live lr lives in the trainer's jax state; sync so
        # the assignment isn't overwritten and is re-fetched next step.
        sync_trainer_state(self.model)
        opt = self._optimizer()
        try:
            opt.learning_rate.assign(lr)
        except AttributeError:
            opt.learning_rate = lr

    def _in_range(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return False
        return True

    def _adjust(self, epoch: float) -> None:
        if not self._in_range(epoch):
            return
        lr = self.initial_lr * self.multiplier(epoch)
        old = self._last_lr if self._last_lr is not None else self._get_lr()
        self._set_lr(lr)
        if self.momentum_correction and old and not math.isclose(lr, old):
            self._apply_momentum_correction(lr / old)
        self._last_lr = lr

    def _apply_momentum_correction(self, ratio: float) -> None:
        opt = self._optimizer()
        mom = getattr(opt, "momentum", None)
        if mom is None:
            return
        if self._saved_momentum is None:
            self._saved_momentum = float(np.asarray(mom))
        opt.momentum = self._saved_momentum * ratio
        self._pending_restore = True

    def _restore_momentum(self) -> None:
        if self._saved_momentum is not None and self._pending_restore:
            self._optimizer().momentum = self._saved_momentum
            self._pending_restore = False

    # -- hooks --------------------------------------------------------------
    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase or self.steps_per_epoch is None:
            self._adjust(float(epoch))

    def on_train_batch_begin(self, batch, logs=None):
        if not self.staircase and self.steps_per_epoch:
            self._adjust(self.current_epoch + batch / self.steps_per_epoch)

    def on_train_batch_end(self, batch, logs=None):
        self._restore_momentum()

    def on_epoch_end(self, epoch, logs=None):
        self._restore_momentum()
        if logs is not None:
            logs["lr"] = self._get_lr()


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Ramp LR linearly from ``initial_lr / size`` to ``initial_lr`` over
    the first ``warmup_epochs`` (reference: _keras/callbacks.py
    LearningRateWarmupCallbackImpl:112-192 — "gradual warmup" from the
    1-hour-ImageNet recipe: start at the single-worker LR, end at the
    size-scaled LR)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        size = _hvd.size()

        def multiplier(epoch: float) -> float:
            if warmup_epochs <= 0:
                return 1.0
            frac = min(epoch / float(warmup_epochs), 1.0)
            return (1.0 / size) * (1 - frac) + 1.0 * frac

        super().__init__(initial_lr=initial_lr, multiplier=multiplier,
                         start_epoch=0, end_epoch=warmup_epochs + 1,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.warmup_epochs - 1 and self.verbose:
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self._get_lr():.6g}.")


class BestModelCheckpoint(keras.callbacks.ModelCheckpoint):
    """``ModelCheckpoint(save_best_only=True)`` whose filepath is injected
    later (reference: keras/callbacks.py:151-164 — the Spark Keras
    estimator sets ``filepath`` on the driver-side copy before fit)."""

    _UNSET_STEM = "__hvd_best_model_unset__"

    def __init__(self, monitor: str = "val_loss", verbose: int = 0,
                 save_weights_only: bool = False, mode: str = "auto",
                 save_freq="epoch"):
        sentinel = self._UNSET_STEM + (".weights.h5" if save_weights_only
                                       else ".keras")
        super().__init__(filepath=sentinel, monitor=monitor,
                         verbose=verbose, save_best_only=True,
                         save_weights_only=save_weights_only,
                         mode=mode, save_freq=save_freq)

    def set_filepath(self, filepath: str) -> None:
        self.filepath = filepath

    def _save_model(self, *args, **kwargs):
        if self._UNSET_STEM in str(self.filepath):
            raise ValueError(
                "BestModelCheckpoint has no filepath; call "
                "set_filepath(...) before fit()")
        return super()._save_model(*args, **kwargs)


__all__ = [
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateScheduleCallback", "LearningRateWarmupCallback",
    "BestModelCheckpoint",
]
