"""Hyperparameter search over distributed training jobs.

Reference: docs/hyperparameter_search.rst — the reference's story is Ray
Tune orchestrating Horovod trials: ``tune.grid_search`` /
Bayesian-optimization search spaces, a ``DistributedTrainableCreator``
adapting a training function into a resource-scoped trial, and
``tune.report`` from inside the trial.

TPU-native reshape: the Bayesian engine is THIS framework's own native
Gaussian process + expected improvement (csrc/optim.cc — the same
optimizer that powers autotune), so no external tuning framework is
required; trials run through the same placement backends the rest of
the stack uses (``distributed_trainable`` wraps a function with
``spark.run``'s task executors, the DistributedTrainableCreator analog).

    from horovod_tpu import tune

    def trainable(config):
        ...train...
        tune.report(loss=val_loss)

    result = tune.run(
        trainable,
        config={"lr": tune.loguniform(1e-4, 1e-1),
                "layers": tune.choice([2, 4, 8])},
        metric="loss", mode="min", num_trials=20)
    print(result.best_config, result.best_metric)
"""

from __future__ import annotations

import itertools
import math
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence


# ----------------------------------------------------------- search space
class _Domain:
    """A sampleable axis of the search space."""

    def to_unit(self, v) -> float:
        raise NotImplementedError

    def from_unit(self, u: float):
        raise NotImplementedError

    grid: Optional[Sequence] = None  # set for grid_search axes


@dataclass
class uniform(_Domain):
    low: float
    high: float

    def __post_init__(self):
        if not self.low < self.high:
            raise ValueError(f"uniform requires low < high, got "
                             f"({self.low}, {self.high})")

    def from_unit(self, u):
        return self.low + (self.high - self.low) * min(max(u, 0.0), 1.0)

    def to_unit(self, v):
        return (v - self.low) / max(self.high - self.low, 1e-30)


@dataclass
class loguniform(_Domain):
    low: float
    high: float

    def __post_init__(self):
        # validate at CONSTRUCTION: from_unit runs outside the per-trial
        # error isolation, so a bad bound there would abort the search
        # with a bare "math domain error"
        if not 0.0 < self.low < self.high:
            raise ValueError(f"loguniform requires 0 < low < high, got "
                             f"({self.low}, {self.high})")

    def from_unit(self, u):
        lo, hi = math.log(self.low), math.log(self.high)
        return math.exp(lo + (hi - lo) * min(max(u, 0.0), 1.0))

    def to_unit(self, v):
        lo, hi = math.log(self.low), math.log(self.high)
        return (math.log(v) - lo) / max(hi - lo, 1e-30)


@dataclass
class choice(_Domain):
    options: Sequence

    def from_unit(self, u):
        i = min(int(min(max(u, 0.0), 1.0) * len(self.options)),
                len(self.options) - 1)
        return self.options[i]

    def to_unit(self, v):
        return (list(self.options).index(v) + 0.5) / len(self.options)


@dataclass
class grid_search(_Domain):
    """Exhaustive axis (reference: tune.grid_search) — crossed with every
    other grid axis; continuous axes may not be mixed into a grid run."""

    values: Sequence = field(default_factory=list)

    def __post_init__(self):
        self.grid = list(self.values)


# --------------------------------------------------------------- report()
_report_ctx = threading.local()


def report(**metrics) -> None:
    """Record metrics from inside a trial (reference: tune.report).
    Callable once or per epoch; the LAST reported value of the target
    metric scores the trial.  Outside a trial this is a no-op, so the
    same training function runs standalone."""
    store = getattr(_report_ctx, "metrics", None)
    if store is not None:
        store.update({k: float(v) for k, v in metrics.items()})


@dataclass
class Trial:
    config: Dict[str, Any]
    metrics: Dict[str, float]
    error: Optional[str] = None


@dataclass
class Result:
    best_config: Optional[Dict[str, Any]]
    best_metric: Optional[float]
    trials: List[Trial]
    metric: str
    mode: str


def _run_trial(fn: Callable, config: Dict[str, Any], metric: str) -> Trial:
    _report_ctx.metrics = {}
    try:
        out = fn(dict(config))
        metrics = dict(_report_ctx.metrics)
        if isinstance(out, dict):
            metrics.update({k: float(v) for k, v in out.items()})
        elif out is not None:
            metrics.setdefault(metric, float(out))
        return Trial(config=dict(config), metrics=metrics)
    except Exception as e:  # a failed trial must not kill the search
        return Trial(config=dict(config), metrics={}, error=str(e))
    finally:
        _report_ctx.metrics = None


def run(trainable: Callable, config: Dict[str, Any], metric: str,
        mode: str = "min", num_trials: int = 16, seed: int = 42,
        gp_noise: float = 1e-3, xi: float = 0.01,
        verbose: bool = False) -> Result:
    """Search ``config``'s space for the best trial (reference:
    tune.run).  Plain values pass through to every trial; ``grid_search``
    axes run exhaustively (their cartesian product caps the trial
    count); continuous/choice axes are driven by the native GP+EI
    optimizer, warm-started with a centered first sample.
    """
    if mode not in ("min", "max"):
        raise ValueError(f"mode must be min|max, got {mode!r}")
    fixed = {k: v for k, v in config.items()
             if not isinstance(v, _Domain)}
    grid_axes = {k: v.grid for k, v in config.items()
                 if isinstance(v, grid_search)}
    model_axes = {k: v for k, v in config.items()
                  if isinstance(v, _Domain) and not isinstance(v,
                                                               grid_search)}

    trials: List[Trial] = []

    def score(t: Trial) -> Optional[float]:
        if t.error is not None or metric not in t.metrics:
            return None
        return t.metrics[metric]

    if grid_axes and model_axes:
        raise ValueError(
            "grid_search axes cannot mix with continuous/choice axes in "
            "one run; split into a grid run over a bayes run's best")

    if not grid_axes and not model_axes:
        # nothing to search: one trial (feeding a zero-length sample to
        # the native GP would be undefined behavior)
        trials.append(_run_trial(trainable, dict(fixed), metric))
        s = score(trials[0])
        return Result(trials[0].config if s is not None else None,
                      s, trials, metric, mode)

    if grid_axes:
        keys = list(grid_axes)
        for combo in itertools.product(*(grid_axes[k] for k in keys)):
            cfg = dict(fixed, **dict(zip(keys, combo)))
            trials.append(_run_trial(trainable, cfg, metric))
            if verbose:
                print(f"[tune] {cfg} -> {score(trials[-1])}")
    else:
        from .common.basics import BayesianOptimizer
        keys = list(model_axes)
        bo = BayesianOptimizer(dims=max(len(keys), 1), xi=xi,
                               seed=seed, gp_noise=gp_noise)
        sign = 1.0 if mode == "max" else -1.0
        for i in range(num_trials):
            u = [0.5] * len(keys) if i == 0 else bo.next_sample()
            cfg = dict(fixed, **{k: model_axes[k].from_unit(u[j])
                                 for j, k in enumerate(keys)})
            t = _run_trial(trainable, cfg, metric)
            trials.append(t)
            s = score(t)
            if s is not None and math.isfinite(s):
                bo.add_sample(u, sign * s)
            if verbose:
                print(f"[tune] {cfg} -> {s}")

    scored = [(score(t), t) for t in trials]
    scored = [(s, t) for s, t in scored
              if s is not None and math.isfinite(s)]
    if not scored:
        return Result(None, None, trials, metric, mode)
    best = (min if mode == "min" else max)(scored, key=lambda st: st[0])
    return Result(best[1].config, best[0], trials, metric, mode)


class _WorkerTrial:
    """Picklable worker-side wrapper: captures ``tune.report`` calls made
    INSIDE the worker process (whose thread-local is otherwise invisible
    to the driver) and ships them back with the return value."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def __call__(self, config):
        from horovod_tpu.tune import _report_ctx
        _report_ctx.metrics = {}
        try:
            ret = self.fn(config)
            return ret, dict(_report_ctx.metrics)
        finally:
            _report_ctx.metrics = None


def distributed_trainable(fn: Callable, num_proc: int = 1,
                          executor_factory: Optional[Callable] = None,
                          coordinator_port: int = 29531) -> Callable:
    """Adapt ``fn(config) -> metric`` into a trial that runs on
    ``num_proc`` distributed workers per trial (reference:
    DistributedTrainableCreator's num_hosts/num_slots scoping).  Workers
    launch through the same placement layer as ``spark.run``; rank 0
    scores the trial — via its return value AND any ``tune.report``
    calls it made (forwarded from the worker process)."""
    def trial(config):
        from .spark.runner import LocalTaskExecutor, run as dist_run
        executor = (executor_factory(num_proc) if executor_factory
                    else LocalTaskExecutor(num_proc))
        out = dist_run(_WorkerTrial(fn), args=(config,),
                       num_proc=num_proc, executor=executor,
                       coordinator_port=coordinator_port)
        ret, reported = out[0]
        if reported:
            report(**reported)
        if ret is None and not reported:
            raise RuntimeError(
                "distributed trial produced no metric: the training "
                "function neither returned a value nor called "
                "tune.report()")
        return ret
    return trial
