"""Process-global runtime: the TPU-native analog of HorovodGlobalState.

The reference keeps a process-wide singleton holding the background thread,
controller, tensor queue, fusion buffers and knobs (reference:
horovod/common/global_state.h:43-132, operations.cc:115) initialized once by
``horovod_init`` (operations.cc:651-699).  On TPU the data plane is XLA SPMD
over a `jax.sharding.Mesh`, so the runtime's job becomes:

  * bring up the (optionally multi-host) JAX runtime and build the mesh,
  * own the knob snapshot, bucket-plan cache, timeline and stall inspector,
  * expose the rank/size topology API.

Topology model (TPU-native reinterpretation of Horovod's 1-process-per-GPU):
the *worker unit is the chip*.  ``size()`` is the number of chips in the mesh
and ``local_size()`` the chips owned by this process.  A process controls
``local_size()`` workers at once — eager collectives therefore accept a
leading per-chip axis (see ops/collectives.py).  Process-level coordinates
(``process_rank``/``process_size``) correspond to the reference's CROSS
communicator scope, and local chips to the LOCAL scope
(reference: common.h:119-123, mpi_context.cc:147-156).
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .common import hvdlogging as log
from .common.knobs import Knobs

_lock = threading.Lock()
_runtime: Optional["Runtime"] = None


def _parse_mesh_spec(spec: str) -> List[Tuple[str, int]]:
    """Parse 'data=4,model=2' into [('data', 4), ('model', 2)]."""
    axes: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size = part.partition("=")
        axes.append((name.strip(), int(size)))
    return axes


class Runtime:
    """Holds the mesh, knobs and auxiliary subsystems for this process."""

    def __init__(self,
                 knobs: Optional[Knobs] = None,
                 devices: Optional[Sequence[Any]] = None,
                 mesh_spec: Optional[str] = None):
        import jax
        import os

        self.knobs = knobs or Knobs()
        self._shutdown = False

        # Honor an EXPLICIT JAX_PLATFORMS env even when site customization
        # (TPU images force-registering a hardware backend) overrode the
        # jax_platforms CONFIG, which beats the env var.  Worker processes
        # spawned by launchers/executors inherit the env but not the
        # parent's config, so without this a CPU-forced worker silently
        # lands on the hardware backend — and multi-process CPU meshes
        # (jax.distributed over gloo) never form.
        env_plat = os.environ.get("JAX_PLATFORMS", "")
        if env_plat and jax.config.jax_platforms != env_plat:
            try:
                jax.config.update("jax_platforms", env_plat)
            except Exception:
                pass  # backends already initialized; nothing to rescue

        # Multi-host bring-up: the launcher (hvdrun) exports coordinator
        # address + process coordinates (the analog of mpirun exporting
        # HOROVOD_RANK/SIZE per slot, reference: gloo_run.py:65-77).
        # jax.distributed.initialize must run before ANY backend-touching
        # call (including jax.process_count()), so gate purely on env/knobs.
        coord = self.knobs["HOROVOD_COORDINATOR_ADDR"]
        if coord and self.knobs["HOROVOD_SIZE"] > 1:
            try:
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=self.knobs["HOROVOD_SIZE"],
                    process_id=max(self.knobs["HOROVOD_RANK"], 0),
                    initialization_timeout=self.knobs[
                        "HOROVOD_START_TIMEOUT"],
                )
            except RuntimeError as e:
                # Already initialized (e.g. by user code) is fine.
                if "already" not in str(e).lower():
                    raise

        self.devices = list(devices if devices is not None else jax.devices())
        self._process_index = jax.process_index()
        self._process_count = jax.process_count()

        spec = mesh_spec if mesh_spec is not None else self.knobs["HOROVOD_TPU_MESH"]
        # 3D layout plane (parallel/layout.py; docs/parallelism.md):
        # HOROVOD_LAYOUT owns the mesh when set — validated BEFORE mesh
        # construction (the layout IS the mesh), 'auto' ranks the
        # factorizations with perf/costmodel.solve_layout under any
        # HOROVOD_TP / HOROVOD_PP constraints.
        from .parallel.layout import (validate_layout_knobs,
                                      resolve_layout, layout_mesh_spec)
        validate_layout_knobs(self.knobs, world=len(self.devices),
                              mesh_spec=str(spec))
        self.layout = resolve_layout(len(self.devices), self.knobs)
        if self.layout is not None:
            spec = layout_mesh_spec(*self.layout)
        self.mesh = self._build_mesh(spec)
        # Canonical worker numbering = flattened *mesh* position, which is
        # what lax.axis_index sees inside collectives.  create_device_mesh
        # may permute devices for ICI adjacency, so re-derive the ordered
        # device list from the mesh rather than jax.devices().
        self.devices = list(self.mesh.devices.flatten())
        self.local_devices = [d for d in self.devices
                              if d.process_index == self._process_index]

        # Bucket-plan cache: the analog of the response cache — repeat steps
        # skip re-planning (reference: response_cache.h:44-100).
        from .ops.fusion import BucketPlanCache
        self.plan_cache = BucketPlanCache(
            capacity=self.knobs["HOROVOD_CACHE_CAPACITY"])

        # Tracing plane (utils/timeline.py, docs/timeline.md): clock
        # alignment first — the NTP-style offset handshake against the
        # rendezvous server puts every rank's trace events on one fleet
        # epoch; a rank without a reachable server traces locally with
        # offset 0 and infinite uncertainty.
        self.clock_sync = None
        rdv_addr = self.knobs["HOROVOD_RENDEZVOUS_ADDR"]
        rdv_port = self.knobs["HOROVOD_RENDEZVOUS_PORT"]
        if rdv_addr and rdv_port and (self.knobs["HOROVOD_TIMELINE"]
                                      or self.knobs["HOROVOD_HEARTBEAT"]):
            # Heartbeats ride the same aligned fleet clock as the trace
            # (postmortem ordering depends on it, docs/postmortem.md).
            from .utils.clocksync import ClockSync
            self.clock_sync = ClockSync(rdv_addr, rdv_port)

        # Timeline + stall inspector are created lazily by their modules.
        self.timeline = None
        self.timeline_publisher = None
        self._trace_drainer = None
        self._timeline_path = self.knobs["HOROVOD_TIMELINE"]
        if self._timeline_path and self._timeline_path != "DYNAMIC":
            from .utils.timeline import Timeline
            self.timeline = Timeline(self._timeline_path,
                                     mark_cycles=self.knobs[
                                         "HOROVOD_TIMELINE_MARK_CYCLES"],
                                     clock=self.clock_sync,
                                     rank=self._process_index)
            self._start_timeline_publisher()

        # Wire-policy plane (ops/wire.py): validate HOROVOD_WIRE_POLICY
        # now — an unknown policy name must fail AT INIT, not as a trace
        # error deep inside the first compiled step.
        from .ops.wire import validate_policy_name
        validate_policy_name(self.knobs["HOROVOD_WIRE_POLICY"])

        # Overlap plane (ops/overlap.py): same init-validation contract
        # for HOROVOD_OVERLAP_DEPTH / HOROVOD_PREFETCH_DEPTH — plus the
        # negative-value checks the wire-era validation never grew for
        # the core numeric knobs.
        from .ops.overlap import validate_overlap_knobs
        validate_overlap_knobs(self.knobs)
        # ZeRO weight-update sharding (parallel/zero.py; docs/zero.md):
        # level and AG-prefetch depth fail AT INIT, not as a trace
        # error inside the first compiled zero step.
        from .parallel.zero import validate_zero_knobs
        validate_zero_knobs(self.knobs)
        # Serving plane (serve/; docs/serving.md): same init-validation
        # contract for the HOROVOD_SERVE_* knob surface (port range,
        # positive budgets) — config-only import, no model/jax cost.
        from .serve.config import validate_serve_knobs
        validate_serve_knobs(self.knobs)
        # Perf-attribution plane (perf/; docs/profiling.md): same
        # init-validation contract for HOROVOD_PERF_* (link class,
        # positive publish period).
        from .perf import validate_perf_knobs
        validate_perf_knobs(self.knobs)
        # Watch plane (watch/; docs/watch.md): series bounds, sentinel
        # cadence, and — when HOROVOD_ALERTS names a rules file — a full
        # parse, so a typo'd ruleset fails bring-up, not a detector.
        from .watch import validate_watch_knobs
        validate_watch_knobs(self.knobs)
        # Memory plane (perf/memstats.py; docs/memory.md): sample rate
        # limit and the OOM-proximity watermark fraction.
        from .perf import validate_mem_knobs
        validate_mem_knobs(self.knobs)
        # Scenario engine (scenario/; docs/scenarios.md): rank/tick
        # overrides, and — when HOROVOD_SCENARIO names a spec — a full
        # parse, so a typo'd scenario fails bring-up, not a replay.
        from .scenario import validate_scenario_knobs
        validate_scenario_knobs(self.knobs)
        if self.knobs["HOROVOD_FUSION_THRESHOLD"] <= 0:
            raise ValueError(
                f"HOROVOD_FUSION_THRESHOLD="
                f"{self.knobs['HOROVOD_FUSION_THRESHOLD']} invalid; the "
                "bucket threshold must be a positive byte count")
        if self.knobs["HOROVOD_CACHE_CAPACITY"] < 0:
            raise ValueError(
                f"HOROVOD_CACHE_CAPACITY="
                f"{self.knobs['HOROVOD_CACHE_CAPACITY']} invalid; use 0 "
                "to disable caching, a positive entry count otherwise")
        # Plan-epoch fast path (csrc/controller.cc; docs/tensor-fusion.md):
        # the native core reads these from env at construction, so a bad
        # value must fail HERE, not as a silently-never-locking epoch.
        if self.knobs["HOROVOD_BYPASS_STABLE_CYCLES"] < 1:
            raise ValueError(
                f"HOROVOD_BYPASS_STABLE_CYCLES="
                f"{self.knobs['HOROVOD_BYPASS_STABLE_CYCLES']} invalid; "
                "the epoch lock needs at least 1 stable step "
                "(docs/knobs.md)")
        # Sharded rendezvous KV (docs/control-plane.md): validate the
        # shard count and the launcher-stamped address list here so a
        # malformed map fails bring-up, not a KV op mid-run.  The
        # client's per-scope routing itself reads the env lazily
        # (runner/http_client), so nothing needs installing.
        if self.knobs["HOROVOD_KV_SHARDS"] < 1:
            raise ValueError(
                f"HOROVOD_KV_SHARDS={self.knobs['HOROVOD_KV_SHARDS']} "
                "invalid; the rendezvous KV needs at least one shard "
                "(docs/control-plane.md)")
        if self.knobs["HOROVOD_KV_SHARD_ADDRS"]:
            from .runner.kvshard import parse_shard_addrs
            addrs = parse_shard_addrs(self.knobs["HOROVOD_KV_SHARD_ADDRS"])
            if len(addrs) != self.knobs["HOROVOD_KV_SHARDS"]:
                raise ValueError(
                    f"HOROVOD_KV_SHARD_ADDRS lists {len(addrs)} "
                    f"shard(s) but HOROVOD_KV_SHARDS="
                    f"{self.knobs['HOROVOD_KV_SHARDS']}; the scope->"
                    "shard map is a modulus of the count, so the two "
                    "must agree (docs/control-plane.md)")

        # Autotune (reference: HOROVOD_AUTOTUNE + ParameterManager,
        # parameter_manager.{h,cc}): Bayesian optimization over (fusion
        # threshold, cycle time), native math in csrc/optim.cc.  When the
        # wire policy is 'auto', the policy dimension joins the search as
        # a bandit over policy arms (mesh-aware: dcn_int8 is only an arm
        # on a two-level mesh).
        self.autotuner = None
        if self.knobs["HOROVOD_AUTOTUNE"]:
            from .utils.autotune import Autotuner
            policy_arms = None
            if self.knobs["HOROVOD_WIRE_POLICY"] == "auto":
                policy_arms = ["auto", "none", "bf16", "int8_ring"]
                if any(str(a).startswith("dcn.")
                       for a in self.mesh.axis_names):
                    policy_arms.append("dcn_int8")
            # Overlap-depth arm dimension (ops/overlap.py): only worth
            # searching when the pipeline is on; the knob's depth stays
            # an arm so tuning can conclude it was right.
            depth_arms = None
            if self.knobs["HOROVOD_OVERLAP"]:
                knob_d = int(self.knobs["HOROVOD_OVERLAP_DEPTH"])
                depth_arms = sorted({1, 2, 4, knob_d})
            self.autotuner = Autotuner(self.knobs,
                                       process_rank=self._process_index,
                                       process_size=self._process_count,
                                       policy_arms=policy_arms,
                                       depth_arms=depth_arms)

        self.stall_inspector = None
        if not self.knobs["HOROVOD_STALL_CHECK_DISABLE"]:
            from .utils.stall import StallInspector
            self.stall_inspector = StallInspector(
                warn_seconds=self.knobs["HOROVOD_STALL_CHECK_TIME_SECONDS"],
                shutdown_seconds=self.knobs[
                    "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"])

        # Metrics plane (utils/metrics.py): when enabled, this worker
        # publishes periodic registry snapshots to the rendezvous KV so
        # the launcher's /metrics route serves a fleet-wide Prometheus
        # view and can print the end-of-run straggler report.
        self.metrics_publisher = None
        if self.knobs["HOROVOD_METRICS"]:
            from .utils.metrics import MetricsPublisher
            self.metrics_publisher = MetricsPublisher(
                addr=self.knobs["HOROVOD_RENDEZVOUS_ADDR"],
                port=self.knobs["HOROVOD_RENDEZVOUS_PORT"],
                rank=self._process_index,
                snapshot_fn=self.metrics_snapshot,
                interval=self.knobs["HOROVOD_METRICS_INTERVAL"])

        # Perf-attribution plane (perf/; docs/profiling.md): when
        # enabled, this worker publishes its step-time decomposition
        # report to the rendezvous KV scope 'perf' so GET /perf serves
        # the merged fleet view and doctor --perf can render it.  The
        # ledger itself is always live (recording costs nothing until a
        # step is recorded); the knob gates only the publisher thread.
        self.perf_publisher = None
        if self.knobs["HOROVOD_PERF"]:
            from .perf import resolve_link
            from .perf.ledger import GLOBAL as _perf_ledger
            from .perf.ledger import PerfPublisher
            _perf_ledger.configure(link=resolve_link(self.knobs,
                                                     self.mesh))
            self.perf_publisher = PerfPublisher(
                addr=self.knobs["HOROVOD_RENDEZVOUS_ADDR"],
                port=self.knobs["HOROVOD_RENDEZVOUS_PORT"],
                rank=self._process_index,
                interval=self.knobs["HOROVOD_PERF_INTERVAL"])

        # Postmortem plane (docs/postmortem.md): per-rank heartbeats to
        # the rendezvous KV scope 'health' — step progress, native cycle
        # liveness and pending-collective counts on the aligned fleet
        # clock — so the launcher can supervise progress (/health,
        # hvdrun --postmortem) and the postmortem can order last events.
        self.heartbeat = None
        if self.knobs["HOROVOD_HEARTBEAT"]:
            from .utils.health import HeartbeatPublisher
            self.heartbeat = HeartbeatPublisher(
                addr=self.knobs["HOROVOD_RENDEZVOUS_ADDR"],
                port=self.knobs["HOROVOD_RENDEZVOUS_PORT"],
                rank=self._process_index,
                payload_fn=self._heartbeat_payload,
                interval=self.knobs["HOROVOD_HEARTBEAT_INTERVAL"])

        # Chaos plane (chaos/): install this rank's deterministic fault
        # injector from the rendezvous-distributed spec (hvdrun --chaos)
        # or a local spec file.  Must precede ensure_core(): the native
        # transport reads its HOROVOD_CHAOS_* env at construction.
        from . import chaos as _chaos
        _chaos.ensure_installed(self.knobs, rank=self._process_index)

        # Native core (C++ controller/tensor-queue): negotiates a global
        # execution order for eager multi-process collectives (SPMD paths
        # don't need it — XLA programs are deterministic).  Reference:
        # the MPI/Gloo controller choice at operations.cc:654-687.
        # Created lazily by ensure_core(): only consumers that need
        # negotiation (eager/torch frontends) pay the TCP bring-up.
        self.core = None
        mode = str(self.knobs["HOROVOD_CONTROLLER"]).lower()
        if mode not in ("auto", "tcp", "none"):
            raise ValueError(
                f"HOROVOD_CONTROLLER={mode!r} not supported; use 'auto', "
                "'tcp' or 'none' (this framework's controller transport is "
                "TCP; the reference's 'mpi'/'gloo' values do not apply)")
        self._controller_mode = mode
        if mode == "tcp":
            self.ensure_core()

        log.debug("Runtime up: %d devices, %d local, mesh=%s",
                  len(self.devices), len(self.local_devices),
                  self.mesh.shape if self.mesh else None)

    # ------------------------------------------------------------------ mesh
    def _build_mesh(self, spec: str):
        import jax
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh

        n = len(self.devices)
        if not spec:
            axes = [("hvd", n)]
        else:
            axes = _parse_mesh_spec(spec)
            # A single trailing -1 axis absorbs the remaining chips.
            sizes = [s for _, s in axes]
            if -1 in sizes:
                known = int(np.prod([s for s in sizes if s != -1]))
                axes = [(a, s if s != -1 else n // known) for a, s in axes]
        shape = tuple(s for _, s in axes)
        names = tuple(a for a, _ in axes)
        if int(np.prod(shape)) != n:
            raise ValueError(
                f"mesh spec {spec!r} covers {int(np.prod(shape))} chips but "
                f"{n} are visible")
        try:
            # ICI-topology-aware assignment: keeps high-traffic axes on
            # physically adjacent chips so collectives ride ICI links.
            devs = mesh_utils.create_device_mesh(shape, devices=self.devices)
        except (ValueError, AssertionError, NotImplementedError):
            devs = np.array(self.devices).reshape(shape)
        return Mesh(devs, names)

    # -------------------------------------------------------------- topology
    # Chip-level coordinates ("rank" = chip, matching 1-process-per-GPU in
    # the reference once you substitute chip for GPU).
    def size(self) -> int:
        return len(self.devices)

    def local_size(self) -> int:
        return len(self.local_devices)

    def rank(self) -> int:
        """Global index of this process's first chip."""
        if not self.local_devices:
            return 0
        first = self.local_devices[0]
        return self.devices.index(first)

    def local_rank(self) -> int:
        """Process index within its host when launched by hvdrun (reference
        semantics: HOROVOD_LOCAL_RANK, gloo_run.py:65-77); 0 standalone."""
        lr = self.knobs["HOROVOD_LOCAL_RANK"]
        return lr if lr >= 0 else 0

    def local_chip_positions(self) -> List[int]:
        """Mesh-flattened positions of this process's chips, in the order
        local data rows map to them (increasing mesh position)."""
        return [i for i, d in enumerate(self.devices)
                if d.process_index == self._process_index]

    def chip_positions_by_process(self) -> List[List[int]]:
        """For each process index, the mesh positions of its chips (in
        increasing order) — the host-side map between process-major data
        (process_allgather results) and chip-major collective numbering."""
        out: List[List[int]] = [[] for _ in range(self._process_count)]
        for i, d in enumerate(self.devices):
            out[d.process_index].append(i)
        return out

    # Process-level coordinates: CROSS scope in the reference.
    def process_rank(self) -> int:
        return self._process_index

    def process_size(self) -> int:
        return self._process_count

    def cross_rank(self) -> int:
        return self._process_index

    def cross_size(self) -> int:
        return self._process_count

    # ------------------------------------------------------------------ core
    def ensure_core(self):
        """Bring up the native coordination core on first use (idempotent).

        Consumers: eager frontends that need cross-process ordering (torch
        bindings, negotiated grouped ops).  In 'auto' mode single-process
        runs never create it; multi-process runs create it on demand using
        the coordinator host from HOROVOD_COORDINATOR_ADDR."""
        if self.core is not None:
            return self.core
        if self._controller_mode == "none":
            return None
        if self._controller_mode == "auto" and self._process_count <= 1:
            return None
        coord = self.knobs["HOROVOD_COORDINATOR_ADDR"]
        coord_host = coord.split(":")[0] if coord else "127.0.0.1"
        from .common.basics import CoordinationCore
        self.core = CoordinationCore.tcp(
            rank=self._process_index, size=self._process_count,
            addr=coord_host,
            port=self.knobs["HOROVOD_CONTROLLER_PORT"],
            cycle_ms=self.knobs["HOROVOD_CYCLE_TIME"],
            fusion_bytes=self.knobs["HOROVOD_FUSION_THRESHOLD"],
            cache_capacity=self.knobs["HOROVOD_CACHE_CAPACITY"],
            stall_warn_seconds=self.knobs[
                "HOROVOD_STALL_CHECK_TIME_SECONDS"])
        if self.knobs["HOROVOD_AUTOTUNE"]:
            self.core.enable_autotune(
                warmup_samples=self.knobs["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"],
                steps_per_sample=self.knobs[
                    "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"],
                max_samples=self.knobs[
                    "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"],
                gp_noise=self.knobs[
                    "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"])
        # Postmortem plane: arm the crash-time flight recorder as soon
        # as there is a core to record (csrc/postmortem.cc; the launcher
        # sets a per-rank path under --postmortem).
        if self.knobs["HOROVOD_FLIGHT_RECORD"]:
            self.core.flight_enable(self.knobs["HOROVOD_FLIGHT_RECORD"])
        self._attach_native_trace()
        return self.core

    def fusion_threshold(self) -> int:
        """Live fusion threshold: autotuned when enabled, knob otherwise."""
        if self.autotuner is not None:
            return self.autotuner.fusion_threshold
        return self.knobs["HOROVOD_FUSION_THRESHOLD"]

    def wire_policy(self) -> str:
        """Live wire-policy name for the fused gradient sync (ops/wire.py).

        Reads the knob via ``current`` (env wins, so tests and launchers
        can flip it without re-initializing) and, when tuning is on,
        refines 'auto' to the bandit's current policy arm — which rank 0
        broadcasts with the threshold, so every process compiles the same
        SPMD program.  A policy change re-traces, like a threshold change.
        """
        from .common.knobs import current
        from .ops.wire import validate_policy_name
        name = validate_policy_name(current("HOROVOD_WIRE_POLICY"))
        if name == "auto" and self.autotuner is not None:
            arm = self.autotuner.wire_policy
            if arm is not None:
                return arm
        return name

    def overlap_enabled(self) -> bool:
        """Live overlap-plane switch (env wins, the `current` contract —
        ops/overlap.py; docs/overlap.md)."""
        from .common.knobs import current
        return bool(current("HOROVOD_OVERLAP"))

    def overlap_depth(self) -> int:
        """Live microbatch-pipeline depth: the knob, refined to the
        bandit's current depth arm when tuning is on — broadcast with the
        threshold so all ranks compile identical SPMD programs (a depth
        change re-traces, like a threshold change)."""
        from .common.knobs import current
        from .ops.overlap import MAX_OVERLAP_DEPTH
        depth = int(current("HOROVOD_OVERLAP_DEPTH"))
        if not 1 <= depth <= MAX_OVERLAP_DEPTH:
            raise ValueError(
                f"HOROVOD_OVERLAP_DEPTH={depth} invalid; must be in "
                f"[1, {MAX_OVERLAP_DEPTH}] (docs/overlap.md)")
        if self.autotuner is not None:
            arm = self.autotuner.overlap_depth
            if arm is not None:
                return arm
        return depth

    def zero_level(self) -> int:
        """Live default ZeRO weight-update sharding level (env-live via
        ``current``; the zero chain's kwarg wins — parallel/zero.py,
        docs/zero.md)."""
        from .common.knobs import current
        from .parallel.zero import resolve_zero_level
        return resolve_zero_level(int(current("HOROVOD_ZERO_LEVEL")))

    def zero_ag_prefetch(self) -> int:
        """Live ZeRO-3 param all-gather prefetch depth: the knob,
        refined to the bandit's tuned overlap-depth arm when tuning is
        on — the SAME arm dimension the microbatch pipeline tunes, so
        one broadcast covers both planes and all ranks compile
        identical SPMD programs (docs/zero.md)."""
        from .common.knobs import current
        from .ops.overlap import MAX_OVERLAP_DEPTH
        depth = int(current("HOROVOD_ZERO_AG_PREFETCH"))
        if not 1 <= depth <= MAX_OVERLAP_DEPTH:
            raise ValueError(
                f"HOROVOD_ZERO_AG_PREFETCH={depth} invalid; must be in "
                f"[1, {MAX_OVERLAP_DEPTH}] (docs/zero.md)")
        if self.autotuner is not None:
            arm = self.autotuner.overlap_depth
            if arm is not None:
                return arm
        return depth

    # -------------------------------------------------------------- metrics
    def metrics_snapshot(self) -> Dict[str, Any]:
        """Point-in-time view of every metric family this process holds
        (the public ``hvd.metrics_snapshot()``): registry values refreshed
        from their live sources — native controller counters/histograms,
        bucket-plan cache, stall inspector — in one JSON-able dict."""
        from .utils import metrics as M
        M.RUNTIME_SIZE.set(self.size())
        M.RUNTIME_LOCAL_SIZE.set(self.local_size())
        # Native build tag (docs/static-analysis.md): loaded_build_info
        # never forces a library load — a pure-SPMD process that built no
        # native core reports nothing rather than paying a csrc build.
        from .common import basics as _basics
        binfo = _basics.loaded_build_info()
        if binfo is not None:
            M.NATIVE_SANITIZER_BUILD.set(
                1, sanitizer=binfo.get("sanitizer", "none"))
        M.PLAN_CACHE_HITS.set_total(self.plan_cache.hits)
        M.PLAN_CACHE_MISSES.set_total(self.plan_cache.misses)
        if self.stall_inspector is not None:
            M.STALL_PENDING.set(self.stall_inspector.pending_count())
        if self.core is not None and getattr(self.core, "_h", None):
            try:
                M.import_core_metrics(self.core.metrics())
            except Exception:
                pass  # a closing core must not break the snapshot
            # Watch plane: the natively-windowed hvd_*_rate gauges ride
            # the same snapshot (csrc/window.h; docs/watch.md).
            try:
                M.import_window_rates(self.core.metrics_window())
            except Exception:
                pass  # pre-watch library or closing core: rates absent
            # Perf plane: the native per-op-name aggregates ride the
            # same snapshot (hvd_perf_native_op_* families).
            try:
                from .perf.ledger import import_op_stats
                import_op_stats(self.core)
            except Exception:
                pass
        # Memory plane (perf/memstats.py; docs/memory.md): sample the
        # measured ledger on the snapshot cadence — the hvd_mem_*
        # families ride THIS snapshot into the publisher, the series
        # store and the committed mem-* rules.
        try:
            from .perf import memstats
            memstats.sample(core=self.core)
        except Exception:
            pass  # sampling must never break a snapshot
        return M.REGISTRY.snapshot()

    def _heartbeat_payload(self) -> Dict[str, Any]:
        """One heartbeat for the health plane (utils/health.py): step
        progress, native core liveness and the pending-collective count
        — the field fleet-stall attribution keys on."""
        from .utils.health import heartbeat_payload
        pending = None
        if self.stall_inspector is not None:
            pending = self.stall_inspector.pending_count()
        core = self.core
        if core is not None and not getattr(core, "_h", None):
            core = None  # closing core: heartbeat must not touch it
        return heartbeat_payload(self._process_index,
                                 clock=self.clock_sync, core=core,
                                 pending_collectives=pending)

    # ------------------------------------------------------------- lifecycle
    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        # Final heartbeat while the core is still alive: the postmortem's
        # last-known state for this rank.
        if self.heartbeat is not None:
            self.heartbeat.close()
        # Final metrics publish while the native core is still alive, so
        # the straggler report sees complete histograms.
        if self.metrics_publisher is not None:
            self.metrics_publisher.close()
        # Final perf-report publish: the fleet /perf view keeps this
        # rank's last decomposition after it exits.
        if self.perf_publisher is not None:
            self.perf_publisher.close()
        # Tracing teardown order: final native drain while the core is
        # alive, final chunk publish while the rendezvous may still be
        # up, then close the local file.
        if self._trace_drainer is not None:
            self._trace_drainer.close()
        if self.timeline_publisher is not None:
            self.timeline_publisher.close()
        if self.timeline is not None:
            self.timeline.close()
        if self.autotuner is not None:
            self.autotuner.close()
        if self.stall_inspector is not None:
            self.stall_inspector.close()
        if self.core is not None:
            self.core.shutdown()
            self.core.close()

    # ------------------------------------------------------------- timeline
    def _start_timeline_publisher(self) -> None:
        """Chunk publishing to the rendezvous 'timeline' scope, when a
        server is known — what GET /timeline and --timeline-merge read."""
        addr = self.knobs["HOROVOD_RENDEZVOUS_ADDR"]
        port = self.knobs["HOROVOD_RENDEZVOUS_PORT"]
        if not (addr and port) or self.timeline is None:
            return
        from .utils.timeline import TimelinePublisher
        try:
            # Replica-fleet lane namespacing (docs/timeline.md): a
            # nonzero serving replica id stamps the chunks so the merged
            # view renders replica{K}.rank{N} lanes.
            replica = int(self.knobs["HOROVOD_SERVE_REPLICA_ID"])
        except Exception:
            replica = 0
        self.timeline_publisher = TimelinePublisher(
            addr=addr, port=port, rank=self._process_index,
            timeline=self.timeline,
            interval=self.knobs["HOROVOD_TIMELINE_MERGE_INTERVAL"],
            clock=self.clock_sync, replica=replica)

    def _attach_native_trace(self) -> None:
        """Pump the native core's span ring into the timeline (idempotent;
        called whenever either side comes up after the other)."""
        if self.core is None or self.timeline is None \
                or self._trace_drainer is not None:
            return
        from .utils.timeline import NativeTraceDrainer
        self._trace_drainer = NativeTraceDrainer(self.core, self.timeline)

    def start_timeline(self, path: str, mark_cycles: bool = False) -> None:
        """Runtime-activated timeline (reference: operations.cc:740-769)."""
        from .utils.timeline import Timeline
        self.stop_timeline()
        if self.clock_sync is None:
            addr = self.knobs["HOROVOD_RENDEZVOUS_ADDR"]
            port = self.knobs["HOROVOD_RENDEZVOUS_PORT"]
            if addr and port:
                from .utils.clocksync import ClockSync
                self.clock_sync = ClockSync(addr, port)
        self.timeline = Timeline(path, mark_cycles=mark_cycles,
                                 clock=self.clock_sync,
                                 rank=self._process_index)
        self._start_timeline_publisher()
        self._attach_native_trace()

    def stop_timeline(self) -> None:
        if self._trace_drainer is not None:
            self._trace_drainer.close()
            self._trace_drainer = None
        if self.timeline_publisher is not None:
            self.timeline_publisher.close()
            self.timeline_publisher = None
        if self.timeline is not None:
            self.timeline.close()
            self.timeline = None


# ----------------------------------------------------------------- module API
def init(mesh_spec: Optional[str] = None,
         devices: Optional[Sequence[Any]] = None,
         **overrides: Any) -> Runtime:
    """Initialize the process-global runtime (idempotent).

    The analog of ``hvd.init()`` -> InitializeHorovodOnce (reference:
    operations.cc:651-699); callers block until the runtime is usable.
    """
    global _runtime
    with _lock:
        if _runtime is None:
            _runtime = Runtime(knobs=Knobs(overrides or None),
                               devices=devices, mesh_spec=mesh_spec)
            atexit.register(shutdown)
        return _runtime


def is_initialized() -> bool:
    return _runtime is not None


def get() -> Runtime:
    if _runtime is None:
        raise RuntimeError(
            "horovod_tpu has not been initialized; call hvd.init() first "
            "(reference semantics: operations.cc:695-697 blocks until init)")
    return _runtime


def shutdown() -> None:
    """The analog of ``hvd.shutdown()`` (reference: operations.cc:731-738)."""
    global _runtime
    with _lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None
