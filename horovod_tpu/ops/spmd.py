"""SPMD (in-jit) collectives over named mesh axes.

These are the primitives the compiled data plane uses — thin, explicit
wrappers over XLA's ICI collectives, replacing the reference's NCCL/MPI/Gloo
execution backends (reference: horovod/common/ops/*).  They must be called
inside a `shard_map` / `pjit` context that binds the axis name.

Unlike the reference — where each backend reimplements
allreduce/allgather/broadcast/alltoall per transport (reference:
nccl_operations.cc, mpi_operations.cc, gloo_operations.cc) — one
implementation serves every topology: the mesh axis determines whether the
collective rides ICI (within a slice) or DCN (across slices).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..common.reduce_op import ReduceOp

AxisName = Union[str, Sequence[str]]


def _axis_size(axis_name: AxisName) -> jax.Array:
    return lax.psum(1, axis_name)


def _hier_knob(name: str) -> bool:
    """Trace-time read of a HOROVOD_HIERARCHICAL_* knob (reference:
    common.h:81-82)."""
    from ..common.knobs import current
    return bool(current(name))


def allreduce(x: jax.Array, axis_name: AxisName,
              op: ReduceOp = ReduceOp.AVERAGE,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0) -> jax.Array:
    """Allreduce over a mesh axis.

    Average follows the reference's convert-to-postscale trick: SUM with a
    1/size postscale (reference: operations.cc:948-1056 AVERAGE->postscale).

    On a two-level ``(dcn.X, ici.X)`` axis pair with
    HOROVOD_HIERARCHICAL_ALLREDUCE set, routes through the two-stage
    reduce_scatter/dcn-allreduce/all_gather algorithm (reference:
    nccl_operations.cc:188-319) so DCN carries 1/ici_size of the bytes.
    """
    from ..parallel.hierarchical import hierarchical_allreduce, split_hierarchy
    pair = split_hierarchy(axis_name)
    if pair is not None and _hier_knob("HOROVOD_HIERARCHICAL_ALLREDUCE"):
        return hierarchical_allreduce(x, ici_axis=pair[1], dcn_axis=pair[0],
                                      op=op, prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor)
    if prescale_factor != 1.0:
        x = x * prescale_factor
    if op == ReduceOp.SUM:
        out = lax.psum(x, axis_name)
    elif op == ReduceOp.AVERAGE:
        out = lax.pmean(x, axis_name)
    elif op == ReduceOp.MIN:
        out = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        out = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        # No hardware product-reduce; gather then multiply. Fine for the
        # rare PRODUCT op (reference exposes it but no backend fast-paths it).
        g = lax.all_gather(x, axis_name)
        out = jnp.prod(g, axis=0)
    elif op == ReduceOp.ADASUM:
        from ..parallel.adasum import adasum_allreduce
        out = adasum_allreduce(x, axis_name)
    else:
        raise ValueError(f"unknown ReduceOp {op!r}")
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return out


def allgather(x: jax.Array, axis_name: AxisName, axis: int = 0) -> jax.Array:
    """Concatenate per-worker tensors along ``axis`` (reference semantics:
    allgather concatenates along the first dimension,
    collective_operations.h:133-204).

    HOROVOD_HIERARCHICAL_ALLGATHER on a two-level axis pair gathers over
    ICI first, then DCN (reference: MPIHierarchicalAllgather,
    mpi_operations.cc)."""
    from ..parallel.hierarchical import (hierarchical_allgather,
                                         split_hierarchy)
    pair = split_hierarchy(axis_name)
    if pair is not None and _hier_knob("HOROVOD_HIERARCHICAL_ALLGATHER"):
        return hierarchical_allgather(x, ici_axis=pair[1], dcn_axis=pair[0],
                                      axis=axis)
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def broadcast(x: jax.Array, axis_name: AxisName, root: int = 0) -> jax.Array:
    """Broadcast the root worker's value to all workers on the axis.

    Non-root contributions are replaced by zeros via ``where`` (not
    multiplication) so NaN/Inf garbage on non-root workers — e.g.
    uninitialized params awaiting a checkpoint broadcast — cannot poison
    the psum."""
    idx = lax.axis_index(axis_name)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name)


def alltoall(x: jax.Array, axis_name: AxisName,
             split_axis: int = 0, concat_axis: int = 0) -> jax.Array:
    """Equal-split all-to-all (the sequence/expert-parallel primitive;
    reference: operations.cc:1136-1198)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def reducescatter(x: jax.Array, axis_name: AxisName,
                  op: ReduceOp = ReduceOp.SUM,
                  scatter_axis: int = 0) -> jax.Array:
    """Reduce-scatter: each worker gets one reduced shard.  The building
    block of hierarchical allreduce (reference: nccl_operations.cc:188-319)
    and FSDP-style gradient sharding."""
    out = lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                           tiled=True)
    if op == ReduceOp.AVERAGE:
        out = out / _axis_size(axis_name)
    return out


def barrier(axis_name: AxisName) -> jax.Array:
    """A synchronization point: a zero-byte-ish psum all workers join."""
    return lax.psum(jnp.zeros((), jnp.int32), axis_name)


def ring_permute(x: jax.Array, axis_name: AxisName,
                 shift: int = 1) -> jax.Array:
    """Send to (i+shift) mod n on the axis ring — the primitive under ring
    attention and Adasum's recursive halving (no reference equivalent;
    SURVEY.md §5 long-context requirement)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)
