"""Version-compat shims for jax APIs that moved between releases."""

from __future__ import annotations

try:  # jax >= 0.6: promoted to top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x / 0.5.x
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["shard_map"]
