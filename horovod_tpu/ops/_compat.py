"""Version-compat shims for jax APIs that moved between releases."""

from __future__ import annotations

import inspect

try:  # jax >= 0.6: promoted to top level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x / 0.5.x
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    # Older jax spells the varying-mesh-axes check `check_rep`; callers in
    # this repo use the current `check_vma` name — translate.
    def shard_map(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, **kwargs)

__all__ = ["shard_map"]
