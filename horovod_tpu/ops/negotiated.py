"""Framework-neutral negotiated dispatch over the native controller.

The torch frontend negotiates every async op through the C++ core
(torch/mpi_ops.py); this module is the SYNCHRONOUS counterpart for
frontends whose ops complete inline (TensorFlow eager):

  * ``SyncNegotiator.run`` submits one collective to the controller and
    pumps responses until it executes — peers' collectives that this rank
    never submitted are answered with ZERO DUMMY tensors (only possible
    for a rank that has JOINed).
  * ``SyncNegotiator.join`` implements the uneven-input Join protocol
    (reference: tensorflow/mpi_ops.py:334 join() -> horovod_join,
    controller JOIN/JOIN_DONE handling controller.cc:254-307): signal no
    more collectives, then keep serving peers until everyone joined.

Signatures use the same wire format as the torch frontend
(``dtype:shape:kind:extra`` joined by ``+`` for groups), so the
controller's consistency validation and fusion logic see one dialect.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..common import basics as _basics
from ..common.exceptions import HorovodInternalError
from ..common.reduce_op import ReduceOp, Sum
from . import collectives as _C

_NP_SIG = {"float32": "f32", "float64": "f64", "float16": "f16",
           "bfloat16": "bf16", "int32": "i32", "int64": "i64",
           "int16": "i16", "int8": "i8", "uint8": "u8", "bool": "b1"}
_NP_SIG_INV = {v: k for k, v in _NP_SIG.items()}


def np_signature(arr: np.ndarray, kind: str, extra: str = "") -> str:
    """Consistency key for one numpy array (same layout the torch
    frontend emits: torch/mpi_ops.py _signature)."""
    shape = "x".join(str(s) for s in arr.shape)
    return f"{_NP_SIG.get(arr.dtype.name, arr.dtype.name)}:{shape}:" \
           f"{kind}:{extra}"


def np_zeros_from_signature(sig: str) -> np.ndarray:
    """Zero dummy for a collective this rank never submitted (reference:
    JoinOp zero tensor, collective_operations.cc:262)."""
    dt, shape, _kind, _extra = sig.split(":", 3)
    dims = tuple(int(s) for s in shape.split("x") if s)
    # unknown tokens are verbatim numpy dtype names (np_signature passes
    # them through) — resolving them keeps the joined rank's SPMD program
    # identical to its peers'; a truly bogus token fails loudly below
    name = _NP_SIG_INV.get(dt, dt)
    if name == "bfloat16":
        import ml_dtypes
        return np.zeros(dims, ml_dtypes.bfloat16)
    return np.zeros(dims, np.dtype(name))


def zero_participate(sig: str, local_size: int = 1) -> None:
    """Serve one negotiated response batch with zero dummies so the
    peers' collective completes (the op/root ride the signature's extra
    field — the compiled SPMD program must match on every process)."""
    parts = sig.split("+") if sig else [""]
    fields = parts[0].split(":", 3)
    kind = fields[2] if len(fields) >= 3 else "allreduce"
    extra = fields[3] if len(fields) >= 4 else ""
    # process_local marking matters: peers submitted marked arrays, so a
    # dummy whose leading dim happens to equal local_size() must NOT be
    # read as a per-chip axis (ops/collectives._per_chip) — the joined
    # rank would compile a different SPMD program than its peers.
    arrs = [_C.process_local(np_zeros_from_signature(p)) for p in parts]
    if kind == "grouped_allreduce":
        _C.grouped_allreduce(arrs, op=ReduceOp(int(extra)) if extra
                             else Sum)
    elif kind == "allreduce":
        _C.allreduce(arrs[0], op=ReduceOp(int(extra)) if extra else Sum)
    elif kind == "allgather":
        _C.allgather(arrs[0])
    elif kind == "allgather_ragged":
        # 0-row contribution: peers' concat sees nothing from us.
        _C.allgather_ragged([arrs[0]] * local_size)
    elif kind == "broadcast":
        _C.broadcast(arrs[0], root_rank=int(extra) if extra else 0)
    else:
        # alltoall's host-side size exchange cannot be mirrored by a
        # joined rank; the reference restricts Join the same way.
        raise HorovodInternalError(
            f"collective kind {kind!r} is not supported while this rank "
            "has joined (reference: Join supports "
            "allreduce/allgather/broadcast)")


class SyncNegotiator:
    """Controller-negotiated execution for synchronous frontends.

    One instance per runtime; thread-safe for the single-caller pattern
    TF uses (ops run on the python thread that drives training).
    """

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.RLock()
        self._pending: Dict[str, Callable[[], Any]] = {}
        self._results: Dict[str, Any] = {}
        self._counter = 0

    def _core(self):
        core = self._rt.ensure_core()
        if core is None:
            raise HorovodInternalError(
                "negotiated dispatch requires the native core (size > 1 "
                "with the controller enabled)")
        return core

    def auto_name(self, prefix: str) -> str:
        with self._lock:
            self._counter += 1
            return f"{prefix}.tfneg.{self._counter}"

    def run(self, name: str, sig: str, op_type: int, nbytes: int,
            execute: Callable[[], Any], timeout_s: float = 300.0) -> Any:
        """Submit + pump until this op's negotiated slot runs it."""
        # Chaos straggler hook: a stall event with point "negotiate"
        # slows every negotiated op on the target rank, dragging its
        # negotiation ages up so the straggler report names it.
        from .. import chaos as _chaos
        _chaos.maybe_stall("negotiate")
        core = self._core()
        with self._lock:
            self._pending[name] = execute
        # Tracing: NEGOTIATE covers submit -> globally-agreed response
        # (ended in _execute_response, where QUEUE/EXEC take over) — the
        # reference's per-tensor phase lifecycle, timeline.cc:244-254.
        # getattr: test fakes stand in for the runtime without one.
        tl = getattr(self._rt, "timeline", None)
        if tl is not None:
            tl.begin(name, "NEGOTIATE")
        core.submit(name, sig, op_type, nbytes)
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                if name in self._results:
                    return self._results.pop(name)
            if time.monotonic() > deadline:
                raise HorovodInternalError(
                    f"timed out after {timeout_s}s negotiating {name!r} "
                    "(stalled peer?)")
            # Poll-first: in the locked-epoch steady state the response
            # was built inline by submit() (csrc plan epochs), so the
            # non-blocking pop usually lands it without entering the
            # native condition-variable wait at all.
            resp = core.poll() or core.wait(timeout_s=1.0)
            if resp is not None:
                self._execute_response(resp)

    def _execute_response(self, resp) -> None:
        if resp.type == "ERROR":
            raise HorovodInternalError(
                f"controller error: {resp.error}")
        if resp.type in ("JOIN_DONE", "SHUTDOWN"):
            return
        tl = getattr(self._rt, "timeline", None)
        arrival_us = tl.now_us() if tl is not None else 0.0
        for name, sig in zip(resp.names,
                             resp.sigs or [""] * len(resp.names)):
            with self._lock:
                execute = self._pending.pop(name, None)
            if execute is not None:
                if tl is not None:
                    # NEGOTIATE ends when the agreed response arrived;
                    # QUEUE is the wait behind batch-mates executed
                    # before this one; EXEC is the collective itself.
                    tl.end(name, "NEGOTIATE", ts_us=arrival_us)
                    tl.begin(name, "QUEUE", ts_us=arrival_us)
                    tl.end(name, "QUEUE")
                # Measured execution (utils/profiler.timed): the xprof
                # range correlates with device activity, and the real
                # duration lands on the EXEC span as a complete (X)
                # event anchored at the op's start — so the timeline
                # carries per-collective durations, not zero-width
                # begin/end pairs.
                from ..utils.profiler import timed
                result, dur_us = timed(execute, name="HOROVOD_EXEC")
                if tl is not None:
                    tl.record_op(name, "EXEC", resp.total_bytes,
                                 duration_us=dur_us)
                with self._lock:
                    self._results[name] = result
            else:
                zero_participate(sig, self._rt.local_size())

    def join(self, timeout_s: float = 300.0) -> int:
        """Reference TF join(): no more collectives from this rank; serve
        stragglers with zeros until every rank joined.  Returns the last
        rank to join (carried in JOIN_DONE, matching the torch path)."""
        core = self._core()
        core.join()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            resp = core.wait(timeout_s=1.0)
            if resp is None:
                continue
            if resp.type == "JOIN_DONE":
                return resp.total_bytes
            self._execute_response(resp)
        raise HorovodInternalError("join() timed out waiting for peers")


OP_ALLREDUCE = _basics.OP_ALLREDUCE
OP_ALLGATHER = _basics.OP_ALLGATHER
OP_BROADCAST = _basics.OP_BROADCAST
OP_JOIN = _basics.OP_JOIN
