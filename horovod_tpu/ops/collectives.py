"""Eager (host-level) collectives: the classic ``hvd.allreduce`` surface.

The reference's public ops take one tensor per rank-process and return the
reduced tensor, executing asynchronously on a background thread (reference:
operations.cc:919-1198 Enqueue*, torch/mpi_ops.py:95-841).  On TPU the worker
unit is the *chip* and a single Python process drives ``local_size()`` chips,
so the eager API here takes a **leading per-chip axis**:

    x.shape == (local_size, *tensor_shape)   # one slice per local chip

and returns the same layout.  A tensor *without* that leading axis is treated
as identical on every local chip (every chip-rank holds the same value —
exactly the reference's semantics when all ranks pass the same tensor).

Execution: each op is a jitted ``shard_map`` over the flattened mesh, cached
by (shape, dtype, op) — the compiled-program cache plays the role of the
reference's response cache for eager mode.  Multi-host processes contribute
their local shard via ``jax.make_array_from_process_local_data``; XLA runs
the collective over ICI/DCN.

Async API: ``allreduce_async`` & friends return a ``Handle``; ``synchronize``
/ ``poll`` mirror the reference's handle manager (reference:
torch/mpi_ops.py:843-881, torch/handle_manager.{h,cc}).  JAX dispatch is
already async — the handle wraps the in-flight on-device value.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import runtime as _rt
from ..common.reduce_op import ReduceOp, Average
from ..utils import metrics as _metrics
from . import spmd
from .fusion import fused_apply

Array = jax.Array
TensorLike = Union[jax.Array, np.ndarray, float, int]


# --------------------------------------------------------------- input marking
class ProcessLocalArray(np.ndarray):
    """Marks an array as *one value per process*: the eager layer replicates
    it across local chips instead of interpreting a leading dim that happens
    to equal local_size() as a per-chip axis (see :func:`_per_chip`)."""
    _hvd_per_chip = False


def process_local(x: TensorLike) -> np.ndarray:
    """View ``x`` as a process-level tensor with no per-chip leading axis."""
    arr = np.asarray(x)
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return arr.reshape(arr.shape).view(ProcessLocalArray)


# --------------------------------------------------------------------- mesh IO
def _mesh_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


def _flat_spec(mesh: Mesh) -> P:
    """PartitionSpec sharding axis 0 over *all* mesh axes (chips flattened)."""
    return P(_mesh_axes(mesh))


def _per_chip(rt: "_rt.Runtime", x: TensorLike) -> Tuple[jnp.ndarray, bool]:
    """Normalize input to a host array of shape [local_size, ...].

    Returns (array, had_chip_axis)."""
    arr = jnp.asarray(x)
    ls = rt.local_size()
    if arr.ndim >= 1 and arr.shape[0] == ls and getattr(
            x, "_hvd_per_chip", True) is not False:
        return arr, True
    # Replicate this process's single value across its chips.
    return jnp.broadcast_to(arr[None], (ls,) + arr.shape), False


def _make_global(rt: "_rt.Runtime", local: jnp.ndarray) -> Array:
    """Assemble the global [size, ...] array sharded over the mesh chips."""
    mesh = rt.mesh
    sharding = NamedSharding(mesh, _flat_spec(mesh))
    if rt.process_size() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def _to_local(rt: "_rt.Runtime", global_arr: Array) -> Array:
    """Extract this process's [local_size, ...] slice of the result."""
    if rt.process_size() == 1:
        return global_arr
    shards = sorted(global_arr.addressable_shards,
                    key=lambda s: (s.index[0].start or 0) if s.index else 0)
    if len(shards) == 1:
        return shards[0].data
    # Shards live on different local devices; assemble on host (jnp.stack
    # across device-committed arrays is rejected by jax).
    return jnp.asarray(np.concatenate([np.asarray(s.data) for s in shards],
                                      axis=0))


# ----------------------------------------------------------------- jit caching
@functools.lru_cache(maxsize=4096)
def _compiled(mesh_id: int, kind: str, **static) -> Any:
    """Build + cache the jitted shard_map program for an eager op.

    Keyed by mesh identity and op signature — the compiled-program cache is
    the eager path's response cache (reference: response_cache.h:44-100)."""
    rt = _rt.get()
    mesh = rt.mesh
    axes = _mesh_axes(mesh)
    spec = _flat_spec(mesh)

    from ._compat import shard_map

    def annotate(jitted):
        # NVTX-range analog (reference: nvtx_op_range.h wraps every
        # user-facing op): xprof correlates this host range with the
        # device activity it launches; no-op outside a trace session.
        range_name = f"HOROVOD_{kind.upper()}"

        def dispatch(*args):
            with jax.profiler.TraceAnnotation(range_name):
                return jitted(*args)
        return dispatch

    def wrap(body, out_specs=None):
        return annotate(jax.jit(shard_map(body, mesh=mesh,
                                          in_specs=(spec,),
                                          out_specs=out_specs or spec)))

    if kind == "allreduce":
        op = ReduceOp(static["op"])
        pre, post = static["pre"], static["post"]

        def body(x):  # x: [1, ...] per chip
            return spmd.allreduce(x, axes, op=op, prescale_factor=pre,
                                  postscale_factor=post)
        return wrap(body)
    if kind == "grouped_allreduce":
        op = ReduceOp(static["op"])
        pre, post = static["pre"], static["post"]
        plan = static["plan"]

        def gbody(*leaves):
            # Leaves arrive as [1, ...] per-chip shards; ravel each so the
            # fusion plan (computed over raveled sizes) lines up.
            flat = [jnp.ravel(l) for l in leaves]
            outs = fused_apply(
                flat, plan,
                lambda buf: spmd.allreduce(buf, axes, op=op,
                                           prescale_factor=pre,
                                           postscale_factor=post))
            return tuple(jnp.reshape(o, l.shape)
                         for o, l in zip(outs, leaves))
        n = static["n_leaves"]
        return annotate(jax.jit(shard_map(
            gbody, mesh=mesh, in_specs=(spec,) * n,
            out_specs=(spec,) * n)))
    if kind == "allgather":
        def agbody(x):  # [1, rows, ...] -> full concat, replicated out
            g = spmd.allgather(x, axes, axis=0)
            return g
        # The gathered result is identical on every chip (out_specs=P());
        # jax's varying-mesh-axes check can't prove that, so disable it.
        return annotate(jax.jit(shard_map(agbody, mesh=mesh,
                                          in_specs=(spec,),
                                          out_specs=P(),
                                          check_vma=False)))
    if kind == "broadcast":
        root = static["root"]

        def bbody(x):
            return spmd.broadcast(x, axes, root=root)
        return wrap(bbody)
    if kind == "alltoall":
        def a2abody(x):  # [1, size*block, ...] equal splits
            y = jnp.squeeze(x, axis=0)
            out = spmd.alltoall(y, axes, split_axis=0, concat_axis=0)
            return out[None]
        return wrap(a2abody)
    if kind == "reducescatter":
        op = ReduceOp(static["op"])

        def rsbody(x):
            y = jnp.squeeze(x, axis=0)
            out = spmd.reducescatter(y, axes, op=op, scatter_axis=0)
            return out[None]
        return wrap(rsbody)
    if kind == "barrier":
        def barbody(x):
            # Fold the collective's result into the output so jit cannot
            # dead-code-eliminate the psum.
            z = spmd.barrier(axes)
            return x + z.astype(x.dtype)
        return wrap(barbody)
    raise ValueError(kind)


def _mesh_key(rt) -> int:
    return id(rt.mesh)


def _tl(rt, name: Optional[str], kind: str, nbytes: int,
        t0: Optional[float] = None) -> None:
    """Timeline emit for one eager collective (reference: per-op activities
    from every backend, e.g. nccl_operations.cc:144-181).  X events carry
    the real host-side latency measured from ``t0`` (the same window _rec
    feeds the metrics histogram) and are anchored at span START, so they
    render where the op ran, at their true width — not as 1 µs slivers at
    completion time.  The negotiated torch path adds NEGOTIATE/QUEUE
    phases around these.

    Auto-generated names ('x.noname.N') collapse to their prefix (the
    timeline's collapse_name): each unique name allocates a chrome pid +
    metadata entry forever, so per-call unique names would leak memory
    and bloat the trace."""
    if rt.timeline is not None:
        if not name:
            name = kind.lower()
        dur_us = None
        if t0 is not None:
            dur_us = (time.perf_counter() - t0) * 1e6
        rt.timeline.record_op(name, kind, nbytes, duration_us=dur_us)


def _rec(kind: str, nbytes: int, t0: float) -> None:
    """Metrics emit for one eager collective: count, payload bytes, and
    host-side latency (assembly + dispatch, plus completion wherever the
    op blocks — the sync allreduce under the stall inspector does)."""
    op = kind.lower()
    _metrics.COLLECTIVE_OPS.inc(op=op)
    _metrics.COLLECTIVE_BYTES.inc(nbytes, op=op)
    _metrics.COLLECTIVE_LATENCY.observe(time.perf_counter() - t0, op=op)


# ------------------------------------------------------------------ public API
def allreduce(tensor: TensorLike,
              average: Optional[bool] = None,
              name: Optional[str] = None,
              op: ReduceOp = Average,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0) -> Array:
    """Allreduce across all chips; returns per-chip results [local_size, ...].

    Mirrors ``hvd.allreduce`` incl. the deprecated ``average`` flag
    (reference: tensorflow/__init__.py:54-155, torch/mpi_ops.py:95-139)."""
    rt = _rt.get()
    t0 = time.perf_counter()
    if average is not None:
        op = ReduceOp.AVERAGE if average else ReduceOp.SUM
    if rt.stall_inspector is not None and name:
        rt.stall_inspector.record_submit(name)
    local, had_axis = _per_chip(rt, tensor)
    g = _make_global(rt, local)
    fn = _compiled(_mesh_key(rt), "allreduce", op=int(op),
                   pre=float(prescale_factor), post=float(postscale_factor))
    out = fn(g)
    if rt.stall_inspector is not None and name:
        # The watchdog must observe actual completion, not async dispatch:
        # block before clearing the pending entry (the sync allreduce API is
        # blocking in the reference too; use allreduce_async to overlap).
        jax.block_until_ready(out)
        rt.stall_inspector.record_complete(name)
    res = _to_local(rt, out)
    _rec("ALLREDUCE", int(local.nbytes), t0)
    _tl(rt, name, "ALLREDUCE", int(local.nbytes), t0)
    return res if had_axis else res[0]


def grouped_allreduce(tensors: Sequence[TensorLike],
                      average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: ReduceOp = Average,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0) -> List[Array]:
    """Fused multi-tensor allreduce (reference: operations.cc:919-1056
    EnqueueTensorAllreduces; torch ``grouped_allreduce``).  Tensors are
    bucketed by the fusion threshold and reduced in few large collectives."""
    rt = _rt.get()
    t0 = time.perf_counter()
    if average is not None:
        op = ReduceOp.AVERAGE if average else ReduceOp.SUM
    pairs = [_per_chip(rt, t) for t in tensors]
    locals_ = [p[0] for p in pairs]
    had = [p[1] for p in pairs]
    # Plan over *per-chip raveled* sizes: inside shard_map each leaf is a
    # [1, ...] shard that gets raveled before bucketing.
    shapes = [(int(np.prod(l.shape[1:])) if l.ndim > 1 else 1,)
              for l in locals_]
    dtypes = [l.dtype for l in locals_]
    plan = rt.plan_cache.get(shapes, dtypes, rt.fusion_threshold())
    gs = [_make_global(rt, l) for l in locals_]
    fn = _compiled(_mesh_key(rt), "grouped_allreduce", op=int(op),
                   pre=float(prescale_factor), post=float(postscale_factor),
                   plan=plan, n_leaves=len(gs))
    outs = fn(*gs)
    res = [_to_local(rt, o) for o in outs]
    _rec("GROUPED_ALLREDUCE", int(sum(l.nbytes for l in locals_)), t0)
    _tl(rt, name, "GROUPED_ALLREDUCE", int(sum(l.nbytes for l in locals_)),
        t0)
    return [r if h else r[0] for r, h in zip(res, had)]


def allgather(tensor: TensorLike, name: Optional[str] = None) -> Array:
    """Concatenate every chip's tensor along axis 0 (reference:
    collective_operations.h:133-204).  Input is per-chip
    ``[local_size, rows, ...]``; output is ``[size*rows, ...]``.  For ragged
    first dims use :func:`allgather_ragged`."""
    rt = _rt.get()
    t0 = time.perf_counter()
    local, had = _per_chip(rt, tensor)
    g = _make_global(rt, local)
    fn = _compiled(_mesh_key(rt), "allgather")
    out = fn(g)  # replicated full concat [size, rows, ...]
    _rec("ALLGATHER", int(local.nbytes), t0)
    _tl(rt, name, "ALLGATHER", int(local.nbytes), t0)
    out = jnp.reshape(out, (-1,) + out.shape[2:])
    return out


def allgather_ragged(tensors: Sequence[TensorLike],
                     name: Optional[str] = None) -> Array:
    """Allgather with per-chip different first dims — the reference supports
    ragged allgather natively via per-rank size negotiation (reference:
    controller.cc:580-650 tensor sizes in Response).  Implemented by padding
    to the max first-dim, gathering, then slicing on the host."""
    rt = _rt.get()
    ls = rt.local_size()
    if len(tensors) != ls:
        raise ValueError(f"expected {ls} per-chip tensors, got {len(tensors)}")
    arrs = [jnp.asarray(t) for t in tensors]
    rows = [int(a.shape[0]) for a in arrs]
    # Host-side size exchange across processes (the negotiation analog).
    # process_allgather is process-major; collectives number chips by mesh
    # position, so re-index via the process->chip-position map.
    if rt.process_size() > 1:
        per_proc = np.asarray(process_allgather(
            np.array(rows, np.int64))).reshape(rt.process_size(), ls)
        all_rows = [0] * rt.size()
        for p, positions in enumerate(rt.chip_positions_by_process()):
            for j, pos in enumerate(positions):
                all_rows[pos] = int(per_proc[p, j])
    else:
        all_rows = rows
    max_rows = int(max(all_rows))
    padded = jnp.stack([
        jnp.pad(a, [(0, max_rows - a.shape[0])] + [(0, 0)] * (a.ndim - 1))
        for a in arrs])
    g = allgather(padded)  # [size*max_rows, ...] after reshape inside
    g = jnp.reshape(g, (len(all_rows), max_rows) + g.shape[1:])
    pieces = [g[i, :r] for i, r in enumerate(all_rows)]
    return jnp.concatenate(pieces, axis=0)


def broadcast(tensor: TensorLike, root_rank: int = 0,
              name: Optional[str] = None) -> Array:
    """Broadcast the value held by chip ``root_rank`` to all chips
    (reference: operations.cc:1096-1134)."""
    rt = _rt.get()
    t0 = time.perf_counter()
    local, had = _per_chip(rt, tensor)
    g = _make_global(rt, local)
    fn = _compiled(_mesh_key(rt), "broadcast", root=int(root_rank))
    out = fn(g)
    _rec("BROADCAST", int(local.nbytes), t0)
    _tl(rt, name, "BROADCAST", int(local.nbytes), t0)
    res = _to_local(rt, out)
    return res if had else res[0]


def alltoall(tensor: TensorLike,
             splits: Optional[TensorLike] = None,
             name: Optional[str] = None) -> Tuple[Array, Array]:
    """All-to-all with optional uneven splits; returns (output, recv_splits)
    like the reference (reference: operations.cc:1136-1198, torch/mpi_ops.py:
    759-841).  Per-chip input ``[local_size, rows, ...]``; ``splits`` is
    ``[local_size, size]`` (rows sent to each destination chip)."""
    rt = _rt.get()
    t0 = time.perf_counter()
    n = rt.size()
    local, had = _per_chip(rt, tensor)
    if splits is None:
        rows = local.shape[1]
        if rows % n != 0:
            raise ValueError(
                f"alltoall without splits requires rows ({rows}) divisible "
                f"by size ({n})")
        g = _make_global(rt, local)
        fn = _compiled(_mesh_key(rt), "alltoall")
        out = _to_local(rt, fn(g))
        _rec("ALLTOALL", int(local.nbytes), t0)
        _tl(rt, name, "ALLTOALL", int(local.nbytes), t0)
        recv = jnp.full((rt.local_size(), n), rows // n, jnp.int32)
        if not had:
            return out[0], recv[0]
        return out, recv

    # Uneven splits: pad each destination block to the global max block,
    # run the dense equal-split all_to_all, reassemble with recv splits.
    sp = np.asarray(splits, np.int64)
    if sp.ndim == 1:
        sp = np.broadcast_to(sp[None], (rt.local_size(), n)).copy()
    if rt.process_size() > 1:
        per_proc = np.asarray(process_allgather(sp)).reshape(
            rt.process_size(), rt.local_size(), n)
        all_sp = np.zeros((n, n), np.int64)  # [src_chip_pos, dst_chip_pos]
        for p, positions in enumerate(rt.chip_positions_by_process()):
            for j, pos in enumerate(positions):
                all_sp[pos] = per_proc[p, j]
    else:
        all_sp = sp  # [size, size]: all_sp[src, dst]
    max_blk = int(all_sp.max())
    ls = rt.local_size()
    pads = []
    for i in range(ls):
        off = 0
        blocks = []
        for d in range(n):
            c = int(sp[i, d])
            blk = local[i, off:off + c]
            blk = jnp.pad(blk, [(0, max_blk - c)] + [(0, 0)] * (blk.ndim - 1))
            blocks.append(blk)
            off += c
        pads.append(jnp.concatenate(blocks, axis=0))
    padded = jnp.stack(pads)  # [ls, n*max_blk, ...]
    g = _make_global(rt, padded)
    fn = _compiled(_mesh_key(rt), "alltoall")
    out = _to_local(rt, fn(g))  # [ls, n*max_blk, ...]
    _rec("ALLTOALL", int(local.nbytes), t0)
    _tl(rt, name, "ALLTOALL", int(local.nbytes), t0)
    # recv_splits[i, src] = all_sp[src, mesh position of local chip i]
    local_pos = rt.local_chip_positions()
    recv_np = np.stack([all_sp[:, local_pos[i]] for i in range(ls)])
    outs = []
    for i in range(ls):
        blocks = [out[i, s * max_blk: s * max_blk + int(recv_np[i, s])]
                  for s in range(n)]
        outs.append(jnp.concatenate(blocks, axis=0))
    if not had:
        return outs[0], jnp.asarray(recv_np[0], jnp.int32)
    # Ragged per-chip outputs can differ in rows; return list if ragged.
    rows_per = {int(r.sum()) for r in recv_np}
    if len(rows_per) == 1:
        return jnp.stack(outs), jnp.asarray(recv_np, jnp.int32)
    return outs, jnp.asarray(recv_np, jnp.int32)  # type: ignore


def reducescatter(tensor: TensorLike, op: ReduceOp = Average,
                  name: Optional[str] = None) -> Array:
    """Reduce across chips and scatter shards: chip i gets rows
    ``[i*rows/n : (i+1)*rows/n]`` of the reduction."""
    rt = _rt.get()
    t0 = time.perf_counter()
    local, had = _per_chip(rt, tensor)
    g = _make_global(rt, local)
    fn = _compiled(_mesh_key(rt), "reducescatter", op=int(op))
    out = _to_local(rt, fn(g))
    _rec("REDUCESCATTER", int(local.nbytes), t0)
    _tl(rt, name, "REDUCESCATTER", int(local.nbytes), t0)
    return out


def barrier() -> None:
    """Block until all processes/chips reach the barrier (reference:
    MPIController::Barrier, mpi_controller.cc:227)."""
    rt = _rt.get()
    t0 = time.perf_counter()
    g = _make_global(rt, jnp.zeros((rt.local_size(), 1), jnp.int32))
    fn = _compiled(_mesh_key(rt), "barrier")
    jax.block_until_ready(fn(g))
    _rec("BARRIER", 0, t0)
    _tl(rt, None, "BARRIER", 0, t0)


def process_allgather(x: np.ndarray) -> np.ndarray:
    """Host-side gather of a small numpy array from every process — used for
    size negotiation of ragged collectives (the reference exchanges sizes in
    the controller: mpi_controller.cc per-rank split exchange)."""
    rt = _rt.get()
    if rt.process_size() == 1:
        return np.asarray(x)[None]
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(np.asarray(x)))


# ------------------------------------------------------------------ async API
class Handle:
    """An in-flight collective (reference: handle_manager.{h,cc}).  JAX
    dispatch is asynchronous, so the value is already on its way; the handle
    exposes poll/synchronize semantics."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def poll(self) -> bool:
        try:
            ready = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda a: a.is_ready() if hasattr(a, "is_ready") else True,
                self._value))
            return all(ready)
        except Exception:
            return True

    def wait(self):
        return jax.block_until_ready(self._value)


def allreduce_async(tensor: TensorLike, average: Optional[bool] = None,
                    name: Optional[str] = None,
                    op: ReduceOp = Average) -> Handle:
    return Handle(allreduce(tensor, average=average, name=name, op=op))


def allgather_async(tensor: TensorLike, name: Optional[str] = None) -> Handle:
    return Handle(allgather(tensor, name=name))


def broadcast_async(tensor: TensorLike, root_rank: int = 0,
                    name: Optional[str] = None) -> Handle:
    return Handle(broadcast(tensor, root_rank=root_rank, name=name))


def synchronize(handle: Handle):
    """Wait for an async op (reference: torch/mpi_ops.py:843-881)."""
    return handle.wait()


def poll(handle: Handle) -> bool:
    return handle.poll()
