"""Gradient compression for collectives.

Mirrors the reference's pluggable compressor surface (reference:
horovod/torch/compression.py, horovod/tensorflow/compression.py:1-74):
``Compression.none`` and ``Compression.fp16`` with
``compress(tensor) -> (tensor, ctx)`` / ``decompress(tensor, ctx)``.

On TPU the natural wire dtype is **bfloat16** (MXU/ICI native); fp16 is kept
for parity.  Compression applies to the fused bucket, so one cast covers
many tensors.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


class Compressor:
    """Interface matching the reference's Compressor static methods."""

    @staticmethod
    def compress(tensor: jax.Array) -> Tuple[jax.Array, Any]:
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: jax.Array, ctx: Any) -> jax.Array:
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 for the wire (reference:
    compression.py FP16Compressor)."""

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(jnp.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class BF16Compressor(Compressor):
    """TPU-native wire compression: bfloat16 keeps fp32 range and is the
    ICI/MXU native narrow type (no reference equivalent; TPU addition)."""

    @staticmethod
    def compress(tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating) and \
                tensor.dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class Compression:
    """Namespace matching ``hvd.Compression`` (reference: compression.py)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor

    @staticmethod
    def by_name(name: str) -> type[Compressor]:
        """Wire-format name -> compressor (the cast formats of the
        wire-policy plane, ops/wire.py)."""
        try:
            return {"none": NoneCompressor, "fp16": FP16Compressor,
                    "bf16": BF16Compressor}[name]
        except KeyError:
            raise ValueError(f"no compressor named {name!r}") from None
