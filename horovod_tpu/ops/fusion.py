"""Tensor fusion: bucket plans + the bucket-plan cache.

The reference packs many small tensors into one persistent 128 MiB fusion
buffer per (device, framework, stream) and runs a single collective over it
(reference: fusion_buffer_manager.{h,cc}, controller.cc:778-915 FuseResponses,
knob HOROVOD_FUSION_THRESHOLD set at operations.cc:448).  On TPU the buffer
itself is unnecessary — XLA keeps the concatenated bucket in HBM and
`donate_argnums` aliases it in place — but the *planning* survives: grouping
gradients into few large same-dtype buckets turns hundreds of tiny `psum`s
into a handful of big ones that saturate ICI.

The reference's response cache memoizes negotiated responses so repeat
iterations skip coordination (reference: response_cache.h:44-100).  Its TPU
analog is the `BucketPlanCache` below: plans are keyed by the exact
(shapes, dtypes, threshold) signature of the step, so steady-state training
hits the cache every step.

All packing/unpacking code is jit-traceable (static shapes only).
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import metrics as _metrics


class Bucket:
    """One fused collective: a list of leaf indices sharing a dtype."""

    __slots__ = ("dtype", "indices", "sizes", "shapes", "nbytes")

    def __init__(self, dtype):
        self.dtype = dtype
        self.indices: List[int] = []
        self.sizes: List[int] = []
        self.shapes: List[Tuple[int, ...]] = []
        self.nbytes = 0

    def add(self, idx: int, shape: Tuple[int, ...], nbytes: int) -> None:
        self.indices.append(idx)
        self.shapes.append(tuple(shape))
        self.sizes.append(int(np.prod(shape)) if shape else 1)
        self.nbytes += nbytes


class BucketPlan:
    """A fusion plan for a flat list of tensors.

    Hashable *by value* so jit caches keyed on a plan don't recompile when
    an identical plan object is rebuilt (e.g. with the plan cache disabled).
    """

    def __init__(self, buckets: List[Bucket], num_leaves: int):
        self.buckets = buckets
        self.num_leaves = num_leaves
        self._sig = (num_leaves, tuple(
            (str(b.dtype), tuple(b.indices), tuple(b.shapes))
            for b in buckets))

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def __hash__(self) -> int:
        return hash(self._sig)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BucketPlan) and self._sig == other._sig


def make_plan(shapes: Sequence[Tuple[int, ...]],
              dtypes: Sequence[Any],
              threshold_bytes: int) -> BucketPlan:
    """Greedy same-dtype bucketing up to ``threshold_bytes`` per bucket.

    Mirrors FuseResponses' greedy fill with the dtype look-ahead (the
    reference skips mixed-dtype fusion; reference: controller.cc:778-915):
    tensors are taken in submission order, opened buckets are per-dtype, and
    a bucket closes when adding the next same-dtype tensor would exceed the
    threshold.  A tensor larger than the threshold gets its own bucket.
    """
    open_buckets: Dict[Any, Bucket] = {}
    done: List[Bucket] = []

    def close(b: Bucket, reason: str) -> None:
        done.append(b)
        _metrics.FUSION_FLUSHES.inc(reason=reason)
        _metrics.FUSION_BUCKET_BYTES.observe(b.nbytes)

    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        dt = jnp.dtype(dtype)
        nbytes = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
        b = open_buckets.get(dt)
        if b is not None and b.nbytes + nbytes > threshold_bytes and b.indices:
            close(b, "threshold")  # next tensor would overflow the bucket
            b = None
        if b is None:
            b = Bucket(dt)
            open_buckets[dt] = b
        b.add(i, shape, nbytes)
        if b.nbytes >= threshold_bytes:
            close(b, "filled")
            del open_buckets[dt]
    for b in open_buckets.values():
        if b.indices:
            close(b, "tail")  # end-of-step leftover
    return BucketPlan(done, len(shapes))


class BucketPlanCache:
    """LRU cache of bucket plans (the response-cache analog).

    Capacity semantics follow HOROVOD_CACHE_CAPACITY (reference:
    global_state.h:89, default 1024); capacity 0 disables caching.
    """

    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        self._cache: "collections.OrderedDict[Any, BucketPlan]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self,
            shapes: Sequence[Tuple[int, ...]],
            dtypes: Sequence[Any],
            threshold_bytes: int) -> BucketPlan:
        key = (tuple(map(tuple, shapes)),
               tuple(str(jnp.dtype(d)) for d in dtypes),
               int(threshold_bytes))
        if self.capacity > 0 and key in self._cache:
            self._cache.move_to_end(key)
            self.hits += 1
            return self._cache[key]
        self.misses += 1
        plan = make_plan(shapes, dtypes, threshold_bytes)
        if self.capacity > 0:
            self._cache[key] = plan
            while len(self._cache) > self.capacity:
                self._cache.popitem(last=False)
        return plan


# -------------------------------------------------------------- pack / unpack
def pack_bucket(leaves: Sequence[jax.Array], bucket: Bucket) -> jax.Array:
    """Concatenate the bucket's leaves into one flat 1-D buffer (jit-safe)."""
    parts = [jnp.ravel(leaves[i]) for i in bucket.indices]
    if len(parts) == 1:
        return parts[0]
    return jnp.concatenate(parts)


def pack_bucket_padded(leaves: Sequence[jax.Array], bucket: Bucket,
                       multiple: int) -> jax.Array:
    """:func:`pack_bucket` padded to a multiple of ``multiple`` — the
    shard-geometry form the ZeRO weight-update chain reduces/scatters
    (parallel/zero.py): a bucket split 1/n per chip needs a length
    divisible by the axis size, and the pad is static so XLA sees
    fixed-shape collectives."""
    flat = pack_bucket(leaves, bucket)
    total = flat.shape[0]
    padded = -(-total // max(multiple, 1)) * max(multiple, 1)
    if padded == total:
        return flat
    return jnp.pad(flat, (0, padded - total))


def unpack_bucket(buffer: jax.Array, bucket: Bucket,
                  out: List[Optional[jax.Array]]) -> None:
    """Split a fused buffer back into its leaves, writing into ``out``."""
    offset = 0
    for idx, size, shape in zip(bucket.indices, bucket.sizes, bucket.shapes):
        piece = buffer[offset:offset + size] if len(bucket.indices) > 1 \
            else buffer
        out[idx] = jnp.reshape(piece, shape)
        offset += size


def fused_apply(leaves: Sequence[jax.Array],
                plan: BucketPlan,
                fn) -> List[jax.Array]:
    """Apply ``fn`` (a collective) to each fused bucket and un-fuse.

    ``fn`` receives the flat 1-D bucket buffer and must return a same-shaped
    buffer (e.g. ``lambda b: lax.psum(b, axis)``).
    """
    return fused_apply_per_bucket(leaves, plan,
                                  [fn] * plan.num_buckets)


def fused_apply_per_bucket(leaves: Sequence[jax.Array],
                           plan: BucketPlan,
                           fns: Sequence) -> List[jax.Array]:
    """Like :func:`fused_apply` with one ``fn`` PER BUCKET — the
    wire-policy plane (ops/wire.py) reduces each bucket in its own wire
    format, so the collective differs bucket to bucket."""
    if len(fns) != plan.num_buckets:
        raise ValueError(f"{len(fns)} fns for {plan.num_buckets} buckets")
    out: List[Optional[jax.Array]] = [None] * plan.num_leaves
    for bucket, fn in zip(plan.buckets, fns):
        buf = pack_bucket(leaves, bucket)
        buf = fn(buf)
        unpack_bucket(buf, bucket, out)
    return out  # type: ignore[return-value]
