"""Overlap plane: schedule collectives concurrently with compute.

The reference's entire native architecture — background thread, tensor
queue, cycle-time batching (reference: operations.cc:115 BackgroundThread,
horovod/common/controller.cc RunLoopOnce) — exists for ONE reason: to
overlap allreduce with backward compute (Sergeev & Del Balso,
arXiv:1802.05799 §3).  On TPU there is no background thread; the program
IS the schedule, so overlap must be restructured into the traced step.
This module owns that restructuring at three levels:

  * **Microbatch pipelining** (:func:`make_pipelined_transform`): with
    ``backward_passes_per_step = k > 1`` the classic path accumulates k
    microbatch gradients and syncs once at the end — the allreduce sits
    fully exposed after the last backward.  The pipelined path holds a
    ``depth``-slot ring buffer of unsynced gradients: the fused sync of
    microbatch *i* is issued in the same program region as microbatch
    *i + depth*'s forward/backward, where XLA's latency-hiding scheduler
    can run them concurrently, and a final flush drains the buffer before
    the optimizer update.  Strictly a SCHEDULING change: the same
    per-microbatch syncs run in the same order on the same values, so the
    result is bit-near the unpipelined issue order (tests/test_overlap.py
    asserts it per wire format, EF on and off).
  * **Bucket-interleaved ZeRO chain** (:func:`priority_order`, consumed
    by parallel/zero.py for ``zero_level`` in {1, 2, 3} — docs/zero.md):
    the monolithic flat-vector RS -> shard-update -> AG chain becomes a
    per-fusion-bucket pipeline, bucket *b*'s sharded update overlapping
    bucket *b+1*'s in-flight reduce_scatter, with issue order reversed
    (last buckets first — the Horovod convention of negotiating tensors
    in reverse registration order, and ByteScheduler's priority
    ordering, arXiv — PAPERS.md) so the next step's first-needed
    parameters finish gathering earliest.  ZeRO-3's just-in-time param
    all_gathers apply the same discipline in the opposite direction:
    plan order, ``HOROVOD_ZERO_AG_PREFETCH`` gathers in flight ahead of
    the bucket being consumed, with the tuned overlap-depth bandit arm
    covering that depth too (Runtime.zero_ag_prefetch).
  * **Observability + autotuning**: the ``hvd_overlap_*`` gauges record
    the analytical exposed-vs-overlapped byte split per trace
    (:func:`record_overlap`), and the pipeline depth joins the autotune
    search as a bandit arm dimension (utils/autotune.py, csrc/optim.cc
    ProductBandit) broadcast with the fusion threshold so every rank
    compiles the same SPMD program.

CPU-virtual caveat: on the host-device test harness the "overlap" is a
program-order restructure only — wall-clock wins need a real TPU, whose
XLA scheduler hides collective latency behind compute (docs/overlap.md).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import metrics as _metrics

# The env knob's legal range; kwargs additionally accept depth 0 (the
# sequential issue order of the same per-microbatch syncs — the reference
# schedule the equivalence tests pin the pipeline against).
MAX_OVERLAP_DEPTH = 8


def validate_overlap_knobs(knobs) -> None:
    """Fail loudly AT INIT on invalid overlap/prefetch knob values
    (consumed by hvd.init, the HOROVOD_WIRE_POLICY validation pattern) —
    a bad depth must not surface as a trace error deep inside the first
    compiled step."""
    depth = int(knobs["HOROVOD_OVERLAP_DEPTH"])
    if not 1 <= depth <= MAX_OVERLAP_DEPTH:
        raise ValueError(
            f"HOROVOD_OVERLAP_DEPTH={depth} invalid; the pipeline depth "
            f"must be in [1, {MAX_OVERLAP_DEPTH}] (docs/overlap.md)")
    pre = int(knobs["HOROVOD_PREFETCH_DEPTH"])
    if pre < 1:
        raise ValueError(
            f"HOROVOD_PREFETCH_DEPTH={pre} invalid; the device-prefetch "
            "depth must be >= 1 (docs/overlap.md)")


def overlap_enabled(overlap: Optional[bool] = None) -> bool:
    """Kwarg wins; the HOROVOD_OVERLAP knob (env-live via ``current``)
    decides otherwise — so ``HOROVOD_OVERLAP=1`` alone activates the
    pipeline for ``backward_passes_per_step > 1`` users with zero code
    changes (the state restructure is safe there: k > 1 state always
    comes from the wrapper's own ``init``, never the inner optimizer's).
    """
    if overlap is not None:
        return bool(overlap)
    from ..common.knobs import current
    return bool(current("HOROVOD_OVERLAP"))


def resolve_depth(depth: Optional[int] = None) -> int:
    """Live pipeline depth: kwarg > tuned bandit arm > knob.  Kwarg 0 is
    the sequential reference schedule; the env knob is clamped to
    [1, MAX_OVERLAP_DEPTH] at hvd.init."""
    if depth is None:
        from .. import runtime as _rt
        if _rt.is_initialized():
            depth = _rt.get().overlap_depth()
        else:
            from ..common.knobs import current
            depth = int(current("HOROVOD_OVERLAP_DEPTH"))
    depth = int(depth)
    if not 0 <= depth <= MAX_OVERLAP_DEPTH:
        raise ValueError(
            f"overlap depth {depth} out of range [0, {MAX_OVERLAP_DEPTH}]")
    return depth


# ------------------------------------------------------- priority ordering
def priority_order(plan) -> Tuple[int, ...]:
    """Bucket ISSUE order for the interleaved ZeRO-1 pipeline: reversed
    plan order (last buckets first).  Backprop produces the last layers'
    gradients first and the reference negotiates tensors in reverse
    registration order for exactly this reason; issuing the tail buckets'
    reduce_scatter first means the head buckets — whose parameters the
    next forward consumes first — run their all_gather at the END of the
    pipeline, freshly resident when step N+1 begins.  Deterministic (a
    pure function of the plan) and therefore plan-cache-keyed: identical
    (shapes, dtypes, threshold) signatures reuse both the plan and its
    order."""
    return tuple(reversed(range(plan.num_buckets)))


# ----------------------------------------------------------- byte model
def record_overlap(total_bytes: float, exposed_bytes: float,
                   plane: str) -> dict:
    """Publish one trace's analytical overlap split to the
    ``hvd_overlap_{exposed_bytes,overlapped_fraction}`` gauges.  A
    *model*, not a measurement (like the wire-byte model, ops/wire.py):
    bytes are modeled payload traffic, 'exposed' means issued with no
    concurrent compute to hide behind."""
    frac = 0.0
    if total_bytes > 0:
        frac = max(0.0, min(1.0, 1.0 - exposed_bytes / total_bytes))
    _metrics.OVERLAP_EXPOSED_BYTES.set(exposed_bytes, plane=plane)
    _metrics.OVERLAP_FRACTION.set(frac, plane=plane)
    return {"total_bytes": total_bytes, "exposed_bytes": exposed_bytes,
            "overlapped_fraction": frac}


def microbatch_overlap_model(leaves, axis_name, k: int,
                             depth: int) -> dict:
    """Analytical exposed/overlapped split of the microbatch pipeline:
    each of the k per-microbatch syncs moves the same modeled payload;
    the ``max(0, k - depth)`` syncs drained while a later microbatch's
    backward runs count as overlapped, the final flush (and everything,
    at depth 0) as exposed.  Runs at trace time, like plan_formats."""
    from ..common.reduce_op import ReduceOp
    from . import wire as _wire
    from .fusion import make_plan

    shapes = [tuple(l.shape) for l in leaves]
    dtypes = [l.dtype for l in leaves]
    from .. import runtime as _rt
    threshold = (_rt.get().fusion_threshold() if _rt.is_initialized()
                 else 128 * 1024 * 1024)
    plan = make_plan(shapes, dtypes, threshold)
    sizes = _wire._axis_sizes(axis_name)
    per_sync = 0.0
    for b in plan.buckets:
        per_sync += _wire.modeled_wire_bytes(
            sum(b.sizes), jnp.dtype(b.dtype).itemsize, "none",
            sizes)["bottleneck"]
    overlapped = max(0, k - depth) if depth >= 1 else 0
    total = k * per_sync
    exposed = (k - overlapped) * per_sync
    # Tracing plane: step-anchored schedule markers (trace time, once per
    # compiled program) — one instant per microbatch slot showing where
    # its sync issues: inside microbatch i+depth's compute region (the
    # ring-buffer drain) or in the exposed final flush.  The merged
    # timeline then shows the pipeline SHAPE next to the controller and
    # transport lanes (docs/timeline.md).
    from ..utils.timeline import trace_instant
    for i in range(k):
        drained_in_loop = depth >= 1 and i < k - depth
        trace_instant(
            "overlap",
            "overlap.sync.issue" if drained_in_loop
            else "overlap.sync.flush",
            args={"microbatch": i,
                  "issued_at_call": (i + depth if drained_in_loop
                                     else k - 1),
                  "depth": depth})
    return record_overlap(total, exposed, plane="microbatch")


# ------------------------------------------------------ pipelined transform
class _OverlapState(NamedTuple):
    """Optimizer state of the microbatch-pipelined sync path: the core
    state (inner optimizer, or _WireState when error feedback is on), the
    microbatch counter, the running sum of already-synced microbatch
    gradients, and the depth-slot ring buffer of gradients whose sync has
    not been issued yet (None at depth 0)."""
    inner: Any
    counter: jax.Array
    synced: Any
    pending: Any


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_zeros(t):
    return jax.tree_util.tree_map(jnp.zeros_like, t)


def make_pipelined_transform(core_init: Callable,
                             sync_fn: Callable,
                             apply_fn: Callable,
                             k: int,
                             depth: int,
                             on_trace: Optional[Callable] = None):
    """Build the pipelined ``backward_passes_per_step=k`` optax transform
    (consumed by optimizer.distributed_optimizer when the overlap plane
    is on).

    ``sync_fn(grads, core_state) -> (synced, core_state)`` issues ONE
    microbatch's fused sync (threading EF residuals through the core
    state when error feedback is on); ``apply_fn(mean, core_state,
    params, **extra) -> (updates, core_state)`` runs the inner optimizer
    only.  Call *i* of a cycle stashes its gradients in slot ``i % depth``
    and issues the sync of the gradients stashed ``depth`` calls ago — so
    inside a ``lax.scan`` over microbatches (or an unrolled loop in one
    jit) the sync of microbatch *i* sits in the program region of
    microbatch *i+depth*'s forward/backward, with no data dependence
    between them: exactly what a latency-hiding scheduler needs.  The
    final call drains the buffer (oldest first), restoring the one global
    sync order 0..k-1 — which is why every depth (including 0, the
    unbuffered sequential schedule) computes bit-near identical results.
    """
    import optax

    if k < 2:
        raise ValueError("the microbatch pipeline needs "
                         f"backward_passes_per_step >= 2 (got {k})")
    d = min(int(depth), k - 1)  # depth >= k would never drain in-loop

    def init_fn(params):
        pending = None
        if d > 0:
            pending = jax.tree_util.tree_map(
                lambda z: jnp.zeros((d,) + z.shape, z.dtype), params)
        return _OverlapState(inner=core_init(params),
                             counter=jnp.zeros((), jnp.int32),
                             synced=_tree_zeros(params),
                             pending=pending)

    def update_fn(grads, state: _OverlapState, params=None, **extra):
        if on_trace is not None:
            on_trace(grads, k, d)
        pos = state.counter % k
        is_final = (pos + 1) == k
        tmap = jax.tree_util.tree_map

        if d == 0:
            # Sequential reference schedule: sync immediately, in call
            # order.  Same math as every pipelined depth; nothing is
            # buffered, nothing overlaps.
            s, inner = sync_fn(grads, state.inner)
            acc = _tree_add(state.synced, s)

            def apply_now(op):
                acc, inner = op
                mean = tmap(lambda a: a / k, acc)
                updates, inner = apply_fn(mean, inner, params, **extra)
                return updates, inner, _tree_zeros(acc)

            def carry(op):
                acc, inner = op
                return _tree_zeros(grads), inner, acc

            updates, inner, acc = lax.cond(is_final, apply_now, carry,
                                           (acc, inner))
            return updates, _OverlapState(inner, state.counter + 1, acc,
                                          None)

        slot = pos % d
        oldest = tmap(
            lambda p: lax.dynamic_index_in_dim(p, slot, keepdims=False),
            state.pending)

        # Drain the sync of the microbatch stashed d calls ago — the
        # issue point that interleaves with THIS microbatch's compute.
        def drain(op):
            oldest, inner, synced = op
            s, inner = sync_fn(oldest, inner)
            return _tree_add(synced, s), inner

        def hold(op):
            _, inner, synced = op
            return synced, inner

        synced, inner = lax.cond(pos >= d, drain, hold,
                                 (oldest, state.inner, state.synced))
        pending = tmap(
            lambda p, g: lax.dynamic_update_index_in_dim(p, g, slot, 0),
            state.pending, grads)

        def flush(op):
            synced, inner, pending = op
            # d microbatches (stashed at calls k-d .. k-1) are still
            # unsynced; drain oldest-first so the global sync order is
            # 0..k-1 at every depth.
            for j in range(d):
                idx = (k - d + j) % d
                item = tmap(lambda p: p[idx], pending)
                s, inner = sync_fn(item, inner)
                synced = _tree_add(synced, s)
            mean = tmap(lambda a: a / k, synced)
            updates, inner = apply_fn(mean, inner, params, **extra)
            return updates, inner, _tree_zeros(synced)

        def carry(op):
            synced, inner, _ = op
            return _tree_zeros(grads), inner, synced

        updates, inner, synced = lax.cond(is_final, flush, carry,
                                          (synced, inner, pending))
        return updates, _OverlapState(inner, state.counter + 1, synced,
                                      pending)

    return optax.GradientTransformation(init_fn, update_fn)
