"""Pallas flash attention for TPU — the framework's hot-op kernel.

The reference ships CUDA kernels for its hot paths (reference:
horovod/common/ops/cuda/cuda_kernels.cu — batched memcpy + scale); this
framework's hot op is model attention, so the native kernel is a
blockwise online-softmax attention (flash attention) written in Pallas
for the MXU:

  * grid over (batch, q-head, q-block); K/V stream through VMEM in
    blocks with running (max, sum, accumulator) state — no [S, S] score
    matrix ever materializes in HBM;
  * fp32 accumulation regardless of input dtype (bf16 in, bf16 out);
  * causal masking skips fully-masked K blocks; GQA maps q-heads onto
    shared KV heads via the BlockSpec index map;
  * same signature as layers.causal_attention ([B, S, H, D], GQA by
    head-count ratio) so models swap it in via ``attn_fn``.

Off-TPU (tests, CPU smoke) the kernel runs in Pallas interpret mode —
same code path, numerics checked against the XLA reference
implementation.  Ring attention (parallel/sequence.py) composes with it:
each ring step's local block attention can use this kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, causal: bool,
                 block_k: int, seq_len: int, scale: float):
    # q_ref: [BQ, D]; k_ref/v_ref: [S, D]; o_ref: [BQ, D]
    qi = pl.program_id(2)
    bq = q_ref.shape[0]
    d = q_ref.shape[1]
    q = q_ref[:].astype(jnp.float32) * scale

    m = jnp.full((bq, 1), NEG_INF, jnp.float32)       # running max
    l = jnp.zeros((bq, 1), jnp.float32)               # running sum
    acc = jnp.zeros((bq, d), jnp.float32)

    q_start = qi * bq
    num_kb = pl.cdiv(seq_len, block_k)
    # causal: K blocks strictly after this q block contribute nothing
    kb_hi = jnp.minimum(num_kb,
                        pl.cdiv(q_start + bq, block_k)) if causal else num_kb

    def body(kb, carry):
        m, l, acc = carry
        k_start = kb * block_k
        k = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, kb_hi, body, (m, l, acc))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _reference_attention(q, k, v, causal):
    """XLA attention (same math) — the backward rule recomputes through
    this, so training gets the Pallas forward + a compiler-derived
    backward without a hand-written bwd kernel."""
    from ..models import layers as L
    return L.causal_attention(q, k, v, causal=causal)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    block_q: int = 256, block_k: int = 256,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Blockwise attention, model layout [B, S, H, D] with GQA.

    ``interpret=None`` auto-selects: compiled on TPU backends, Pallas
    interpreter elsewhere (numerics-identical, for tests/CPU smoke)."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _reference_attention(q, k, v, causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True,
                   block_q: int = 256, block_k: int = 256,
                   interpret: Optional[bool] = None) -> jax.Array:
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, S, H, D = q.shape
    HK = k.shape[2]
    if H % HK:
        raise ValueError(
            f"q heads ({H}) must be a multiple of kv heads ({HK}) for GQA")
    group = H // HK

    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"seq len {S} must divide block sizes "
                         f"({block_q}, {block_k})")

    # kernel layout [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scale = 1.0 / (D ** 0.5)

    kernel = functools.partial(_attn_kernel, causal=causal,
                               block_k=block_k, seq_len=S, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(B, H, S // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, S, D),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, S, D),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, D),
                               lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2)
