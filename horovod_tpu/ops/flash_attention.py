"""Pallas flash attention for TPU — the framework's hot-op kernel.

The reference ships CUDA kernels for its hot paths (reference:
horovod/common/ops/cuda/cuda_kernels.cu — batched memcpy + scale); this
framework's hot op is model attention, so the native kernel is a
blockwise online-softmax attention (flash attention) written in Pallas
for the MXU:

  * grid over (batch, q-head, q-block); K/V stream through VMEM in
    blocks with running (max, sum, accumulator) state — no [S, S] score
    matrix ever materializes in HBM;
  * fp32 accumulation regardless of input dtype (bf16 in, bf16 out);
  * causal masking skips fully-masked K blocks; GQA maps q-heads onto
    shared KV heads via the BlockSpec index map;
  * same signature as layers.causal_attention ([B, S, H, D], GQA by
    head-count ratio) so models swap it in via ``attn_fn``.

Off-TPU (tests, CPU smoke) the kernel runs in Pallas interpret mode —
same code path, numerics checked against the XLA reference
implementation.  Ring attention (parallel/sequence.py) composes with it:
each ring step's local block attention can use this kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Every grid axis (batch, head, q-or-k block) is independent — the
# sequential online-softmax walk over K/V lives in an in-kernel
# fori_loop, not on the grid — so Mosaic may pipeline/reorder grid
# iterations freely.  Ignored in interpret mode.
# (CompilerParams was spelled TPUCompilerParams before jax 0.5.x.)
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))
_GRID_SEMANTICS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel"))


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal: bool,
                 block_k: int, seq_len: int, scale: float):
    # q_ref: [BQ, D]; k_ref/v_ref: [S, D]; o_ref: [BQ, D]; lse_ref: [BQ, 1]
    # (the trailing unit lane dim keeps the row-statistic blocks legal for
    # Mosaic's last-two-dims tiling rule; callers see lse as [B, H, S])
    #
    # MXU dtype discipline: matmul OPERANDS stay in the input dtype (the
    # MXU runs bf16 x bf16 -> fp32 at full rate; upcasting operands to
    # fp32 first would halve-or-worse its throughput), while every
    # softmax statistic and the output accumulator are fp32 via
    # preferred_element_type.  The scale folds into the fp32 accumulator
    # AFTER the q.k matmul, not into q.
    qi = pl.program_id(2)
    bq = q_ref.shape[0]
    d = q_ref.shape[1]
    q = q_ref[:]

    m = jnp.full((bq, 1), NEG_INF, jnp.float32)       # running max
    l = jnp.zeros((bq, 1), jnp.float32)               # running sum
    acc = jnp.zeros((bq, d), jnp.float32)

    q_start = qi * bq
    num_kb = pl.cdiv(seq_len, block_k)
    # causal split: K blocks strictly after this q block contribute
    # nothing; blocks entirely at-or-below the diagonal need no mask at
    # all (most blocks, for long sequences) — only the diagonal-crossing
    # tail pays the iota/compare/select VPU tax.
    kb_hi = jnp.minimum(num_kb,
                        pl.cdiv(q_start + bq, block_k)) if causal else num_kb
    kb_full = (q_start // block_k) if causal else num_kb

    def body(kb, carry, *, masked):
        m, l, acc = carry
        k_start = kb * block_k
        k = k_ref[pl.ds(k_start, block_k), :]
        v = v_ref[pl.ds(k_start, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if masked:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        # p back to the input dtype for the second matmul (bf16 inputs ->
        # full-rate MXU; fp32 inputs keep fp32 precision)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(
        0, kb_full, functools.partial(body, masked=False), (m, l, acc))
    m, l, acc = jax.lax.fori_loop(
        kb_full, kb_hi, functools.partial(body, masked=causal), (m, l, acc))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    # logsumexp of the SCALED scores — the backward kernels rebuild
    # p = exp(s - lse) from it without re-running the online softmax.
    lse_ref[:] = m + jnp.log(l)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, causal: bool, block_k: int, seq_len: int,
                   scale: float):
    # q/do/dq: [BQ, D]; k/v: [S, D]; lse/delta: [BQ, 1]
    qi = pl.program_id(2)
    bq = q_ref.shape[0]
    d = q_ref.shape[1]
    # Same MXU dtype discipline as the forward: operands in input dtype,
    # fp32 accumulation, scale folded in fp32 (s after the matmul, dq at
    # the end).
    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[:].astype(jnp.float32)
    delta = delta_ref[:].astype(jnp.float32)

    q_start = qi * bq
    num_kb = pl.cdiv(seq_len, block_k)
    kb_hi = jnp.minimum(num_kb,
                        pl.cdiv(q_start + bq, block_k)) if causal else num_kb
    # blocks entirely below the diagonal skip the mask (see _attn_kernel)
    kb_full = (q_start // block_k) if causal else num_kb

    def body(kb, dq, *, masked):
        k_start = kb * block_k
        k = k_ref[pl.ds(k_start, block_k), :]
        v = v_ref[pl.ds(k_start, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if masked:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q_ref.dtype)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, kb_full,
                           functools.partial(body, masked=False),
                           jnp.zeros((bq, d), jnp.float32))
    dq = jax.lax.fori_loop(kb_full, kb_hi,
                           functools.partial(body, masked=causal), dq)
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, causal: bool, block_q: int,
                    seq_len: int, scale: float):
    # k/v/dk/dv: [BK, D]; q/do: [S, D]; lse/delta: [S, 1]
    ki = pl.program_id(2)
    bk = k_ref.shape[0]
    d = k_ref.shape[1]
    # Input-dtype operands / fp32 accumulators, as in the other kernels.
    # dk absorbs the softmax scale once at the end (d/dk of s=(q.k)*scale)
    # instead of pre-scaling every q block.
    k = k_ref[:]
    v = v_ref[:]

    k_start = ki * bk
    num_qb = pl.cdiv(seq_len, block_q)
    # causal: q blocks strictly before this k block contribute nothing;
    # q blocks entirely past the diagonal need no mask (see _attn_kernel)
    qb_lo = (k_start // block_q) if causal else 0
    qb_full_lo = (pl.cdiv(k_start + bk, block_q) if causal else 0)

    def body(qb, carry, *, masked):
        dk, dv = carry
        q_start = qb * block_q
        q = q_ref[pl.ds(q_start, block_q), :]
        do = do_ref[pl.ds(q_start, block_q), :]
        lse = lse_ref[pl.ds(q_start, block_q), :].astype(jnp.float32)
        delta = delta_ref[pl.ds(q_start, block_q), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if masked:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                              # [BQ2, BK]
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    zeros = (jnp.zeros((bk, d), jnp.float32),
             jnp.zeros((bk, d), jnp.float32))
    dk, dv = jax.lax.fori_loop(
        qb_lo, jnp.minimum(qb_full_lo, num_qb),
        functools.partial(body, masked=causal), zeros)
    dk, dv = jax.lax.fori_loop(
        jnp.minimum(qb_full_lo, num_qb), num_qb,
        functools.partial(body, masked=False), (dk, dv))
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    block_q: int = 256, block_k: int = 256,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Blockwise attention, model layout [B, S, H, D] with GQA.

    Training uses Pallas kernels on BOTH passes: the forward saves the
    per-row logsumexp, and the backward rebuilds the probabilities
    blockwise in two kernels (dq; dk+dv) — the flash-attention backward
    algorithm, no [S, S] score matrix in either direction.

    ``interpret=None`` auto-selects: compiled on TPU backends, Pallas
    interpreter elsewhere (numerics-identical, for tests/CPU smoke)."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)[0]


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k,
                           interpret)


def _resolve_blocks(S, block_q, block_k, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"seq len {S} must divide block sizes "
                         f"({block_q}, {block_k})")
    return block_q, block_k, interpret


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True,
                   block_q: int = 256, block_k: int = 256,
                   interpret: Optional[bool] = None):
    B, S, H, D = q.shape
    HK = k.shape[2]
    if H % HK:
        raise ValueError(
            f"q heads ({H}) must be a multiple of kv heads ({HK}) for GQA")
    group = H // HK
    block_q, block_k, interpret = _resolve_blocks(S, block_q, block_k,
                                                  interpret)

    # kernel layout [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scale = 1.0 / (D ** 0.5)

    kernel = functools.partial(_attn_kernel, causal=causal,
                               block_k=block_k, seq_len=S, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, S // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, S, D),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, S, D),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1),
                         lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        compiler_params=_GRID_SEMANTICS,
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2), lse[..., 0]


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_backward(q, k, v, out, lse, g, causal: bool = True,
                    block_q: int = 256, block_k: int = 256,
                    interpret: Optional[bool] = None):
    B, S, H, D = q.shape
    HK = k.shape[2]
    group = H // HK
    block_q, block_k, interpret = _resolve_blocks(S, block_q, block_k,
                                                  interpret)
    scale = 1.0 / (D ** 0.5)

    qt = jnp.swapaxes(q, 1, 2)
    do = jnp.swapaxes(g, 1, 2)
    ot = jnp.swapaxes(out, 1, 2)
    # GQA: K/V stay at their real [B, HK, S, D] footprint; the h//group
    # index maps fan each q-head onto its shared kv head (same trick as
    # the forward), and only the per-q-head dk/dv OUTPUTS carry H extent
    # before the group summation below.
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # delta_i = sum_d dO_i * O_i  (the softmax-jacobian row correction);
    # row statistics carry a trailing unit lane dim for Mosaic tiling
    delta = jnp.sum(do.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1, keepdims=True)
    lse = lse[..., None]

    qspec = pl.BlockSpec((None, None, block_q, D),
                         lambda b, h, i: (b, h, i, 0))
    kvfull = pl.BlockSpec((None, None, S, D),
                          lambda b, h, i, g=group: (b, h // g, 0, 0))
    qfull = pl.BlockSpec((None, None, S, D), lambda b, h, i: (b, h, 0, 0))
    rowq = pl.BlockSpec((None, None, block_q, 1),
                        lambda b, h, i: (b, h, i, 0))
    rowfull = pl.BlockSpec((None, None, S, 1), lambda b, h, i: (b, h, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, block_k=block_k,
                          seq_len=S, scale=scale),
        grid=(B, H, S // block_q),
        in_specs=[qspec, kvfull, kvfull, qspec, rowq, rowq],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        compiler_params=_GRID_SEMANTICS,
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)

    kspec = pl.BlockSpec((None, None, block_k, D),
                         lambda b, h, i: (b, h, i, 0))
    kvblock = pl.BlockSpec((None, None, block_k, D),
                           lambda b, h, i, g=group: (b, h // g, i, 0))
    # Per-q-head dk/dv stay fp32 so the GQA group summation below does
    # not compound bf16 rounding; one cast to the input dtype at the end.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, block_q=block_q,
                          seq_len=S, scale=scale),
        grid=(B, H, S // block_k),
        in_specs=[kvblock, kvblock, qfull, qfull, rowfull, rowfull],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((B, H, S, D), jnp.float32),
                   jax.ShapeDtypeStruct((B, H, S, D), jnp.float32)],
        compiler_params=_GRID_SEMANTICS,
        interpret=interpret,
    )(kt, vt, qt, do, lse, delta)

    if group > 1:  # sum each kv head's group of q-head contributions
        dk = dk.reshape(B, HK, group, S, D).sum(axis=2)
        dv = dv.reshape(B, HK, group, S, D).sum(axis=2)

    return (jnp.swapaxes(dq, 1, 2),
            jnp.swapaxes(dk, 1, 2).astype(k.dtype),
            jnp.swapaxes(dv, 1, 2).astype(v.dtype))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
