"""Quantized ring allreduce: int8 on the wire, fp32 accumulation.

Technique: EQuARX — Efficient Quantized AllReduce in XLA
(arxiv.org/pdf/2506.17615; listed in PAPERS.md): decompose the
allreduce into its ring reduce-scatter + allgather phases and quantize
each HOP's payload to int8 with a fresh per-chunk scale, so the wire
carries 1/4 the bytes of an fp32 allreduce (half a bf16 one) while
accumulation stays full precision.  A plain ``psum`` of int8 values cannot do this
(integer overflow, and per-rank scales don't commute with the sum) —
the hop structure is the point.

The reference framework's analog is its fp16 wire compression
(horovod/*/compression.py) applied around NCCL allreduce; int8 needs
the hop-level design, which its fixed collective backends cannot
express and `lax.ppermute` can.

Shape: the standard two-phase ring on a mesh axis of size N —
N-1 reduce-scatter hops (each rank accumulates one incoming quantized
chunk per hop) then N-1 allgather hops (fully-reduced chunks circulate,
also quantized).  Per-element quantization error is bounded by
``scale/2`` per hop and chunks take ~2(N-1) quantized trips, so noise
grows linearly in N — acceptable for gradient averaging (EQuARX's
finding), and the error-bound test pins it.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Any


def _quantize(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-chunk int8: q = round(x/scale), scale = max|x|/127."""
    scale = jnp.max(jnp.abs(x)) / 127.0
    scale = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale


def int8_roundtrip(x: jax.Array) -> jax.Array:
    """``dequantize(quantize(x))`` — the one-shot codec model of the int8
    wire.  The wire-policy plane's error feedback (ops/wire.py) uses
    ``x - int8_roundtrip(x)`` as the rank-local compensable encode error:
    exactly the EF-SGD residual ``x - C(x)`` for this quantizer."""
    q, scale = _quantize(x.astype(jnp.float32))
    return _dequantize(q, scale).astype(x.dtype)


def quantized_ring_allreduce(x: jax.Array, axis_name: AxisName,
                             average: bool = True) -> jax.Array:
    """Allreduce ``x`` over ``axis_name`` with int8 wire traffic.

    Call inside ``shard_map``/``pjit`` like the other SPMD collectives;
    returns the mean (``average=True``, the gradient-sync convention) or
    sum in ``x``'s dtype.  Single-member axes return ``x`` unchanged.

    A TUPLE of axes runs one ring PER AXIS, innermost last — on a
    two-level ``('dcn.x', 'ici.x')`` mesh the big ring stays on ICI and
    only the small cross-slice ring touches DCN (the hierarchical
    routing a single combined ring would destroy, since every combined
    hop would cross DCN).
    """
    if isinstance(axis_name, (tuple, list)):
        total = 1
        out = x.astype(jnp.float32)
        for ax in axis_name:
            n_ax = int(lax.psum(1, ax))
            total *= n_ax
            out = quantized_ring_allreduce(out, ax, average=False)
        if average:
            out = out / total
        return out.astype(x.dtype)

    n = int(lax.psum(1, axis_name))
    if n == 1:
        return x
    idx = lax.axis_index(axis_name)
    shape, dtype = x.shape, x.dtype

    flat = x.astype(jnp.float32).ravel()
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    chunks = flat.reshape(n, -1)

    fwd = [(i, (i + 1) % n) for i in range(n)]

    # Both phases roll as lax.fori_loop: the perm table is static and
    # every per-hop index is traced arithmetic, so the compiled program
    # holds ONE loop body instead of 2(N-1) inlined collective-permutes
    # (compile time would otherwise grow linearly with the axis size).

    # Phase 1 — reduce-scatter: at hop s, rank r sends its running
    # accumulation of chunk (r - s) mod n; after N-1 hops rank r holds
    # the FULL sum of chunk (r + 1) mod n.
    def rs_hop(step, acc):
        send = (idx - step) % n
        recv = (idx - step - 1) % n
        payload = jnp.take(acc, send, axis=0)
        q, scale = _quantize(payload)
        q = lax.ppermute(q, axis_name, fwd)
        scale = lax.ppermute(scale, axis_name, fwd)
        return acc.at[recv].add(_dequantize(q, scale))

    acc = lax.fori_loop(0, n - 1, rs_hop, chunks)

    own = (idx + 1) % n  # the chunk this rank fully reduced
    done = jnp.take(acc, own, axis=0)

    # Phase 2 — allgather: circulate fully-reduced chunks (quantized on
    # the wire like phase 1); after N-1 hops every rank saw all chunks.
    # The origin rank keeps the DEQUANTIZED version of its own chunk, so
    # every rank decodes bit-identical values (a rank-dependent result
    # would make replicated params drift apart).
    q0, scale0 = _quantize(done)
    out0 = jnp.zeros_like(chunks).at[own].set(_dequantize(q0, scale0))

    def ag_hop(step, carry):
        out, q, scale = carry
        q = lax.ppermute(q, axis_name, fwd)
        scale = lax.ppermute(scale, axis_name, fwd)
        src_chunk = (idx - step) % n  # chunk id that just arrived
        return out.at[src_chunk].set(_dequantize(q, scale)), q, scale

    out, _, _ = lax.fori_loop(0, n - 1, ag_hop, (out0, q0, scale0))

    total = out.ravel()
    if pad:
        total = total[:-pad]
    if average:
        total = total / n
    return total.reshape(shape).astype(dtype)
