"""horovod_tpu.ops subpackage."""
