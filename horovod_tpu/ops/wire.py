"""Wire-policy plane: per-bucket wire formats for the fused gradient sync.

The ingredients existed in isolation — bf16/fp16 cast compression
(ops/compression.py), the int8 quantized ring (ops/quantized.py, EQuARX
arxiv 2506.17615), two-level ici/dcn routing (parallel/hierarchical.py) —
but as mutually-exclusive global flags: one wire format for every bucket,
no error compensation, so the aggressive formats were unsafe to enable.
This module composes them into a *policy*: a function

    policy(bucket_nbytes, dtype, axis_name) -> wire format name

evaluated per fusion bucket at trace time, so a compiled step can send its
handful of huge fp32 buckets as int8 ring hops while the small latency-bound
tail rides uncompressed.  The reference's analog is a single global
``Compression.fp16`` switch (horovod/torch/compression.py); per-bucket
selection has no reference equivalent.

Formats
-------
  none       exact allreduce in the bucket dtype
  bf16/fp16  cast compression around the allreduce (ops/compression.py)
  int8_ring  int8 quantized ring allreduce, fp32 accumulation
             (ops/quantized.py) — 1/4 the wire bytes of fp32
  dcn_int8   EQuARX-selective composition for two-level (dcn.X, ici.X)
             meshes: reduce_scatter(ici) -> int8 ring over dcn ->
             all_gather(ici) — only the slow DCN leg is quantized
             (parallel/hierarchical.py dcn_selective_int8_allreduce)

Policies are named by the same strings plus ``auto`` (per-bucket heuristic,
bandit-tuned online when HOROVOD_AUTOTUNE is on — utils/autotune.py).
Convergence safety for the lossy formats comes from error-feedback
residuals kept as optimizer state (optimizer.py): each rank's one-shot
encode error ``x - C(x)`` is added back into the next step's gradient
before compression (EF-SGD), which rescues the small-magnitude coordinates
an int8 dead zone would otherwise silently drop forever.

Determinism: every format decodes to bit-identical values on all ranks
(the int8 ring's allgather phase circulates the *quantized* chunks, and
the cast formats decompress a replicated psum result), so replicated
params cannot drift — asserted per format by tests/test_wire.py.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..common.reduce_op import ReduceOp
from ..utils import metrics as _metrics
from .compression import Compression

AxisName = Union[str, Sequence[str]]
# policy(bucket_nbytes, dtype, axis_name) -> format name
Policy = Callable[[int, Any, AxisName], str]

FORMAT_NAMES = ("none", "bf16", "fp16", "int8_ring", "dcn_int8")
POLICY_NAMES = FORMAT_NAMES + ("auto",)
LOSSY_FORMATS = ("bf16", "fp16", "int8_ring", "dcn_int8")

# auto-policy thresholds: below SMALL the collective is latency-bound and
# compression overhead (quantize/cast + scale exchange) buys nothing;
# above INT8_MIN the 4x byte saving dominates the bounded ring noise.
SMALL_BUCKET_BYTES = 64 * 1024
INT8_MIN_BYTES = 4 * 1024 * 1024

# The int8 wire also carries one fp32 scale per chunk per hop
# (ops/quantized.py).  The byte MODEL below excludes it: for the buckets
# the int8 formats ever apply to (>= INT8_MIN_BYTES) the scale words are
# < 0.01% of the payload, and excluding them keeps the per-element
# ratios exact (int8 = 1/2 bf16 = 1/4 fp32).


def validate_policy_name(name: str) -> str:
    """Fail loudly on unknown policy names (consumed by hvd.init for the
    HOROVOD_WIRE_POLICY knob)."""
    if name not in POLICY_NAMES:
        raise ValueError(
            f"unknown wire policy {name!r}; valid policies: "
            f"{', '.join(POLICY_NAMES)} (HOROVOD_WIRE_POLICY, "
            "docs/tensor-fusion.md)")
    return name


def _is_hierarchical(axis_name: AxisName) -> bool:
    from ..parallel.hierarchical import split_hierarchy
    return split_hierarchy(axis_name) is not None


def auto_policy(nbytes: int, dtype: Any, axis_name: AxisName) -> str:
    """The per-bucket heuristic behind ``HOROVOD_WIRE_POLICY=auto``:
    big floating buckets take the int8 wire (DCN-selective on a two-level
    mesh), mid-size fp32 buckets cast to bf16, and the small latency-bound
    tail stays exact."""
    dt = jnp.dtype(dtype)
    if not jnp.issubdtype(dt, jnp.floating):
        return "none"
    if nbytes < SMALL_BUCKET_BYTES:
        return "none"
    if nbytes >= INT8_MIN_BYTES:
        return "dcn_int8" if _is_hierarchical(axis_name) else "int8_ring"
    # mid-size: halve the wire if the dtype has the headroom
    return "bf16" if dt.itemsize >= 4 else "none"


def get_policy(policy: Union[str, Policy]) -> Policy:
    """Resolve a policy name (or pass a callable through) to the
    per-bucket decision function."""
    if callable(policy):
        return policy
    validate_policy_name(policy)
    if policy == "auto":
        return auto_policy
    return lambda nbytes, dtype, axis_name: policy


def is_lossy(fmt: str) -> bool:
    return fmt in LOSSY_FORMATS


def resolve_format(fmt: str, dtype: Any, axis_name: AxisName,
                   op: ReduceOp) -> str:
    """Degrade a requested format to what the bucket can actually carry:
    non-float buckets and non-linear reductions stay exact, no-op casts
    collapse to none, and ``dcn_int8`` on a flat axis falls back to the
    flat int8 ring (there is no separate slow leg to select)."""
    if fmt not in FORMAT_NAMES:
        raise ValueError(f"unknown wire format {fmt!r}; valid formats: "
                         f"{', '.join(FORMAT_NAMES)}")
    dt = jnp.dtype(dtype)
    if fmt == "none" or not jnp.issubdtype(dt, jnp.floating):
        return "none"
    if fmt in ("int8_ring", "dcn_int8"):
        # Quantized rings exist for Average/Sum only (scales don't commute
        # with min/max/product and Adasum re-reduces pairwise).
        if op not in (ReduceOp.AVERAGE, ReduceOp.SUM):
            return "none"
        if fmt == "dcn_int8" and not _is_hierarchical(axis_name):
            return "int8_ring"
        return fmt
    wire_dt = jnp.dtype({"bf16": jnp.bfloat16, "fp16": jnp.float16}[fmt])
    if wire_dt == dt:
        return "none"  # casting to the bucket's own dtype moves nothing
    return fmt


def reduce_bucket(buf: jax.Array, fmt: str, axis_name: AxisName,
                  op: ReduceOp, prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0) -> jax.Array:
    """Allreduce one flat bucket buffer in its wire format."""
    from . import spmd
    if fmt in ("none", "bf16", "fp16"):
        comp = Compression.by_name(fmt) if fmt != "none" else None
        if comp is not None:
            buf, ctx = comp.compress(buf)
        buf = spmd.allreduce(buf, axis_name, op=op,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor)
        return comp.decompress(buf, ctx) if comp is not None else buf

    average = op == ReduceOp.AVERAGE
    if prescale_factor != 1.0:
        buf = buf * prescale_factor
    if fmt == "int8_ring":
        from .quantized import quantized_ring_allreduce
        out = quantized_ring_allreduce(buf, axis_name, average=average)
    elif fmt == "dcn_int8":
        from ..parallel.hierarchical import (dcn_selective_int8_allreduce,
                                             split_hierarchy)
        pair = split_hierarchy(axis_name)
        if pair is None:
            raise ValueError(
                "dcn_int8 needs a canonical (dcn.X, ici.X) axis pair; "
                f"got {axis_name!r} (resolve_format degrades this case)")
        out = dcn_selective_int8_allreduce(buf, ici_axis=pair[1],
                                           dcn_axis=pair[0],
                                           average=average)
    else:
        raise ValueError(f"unknown wire format {fmt!r}")
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return out


def wire_roundtrip(buf: jax.Array, fmt: str) -> jax.Array:
    """``C(buf)`` — the decoded value of putting ``buf`` on the wire in
    ``fmt``, under the one-shot codec model (encode once, decode once).
    This is what the ZeRO chain's reduce_scatter leg feeds the collective
    (parallel/zero.py): each rank's contribution is encoded exactly once
    before the scatter, so the compensable error is ``buf - C(buf)`` —
    the same residual :func:`local_error` reports."""
    if fmt in ("bf16", "fp16"):
        comp = Compression.by_name(fmt)
        c, ctx = comp.compress(buf)
        return comp.decompress(c, ctx)
    if fmt in ("int8_ring", "dcn_int8"):
        from .quantized import int8_roundtrip
        return int8_roundtrip(buf)
    return buf


def local_error(buf: jax.Array, fmt: str) -> jax.Array:
    """The rank-local compensable encode error ``x - C(x)`` of putting
    ``buf`` on the wire in ``fmt`` — the EF-SGD residual.  One-shot codec
    model: for the multi-hop rings this is the error of this rank's own
    contribution (the only part a rank *can* compensate)."""
    if is_lossy(fmt):
        return buf - wire_roundtrip(buf, fmt)
    return jnp.zeros_like(buf)


# ------------------------------------------------------------ wire model
def _axis_sizes(axis_name: AxisName) -> Dict[str, int]:
    """Trace-time ring sizes by fabric: ``{"flat": n}`` for a plain axis,
    ``{"ici": i, "dcn": d}`` for the canonical two-level pair.  Unbound
    axes (host-side calls outside shard_map) report size 1."""
    from ..parallel.hierarchical import split_hierarchy

    def size(ax) -> int:
        try:
            return int(lax.psum(1, ax))
        except NameError:
            return 1
    pair = split_hierarchy(axis_name)
    if pair is not None:
        return {"dcn": size(pair[0]), "ici": size(pair[1])}
    if isinstance(axis_name, (tuple, list)):
        n = 1
        for ax in axis_name:
            n *= size(ax)
        return {"flat": n}
    return {"flat": size(axis_name)}


def modeled_wire_bytes(nelems: int, itemsize: int, fmt: str,
                      axis_sizes: Dict[str, int]) -> Dict[str, Any]:
    """Per-chip wire bytes for ONE allreduce of an ``nelems``-element
    bucket, by fabric, under the standard ring model (each chip sends
    2(n-1) chunks of nelems/n elements; int8 hops add one fp32 scale per
    chunk).  ``bottleneck`` is the slow-fabric total — DCN on a two-level
    mesh, the single fabric otherwise.  A *model*, not a measurement: it
    exists so policies are comparable analytically (bench.py --wire) and
    the savings counters move without device introspection."""
    def ring(n: int, elems: int, wire_itemsize: float) -> float:
        if n <= 1:
            return 0.0
        return 2.0 * (n - 1) * math.ceil(elems / n) * wire_itemsize

    two_level = "dcn" in axis_sizes
    if fmt == "dcn_int8" and two_level:
        ici, dcn = axis_sizes["ici"], axis_sizes["dcn"]
        shard = math.ceil(nelems / max(ici, 1))
        per_fabric = {
            # exact fp32 reduce_scatter + all_gather legs on ICI
            "ici": 2.0 * (ici - 1) * shard * 4.0,
            "dcn": ring(dcn, shard, 1.0),
        }
        return {"per_fabric": per_fabric,
                "bottleneck": per_fabric["dcn"]}

    wire_itemsize = {"none": float(itemsize), "bf16": 2.0, "fp16": 2.0,
                     "int8_ring": 1.0, "dcn_int8": 1.0}[fmt]
    if two_level:
        # flat formats on a hierarchical axis: the combined ring's hops all
        # potentially cross DCN (exactly why dcn_int8/hierarchical exist) —
        # charge the full ring to the slow fabric.
        n = axis_sizes["ici"] * axis_sizes["dcn"]
        total = ring(n, nelems, wire_itemsize)
        return {"per_fabric": {"dcn": total}, "bottleneck": total}
    n = axis_sizes.get("flat", 1)
    total = ring(n, nelems, wire_itemsize)
    return {"per_fabric": {"flat": total}, "bottleneck": total}


def plan_formats(plan, policy: Policy, axis_name: AxisName,
                 op: ReduceOp,
                 axis_sizes: Optional[Dict[str, int]] = None) -> List[str]:
    """Decide (and record) the wire format of every bucket in a fusion
    plan.  Runs at trace time, once per compiled program — the metric
    families therefore count decisions per trace (see utils/metrics.py).

    ``axis_sizes`` overrides the bound-axis probe: callers that decide
    formats OUTSIDE shard_map (the ZeRO chain's state init, which must
    agree structurally with the traced step — parallel/zero.py) pass the
    mesh sizes explicitly so both sides resolve identical formats."""
    sizes = _axis_sizes(axis_name) if axis_sizes is None else axis_sizes
    total_ranks = 1
    for v in sizes.values():
        total_ranks *= v
    fmts: List[str] = []
    for bucket in plan.buckets:
        fmt = resolve_format(policy(bucket.nbytes, bucket.dtype, axis_name),
                             bucket.dtype, axis_name, op)
        if total_ranks <= 1:
            # a single-member axis moves no bytes: compressing would only
            # add noise — and EF would "compensate" an error the wire
            # never incurred.
            fmt = "none"
        fmts.append(fmt)
        _metrics.WIRE_BUCKETS.inc(format=fmt)
        # Tracing plane: one instant per bucket decision (trace time, once
        # per compiled program) so the merged timeline shows WHICH wire
        # format each bucket encodes/decodes with (docs/timeline.md).
        from ..utils.timeline import trace_instant
        trace_instant("wire", f"wire.encode.{fmt}",
                      args={"bucket": len(fmts) - 1,
                            "nbytes": int(bucket.nbytes)})
        if fmt != "none":
            nelems = sum(bucket.sizes)
            itemsize = jnp.dtype(bucket.dtype).itemsize
            base = modeled_wire_bytes(nelems, itemsize, "none", sizes)
            this = modeled_wire_bytes(nelems, itemsize, fmt, sizes)
            saved = base["bottleneck"] - this["bottleneck"]
            if saved > 0:
                _metrics.WIRE_BYTES_SAVED.inc(saved, format=fmt)
    return fmts


# ------------------------------------------------------------- sync engine
def wire_sync(leaves: Sequence[jax.Array], plan, formats: Sequence[str],
              axis_name: AxisName, op: ReduceOp,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              residuals: Optional[Sequence[jax.Array]] = None):
    """Reduce every bucket in its wire format.

    With ``residuals`` (error feedback): the residual is added into the
    gradient per leaf BEFORE packing, each lossy bucket's one-shot encode
    error is captured as the new residual, and the function returns
    ``(synced_leaves, new_residuals)``.  Without residuals the second
    element is None.  Residuals are rank-local state; synced outputs are
    bit-identical on every rank regardless.
    """
    from .fusion import pack_bucket, unpack_bucket
    ef = residuals is not None
    if ef:
        leaves = [l + r.astype(l.dtype) for l, r in zip(leaves, residuals)]
        new_res: List[jax.Array] = [jnp.zeros_like(l) for l in leaves]
    out: List[Optional[jax.Array]] = [None] * plan.num_leaves
    for bucket, fmt in zip(plan.buckets, formats):
        buf = pack_bucket(leaves, bucket)
        if ef and is_lossy(fmt):
            unpack_bucket(local_error(buf, fmt), bucket, new_res)
        buf = reduce_bucket(buf, fmt, axis_name, op,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor)
        unpack_bucket(buf, bucket, out)
    return out, (new_res if ef else None)
