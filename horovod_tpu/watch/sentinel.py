"""Training-quality sentinels: watch the MODEL, not just the machinery.

Every plane so far watches infrastructure (cycles, bytes, heartbeats);
a run can be infrastructurally perfect while the model silently
diverges.  This module closes that gap (docs/watch.md#sentinels):

  * :func:`sentinel_stats` — trace-time scalars computed INSIDE the
    compiled step: global gradient norm, nonfinite element count (a
    psum of ``isfinite`` complements, so the verdict is SPMD-identical
    on every rank — no rank can disagree about whether the step was
    finite), and the (p)mean loss;
  * :func:`record` — the host-side sink: updates the
    ``hvd_sentinel_*`` gauge/counter families that ride the existing
    MetricsPublisher (zero new plumbing), maintains the loss EMA and
    its divergence ratio, and on a nonfinite step fires the full
    forensics chain — an explicit native flight dump
    (``hvd_core_flight_dump`` reason ``nan``, closing the loop into the
    PR-6 postmortem plane), a timeline instant, and the counter the
    committed ``sentinel-nonfinite`` critical rule watches
    (watch/rules.py);
  * :func:`wrap` — the drop-in: wraps a train step whose output carries
    ``(loss, grads, ...)``; stats are computed in-graph and delivered
    host-side through ``jax.debug.callback`` (async, jit/pjit-safe), so
    the wrapped step's signature and outputs are UNCHANGED.

Knobs: ``HOROVOD_SENTINEL`` (kill switch — off, :func:`wrap` returns
the step untouched) and ``HOROVOD_SENTINEL_INTERVAL`` (EMA/gauge update
cadence in recorded steps; nonfinite is checked EVERY step regardless —
a NaN must never slip between samples).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

# Loss EMA smoothing: ~50-step horizon, the scale at which "diverging"
# is distinguishable from batch noise on the toy and real losses alike.
EMA_ALPHA = 0.02


class _SentinelState:
    def __init__(self):
        self.lock = threading.Lock()
        self.step = 0                 # auto-advanced when record() gets None
        self.ema: Optional[float] = None
        self.recorded = 0
        self.last_nonfinite_step = -1
        self.dump_paths: list = []    # test-visible: flight dumps written


_state = _SentinelState()


def reset() -> None:
    """Test hook: forget EMA/step state (module-global)."""
    global _state
    _state = _SentinelState()


def _knob(name: str):
    from ..common.knobs import current
    return current(name)


def enabled() -> bool:
    return bool(_knob("HOROVOD_SENTINEL"))


# ------------------------------------------------------------- trace time
def sentinel_stats(loss, grads=None, axis_name: Optional[str] = None
                   ) -> Dict[str, Any]:
    """Trace-time sentinel scalars: ``{"loss", "grad_norm",
    "nonfinite"}``, each a replicated f32 scalar.  With ``axis_name``
    the gradient square-sum and nonfinite count are ``psum``'d and the
    loss ``pmean``'d, so every rank computes the IDENTICAL verdict (the
    SPMD caveat documented in docs/watch.md: call it inside the same
    collective context as the gradient sync, or the psum deadlocks)."""
    import jax
    import jax.numpy as jnp
    loss = jnp.asarray(loss, jnp.float32)
    leaves = jax.tree_util.tree_leaves(grads) if grads is not None else []
    sq = jnp.zeros((), jnp.float32)
    bad = jnp.zeros((), jnp.float32)
    for g in leaves:
        g32 = jnp.asarray(g, jnp.float32)
        fin = jnp.isfinite(g32)
        # Nonfinite elements poison a plain square-sum; count them
        # separately and keep the norm over the finite mass so BOTH
        # signals stay informative on a partially-bad gradient.
        sq = sq + jnp.sum(jnp.where(fin, g32, 0.0) ** 2)
        bad = bad + jnp.sum(1.0 - fin.astype(jnp.float32))
    bad = bad + (1.0 - jnp.isfinite(loss).astype(jnp.float32))
    if axis_name is not None:
        from jax import lax
        sq = lax.psum(sq, axis_name)
        bad = lax.psum(bad, axis_name)
        loss = lax.pmean(loss, axis_name)
    return {"loss": loss, "grad_norm": jnp.sqrt(sq), "nonfinite": bad}


# -------------------------------------------------------------- host side
def record(stats: Dict[str, Any], step: Optional[int] = None,
           core: Any = None) -> Dict[str, float]:
    """Sink one step's concrete sentinel scalars: update the
    hvd_sentinel_* families, the loss EMA/divergence, and — on a
    nonfinite step — fire the flight dump + alert chain.  Returns the
    recorded row (tests and callers can assert on it)."""
    loss = float(stats.get("loss", float("nan")))
    grad_norm = float(stats.get("grad_norm", float("nan")))
    nonfinite = float(stats.get("nonfinite", 0.0))
    from ..utils import metrics as M
    with _state.lock:
        if step is None:
            step = _state.step
        _state.step = int(step) + 1
        _state.recorded += 1
        interval = max(1, int(_knob("HOROVOD_SENTINEL_INTERVAL")))
        update_gauges = (_state.recorded % interval) == 0 or \
            _state.recorded == 1
        ema = _state.ema
        if update_gauges and math.isfinite(loss):
            ema = loss if ema is None else \
                (1.0 - EMA_ALPHA) * ema + EMA_ALPHA * loss
            _state.ema = ema
    bad = nonfinite > 0 or not math.isfinite(loss) \
        or not math.isfinite(grad_norm)
    row = {"step": int(step), "loss": loss, "grad_norm": grad_norm,
           "nonfinite": nonfinite,
           "ema": ema if ema is not None else loss,
           "divergence": (loss / ema) if (ema and math.isfinite(loss)
                                          and ema > 0) else 1.0}
    if update_gauges:
        M.SENTINEL_STEPS.inc()
        M.SENTINEL_LOSS.set(loss)
        M.SENTINEL_GRAD_NORM.set(grad_norm)
        if ema is not None:
            M.SENTINEL_LOSS_EMA.set(ema)
            M.SENTINEL_LOSS_DIVERGENCE.set(row["divergence"])
    if bad:
        _on_nonfinite(int(step), nonfinite, core=core)
    return row


def _on_nonfinite(step: int, count: float, core: Any = None) -> None:
    """The nonfinite chain: counter + step gauge (what the committed
    `sentinel-nonfinite` critical rule and its context ride), a native
    flight dump (reason ``nan`` — the postmortem plane's black box taken
    NOW, while the bad step's spans are still in the ring), a timeline
    instant, and a loud log line naming the step."""
    from ..utils import metrics as M
    with _state.lock:
        already = _state.last_nonfinite_step == step
        _state.last_nonfinite_step = step
    if already:
        return  # one verdict per step, however many records land on it
    M.SENTINEL_NONFINITE.inc()
    M.SENTINEL_LAST_NONFINITE_STEP.set(step)
    dump = _flight_dump(step, core=core)
    try:
        from ..utils.timeline import trace_instant
        trace_instant("alerts", "sentinel.nonfinite",
                      args={"step": step, "count": count})
    except Exception:
        pass
    try:
        from ..common import hvdlogging as log
        log.warning(
            "sentinel: NONFINITE training step %d (%s nonfinite values)%s "
            "— docs/watch.md#sentinels", step, int(count),
            f"; flight dump: {dump}" if dump else "")
    except Exception:
        pass


def _flight_dump(step: int, core: Any = None) -> Optional[str]:
    """Explicit native flight dump for a nonfinite step.  Uses the
    caller's core, else the initialized runtime's (never forces a core
    into existence — a pure-SPMD run has no controller to dump).  The
    path derives from HOROVOD_FLIGHT_RECORD (the postmortem plane's
    per-rank path) with a ``.nan`` suffix so a later crash record never
    overwrites the divergence evidence."""
    path = str(_knob("HOROVOD_FLIGHT_RECORD") or "")
    if core is None:
        try:
            from .. import runtime as _rt
            if _rt.is_initialized():
                core = _rt.get().core
        except Exception:
            core = None
    if core is None or not getattr(core, "_h", True):
        return None
    if not path:
        return None
    path = f"{path}.nan"
    try:
        if core.flight_dump(path, reason=f"nan step={step}"):
            with _state.lock:
                _state.dump_paths.append(path)
            return path
    except Exception:
        pass  # forensics must never take the training loop down
    return None


# ----------------------------------------------------------------- wrap
def wrap(step_fn: Callable, axis_name: Optional[str] = None,
         extract: Optional[Callable[[Any], Tuple[Any, Any]]] = None
         ) -> Callable:
    """Sentinel-wrap a train step: same signature, same outputs, plus
    the in-graph sentinel scalars delivered host-side via
    ``jax.debug.callback``.  ``extract(out) -> (loss, grads)`` defaults
    to ``(out[0], out[1])`` for tuple outputs and ``(out, None)`` for a
    bare loss.  With HOROVOD_SENTINEL=0 the step is returned untouched
    (the kill switch costs nothing)."""
    if not enabled():
        return step_fn

    def _default_extract(out):
        if isinstance(out, (tuple, list)) and len(out) >= 2:
            return out[0], out[1]
        return out, None

    pick = extract or _default_extract

    def wrapped(*args, **kwargs):
        import jax
        out = step_fn(*args, **kwargs)
        loss, grads = pick(out)
        stats = sentinel_stats(loss, grads, axis_name=axis_name)

        def _sink(loss_v, gn_v, nf_v):
            try:
                record({"loss": loss_v, "grad_norm": gn_v,
                        "nonfinite": nf_v})
            except Exception:
                pass  # telemetry must never take the step down

        jax.debug.callback(_sink, stats["loss"], stats["grad_norm"],
                           stats["nonfinite"])
        return out

    return wrapped
