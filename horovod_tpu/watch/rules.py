"""Declarative alert rules: detection ON TOP of the fleet time series.

The Horovod paper's timeline (arxiv 1802.05799) was born as an in-flight
diagnosis tool; this module is that idea made *standing*: YAML rules —
distributed like chaos specs (``hvdrun --alerts rules.yaml``, KV scope
``alerts``) — evaluated continuously by the driver's
:class:`AlertEngine` against the :class:`~.series.SeriesStore`
(docs/watch.md).  Five closed kinds:

  * ``threshold``       — latest value ``op`` value;
  * ``rate_of_change``  — per-second rate over ``window`` ``op`` value
                          (``roc``; counters become rates here);
  * ``mad``             — |latest - rolling median| > value x MAD over
                          ``window`` (``mad-anomaly``; a flat series has
                          MAD 0 — the ``zero_band`` field is the
                          absolute floor that decides whether a first
                          deviation off a constant fires, default 0 =
                          never, so quantized-flat series stay quiet);
  * ``absence``         — no new point for ``window`` seconds (only for
                          series that existed: bring-up is not absence);
  * ``nonfinite``       — latest value is NaN/Inf.

``for:`` durations gate firing on the condition holding continuously;
severities are ``info | warning | critical``.  Firing alerts surface at
``GET /alerts``, as instants in the merged Perfetto timeline, and as the
``hvd_alerts_total{rule,severity}`` / ``hvd_alerts_firing`` families.

The committed :data:`DEFAULT_RULES` cover the fleet's standing failure
modes: straggler suspect (the PR-5 4x-median-p99 check, now a rule —
:func:`straggler_skew` is the ONE implementation both the rules engine
and ``utils.metrics.detect_straggler`` evaluate), perf model drift,
serve shed rate, KV shard unavailability, heartbeat staleness, and the
training-quality sentinels (watch/sentinel.py).

Stdlib-only at module level (yaml and the metrics registry import
lazily), the utils/metrics.py discipline — the engine runs inside the
rendezvous server's request handlers.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

KINDS = ("threshold", "rate_of_change", "mad", "absence", "nonfinite")
_KIND_ALIASES = {"roc": "rate_of_change", "rate-of-change": "rate_of_change",
                 "mad-anomaly": "mad"}
SEVERITIES = ("info", "warning", "critical")
OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

KV_SCOPE = "alerts"
KV_KEY = "rules"


@dataclasses.dataclass(frozen=True)
class AlertRule:
    name: str
    family: str
    kind: str
    op: str = ">"
    value: float = 0.0
    window: float = 30.0      # roc/mad/absence horizon, seconds
    for_s: float = 0.0        # condition must hold this long ("for:")
    severity: str = "warning"
    rank: int = -1            # pin to one rank; -1 = every rank
    zero_band: float = 0.0    # mad: absolute floor when MAD == 0
    context_family: str = ""  # attach this family's latest value to
                              # firings (e.g. the nonfinite step number)

    def describe(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["for"] = d.pop("for_s")
        return d


# -------------------------------------------------------------- validation
def parse_rules(doc: Any) -> List[AlertRule]:
    """Build + validate rules from a parsed YAML/JSON document (a
    mapping with a ``rules`` list, or a bare list).  Raises ValueError
    on unknown kinds/ops/fields so a typo'd ruleset fails at launch,
    not silently at the detection site — the chaos-spec contract."""
    if isinstance(doc, dict):
        unknown = set(doc) - {"rules"}
        if unknown:
            raise ValueError(
                f"alert rules: unknown top-level keys {sorted(unknown)}")
        items = doc.get("rules") or []
    elif isinstance(doc, list) or doc is None:
        items = doc or []
    else:
        raise ValueError(
            f"alert rules must be a mapping or list, got {type(doc)}")
    fields = {f.name for f in dataclasses.fields(AlertRule)} | {"for"}
    rules: List[AlertRule] = []
    seen = set()
    for i, raw in enumerate(items):
        if not isinstance(raw, dict):
            raise ValueError(f"alert rules: rule #{i} must be a mapping")
        raw = dict(raw)
        if "for" in raw:
            raw["for_s"] = raw.pop("for")
        bad = set(raw) - fields
        if bad:
            raise ValueError(
                f"alert rules: rule #{i} unknown fields {sorted(bad)}")
        for req in ("name", "family", "kind"):
            if not raw.get(req):
                raise ValueError(f"alert rules: rule #{i} missing {req!r}")
        raw["kind"] = _KIND_ALIASES.get(str(raw["kind"]), str(raw["kind"]))
        if raw["kind"] not in KINDS:
            raise ValueError(
                f"alert rules: rule {raw['name']!r} kind {raw['kind']!r} "
                f"not in {KINDS}")
        if str(raw.get("op", ">")) not in OPS:
            raise ValueError(
                f"alert rules: rule {raw['name']!r} op {raw.get('op')!r} "
                f"not in {sorted(OPS)}")
        if str(raw.get("severity", "warning")) not in SEVERITIES:
            raise ValueError(
                f"alert rules: rule {raw['name']!r} severity "
                f"{raw.get('severity')!r} not in {SEVERITIES}")
        for num in ("value", "window", "for_s", "zero_band"):
            if num in raw:
                raw[num] = float(raw[num])
        if raw.get("for_s", 0.0) < 0 or raw.get("window", 30.0) <= 0:
            raise ValueError(
                f"alert rules: rule {raw['name']!r} needs for >= 0 and "
                "window > 0")
        if raw["name"] in seen:
            raise ValueError(
                f"alert rules: duplicate rule name {raw['name']!r}")
        seen.add(raw["name"])
        rules.append(AlertRule(**raw))
    return rules


def loads_rules(text: str) -> List[AlertRule]:
    try:
        doc = json.loads(text)
    except ValueError:
        import yaml
        doc = yaml.safe_load(text)
    return parse_rules(doc)


def load_rules(path: str) -> List[AlertRule]:
    with open(path) as f:
        return loads_rules(f.read())


def rules_to_json(rules: List[AlertRule]) -> str:
    """Wire format for rendezvous-KV distribution (scope ``alerts``):
    JSON, so readers never need a YAML parser — the chaos contract."""
    return json.dumps({"rules": [r.describe() for r in rules]},
                      sort_keys=True)


# ------------------------------------------------------- straggler signal
def straggler_skew(p99_by_rank: Dict[int, float],
                   floor_seconds: float = 1e-3
                   ) -> Dict[int, Dict[str, float]]:
    """Per-rank negotiation-age skew: rank's p99 over the median of its
    PEERS' p99s — the ONE implementation of the PR-5 straggler check.
    ``utils.metrics.detect_straggler`` (the live monitor + end-of-run
    report path) and the series store's derived ``hvd_straggler_skew``
    family (which the committed `straggler-suspect` threshold rule
    watches) both evaluate THIS.  Ratios below the absolute floor are
    reported as 0 so µs-level jitter on an idle fleet never fires; the
    default threshold stays 4x because power-of-2 histogram buckets make
    2x degenerate (adjacent buckets differ by exactly 2x)."""
    out: Dict[int, Dict[str, float]] = {}
    if len(p99_by_rank) < 2:
        return out  # detection needs a peer baseline
    for rank, p99 in p99_by_rank.items():
        peers = sorted(v for r, v in p99_by_rank.items() if r != rank)
        peer_median = peers[len(peers) // 2]
        ratio = p99 / max(peer_median, 1e-9)
        if p99 < floor_seconds:
            ratio = 0.0
        out[rank] = {"ratio": ratio, "p99": p99,
                     "peer_median_p99": peer_median}
    return out


def straggler_verdict(p99_by_rank: Dict[int, float],
                      skew_ratio: float = 4.0,
                      floor_seconds: float = 1e-3
                      ) -> Optional[Dict[str, float]]:
    """The monitor-shaped verdict over :func:`straggler_skew`: the
    worst-skewed rank iff its ratio clears the threshold, else None."""
    skews = straggler_skew(p99_by_rank, floor_seconds=floor_seconds)
    if not skews:
        return None
    rank = max(skews, key=lambda r: skews[r]["ratio"])
    s = skews[rank]
    if not OPS[">="](s["ratio"], skew_ratio):
        return None
    return {"rank": rank, "p99": s["p99"],
            "peer_median_p99": s["peer_median_p99"],
            "ratio": s["ratio"]}


# --------------------------------------------------------- default ruleset
# The standing failure modes every fleet watches (docs/watch.md#defaults);
# `hvdrun --alerts` rules MERGE over these by name (a user rule named
# like a default replaces it).
DEFAULT_RULES: List[AlertRule] = parse_rules({"rules": [
    # PR-5's 4x-median-p99 straggler check as a rule: the series store
    # derives hvd_straggler_skew from the shared _age_rows/straggler_skew
    # path, so this threshold IS the old monitor's comparison.
    {"name": "straggler-suspect", "family": "hvd_straggler_skew",
     "kind": "threshold", "op": ">=", "value": 4.0, "severity": "warning"},
    # Perf plane self-assessment: the roofline model pricing less than
    # half of what the wall clock measures for 15 s means the
    # attribution (and anything autoscaling on it) is off the rails.
    {"name": "perf-model-drift", "family": "hvd_perf_model_drift_ratio",
     "kind": "threshold", "op": ">=", "value": 2.0, "for": 15,
     "severity": "warning"},
    # Serving front door under duress: any sustained shedding is an
    # incident (capacity, not code — but an incident).
    {"name": "serve-shed-rate", "family": "hvd_serve_sheds_total",
     "kind": "rate_of_change", "op": ">", "value": 0.0, "window": 30,
     "for": 5, "severity": "warning"},
    # Control-plane partial outage: client-side per-attempt failures
    # against a KV shard (docs/control-plane.md).
    {"name": "kv-shard-unavailable",
     "family": "hvd_kv_shard_unavailable_total",
     "kind": "rate_of_change", "op": ">", "value": 0.0, "window": 30,
     "severity": "critical"},
    # Liveness: a rank that heartbeated before has gone silent (the
    # health plane's staleness as a standing rule).
    {"name": "heartbeat-stale", "family": "heartbeat", "kind": "absence",
     "window": 15, "severity": "critical"},
    # Training-quality sentinels (watch/sentinel.py): a nonfinite step
    # (counter moved — context carries the step number), a NaN loss
    # series, and a loss diverging from its own EMA.
    {"name": "sentinel-nonfinite",
     "family": "hvd_sentinel_nonfinite_total", "kind": "rate_of_change",
     "op": ">", "value": 0.0, "window": 60, "severity": "critical",
     "context_family": "hvd_sentinel_last_nonfinite_step"},
    {"name": "sentinel-loss-nonfinite", "family": "hvd_sentinel_loss",
     "kind": "nonfinite", "severity": "critical"},
    {"name": "sentinel-loss-divergence",
     "family": "hvd_sentinel_loss_divergence", "kind": "threshold",
     "op": ">=", "value": 3.0, "for": 20, "severity": "warning"},
    # Memory plane (perf/memstats.py; docs/memory.md): device residency
    # sustained above the high watermark — the page that precedes the
    # kernel's SIGKILL.  `for:` keeps a transient allocation spike from
    # paging; the memstats sentinel separately fires once per crossing
    # (flight dump reason 'mem'), so the black box exists even when the
    # rule's duration gate never opens.
    {"name": "mem-pressure-high", "family": "hvd_mem_watermark",
     "kind": "threshold", "op": ">=", "value": 0.9, "for": 10,
     "severity": "critical", "context_family": "hvd_mem_bytes_in_use"},
    # Serve KV-cache pool exhausted: admission stalls and eviction
    # pressure follow — capacity, not code, but an incident
    # (docs/serving.md, docs/memory.md#kv-pool).  Watches utilization,
    # not the free count: an unset gauge snapshots as 0, so free <= 0
    # would read as 'dry' on every non-serving rank, while util only
    # reaches 1.0 when an ACTIVE pool has no free blocks.
    {"name": "kv-pool-dry", "family": "hvd_mem_kv_util",
     "kind": "threshold", "op": ">=", "value": 1.0, "for": 10,
     "severity": "warning", "context_family": "hvd_mem_kv_blocks_used"},
    # Request-lifecycle component regressions (docs/serving.md#request-
    # lifecycle): the series store derives per-component p99 gauges from
    # the hvd_serve_component_seconds histogram.  A sustained handoff
    # p99 means the prefill->decode KV transfer (or the router transit
    # under it) is the tail — the disaggregation tax made visible; a
    # sustained queue p99 is admission backlog ahead of any engine work.
    {"name": "serve-handoff-p99", "family": "hvd_serve_handoff_p99_seconds",
     "kind": "threshold", "op": ">=", "value": 0.5, "for": 10,
     "severity": "warning"},
    {"name": "serve-queue-p99", "family": "hvd_serve_queue_p99_seconds",
     "kind": "threshold", "op": ">=", "value": 2.0, "for": 10,
     "severity": "warning"},
    # Memory model self-assessment: measured residency 2x away from the
    # zero_memory_bytes prediction for 15 s means the attribution (and
    # the layout solver consuming its headroom number) is off the rails
    # — the PR-14 drift discipline, for bytes-resident.
    {"name": "mem-model-drift", "family": "hvd_mem_model_drift_ratio",
     "kind": "threshold", "op": ">=", "value": 2.0, "for": 15,
     "severity": "warning"},
]})


def merge_rules(user_rules: Optional[List[AlertRule]]) -> List[AlertRule]:
    """Defaults + user rules, user winning by name."""
    by_name = {r.name: r for r in DEFAULT_RULES}
    for r in (user_rules or []):
        by_name[r.name] = r
    return [by_name[n] for n in by_name]


# ----------------------------------------------------------------- engine
class AlertEngine:
    """Evaluate rules against a SeriesStore; track ``for:`` state,
    firing transitions, the alert metric families, and the timeline
    instants.  Evaluation is cheap (latest points + small windows) and
    runs on every metrics ingest and every ``GET /alerts``."""

    HISTORY = 256

    def __init__(self, store, rules: Optional[List[AlertRule]] = None,
                 instant_fn: Optional[Callable[..., None]] = None,
                 log_fn: Optional[Callable[[str], None]] = None):
        self.store = store
        self.rules = merge_rules(rules)
        self.user_rule_names: List[str] = [r.name for r in (rules or [])]
        self._instant_fn = instant_fn
        self._log = log_fn
        self._lock = threading.Lock()
        # (rule, rank) -> {"pending_since", "firing_since", "value"}
        self._state: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self._fired_total: Dict[Tuple[str, str], int] = {}
        self._history: deque = deque(maxlen=self.HISTORY)

    def set_rules(self, rules: Optional[List[AlertRule]]) -> None:
        with self._lock:
            self.rules = merge_rules(rules)
            self.user_rule_names = [r.name for r in (rules or [])]
            self._state.clear()

    # ---------------------------------------------------------- evaluation
    def _condition(self, rule: AlertRule, rank: int, now: float
                   ) -> Tuple[bool, Optional[float]]:
        """(condition holds, observed value) for one (rule, rank)."""
        cmp = OPS[rule.op]
        if rule.kind == "absence":
            latest = self.store.latest(rank, rule.family)
            if latest is None:
                return False, None  # never seen: bring-up, not absence
            age = now - latest[0]
            return age > rule.window, age
        latest = self.store.latest(rank, rule.family)
        if latest is None:
            return False, None
        t, v = latest
        if rule.kind == "threshold":
            return cmp(v, rule.value), v
        if rule.kind == "nonfinite":
            return not math.isfinite(v), v
        pts = self.store.points(rank, rule.family, now, rule.window)
        if rule.kind == "rate_of_change":
            if len(pts) < 2:
                return False, None
            (t0, v0), (t1, v1) = pts[0], pts[-1]
            if t1 <= t0:
                return False, None
            rate = (v1 - v0) / (t1 - t0)
            return cmp(rate, rule.value), rate
        if rule.kind == "mad":
            if len(pts) < 4:
                return False, None  # too little history to call anomaly
            vals = sorted(p[1] for p in pts[:-1])
            median = vals[len(vals) // 2]
            mad = sorted(abs(x - median) for x in vals)[len(vals) // 2]
            dev = abs(v - median)
            if mad > 0:
                return dev > rule.value * mad, dev / mad
            # MAD zero-band: a perfectly flat history fires only past
            # the explicit absolute band (default 0 = never) — power-of-2
            # bucket quantization makes flat series the common case.
            return (rule.zero_band > 0 and dev > rule.zero_band), dev
        return False, None

    def _candidate_ranks(self, rule: AlertRule) -> List[int]:
        if rule.rank >= 0:
            return [rule.rank]
        return self.store.ranks(rule.family)

    def evaluate(self, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the currently-firing list (the
        ``GET /alerts`` ``firing`` payload).  Transitions update the
        hvd_alerts_* families, the bounded history, the timeline
        instants, and the log."""
        now = time.time() if now is None else float(now)
        with self._lock:
            rules = list(self.rules)
        firing: List[Dict[str, Any]] = []
        per_rule_firing: Dict[str, int] = {r.name: 0 for r in rules}
        for rule in rules:
            for rank in self._candidate_ranks(rule):
                cond, value = self._condition(rule, rank, now)
                key = (rule.name, rank)
                with self._lock:
                    st = self._state.setdefault(
                        key, {"pending_since": None, "firing_since": None})
                    if not cond:
                        st["pending_since"] = None
                        if st["firing_since"] is not None:
                            st["firing_since"] = None
                            self._history.append(
                                {"t": now, "rule": rule.name, "rank": rank,
                                 "event": "resolved"})
                        continue
                    if st["pending_since"] is None:
                        st["pending_since"] = now
                    if now - st["pending_since"] < rule.for_s:
                        continue  # condition true, `for:` not yet served
                    newly = st["firing_since"] is None
                    if newly:
                        st["firing_since"] = now
                        k = (rule.name, rule.severity)
                        self._fired_total[k] = \
                            self._fired_total.get(k, 0) + 1
                        self._history.append(
                            {"t": now, "rule": rule.name, "rank": rank,
                             "event": "firing",
                             "severity": rule.severity, "value": value})
                    since = st["firing_since"]
                entry = {"rule": rule.name, "severity": rule.severity,
                         "kind": rule.kind, "family": rule.family,
                         "rank": rank, "since": since, "value": value}
                if rule.context_family:
                    ctx = self.store.latest(rank, rule.context_family)
                    if ctx is not None:
                        entry["context"] = {rule.context_family: ctx[1]}
                firing.append(entry)
                per_rule_firing[rule.name] += 1
                if newly:
                    self._announce(rule, rank, value, now)
        self._update_metrics(per_rule_firing)
        return firing

    def _announce(self, rule: AlertRule, rank: int, value, now: float
                  ) -> None:
        msg = (f"[hvd] ALERT {rule.severity} {rule.name}: rank {rank} "
               f"{rule.family} {rule.kind} value={value}")
        if self._log is not None:
            try:
                self._log(msg)
            except Exception:
                pass  # alerting must never take the server down
        if self._instant_fn is not None:
            try:
                self._instant_fn(rule=rule.name, rank=rank,
                                 severity=rule.severity, now=now)
            except Exception:
                pass

    def _update_metrics(self, per_rule_firing: Dict[str, int]) -> None:
        try:  # lazy: the engine must stay importable standalone
            from ..utils import metrics as M
        except ImportError:
            return
        with self._lock:
            fired = dict(self._fired_total)
        for (rule, severity), count in fired.items():
            M.ALERTS_TOTAL.set_total(count, rule=rule, severity=severity)
        for rule, n in per_rule_firing.items():
            M.ALERTS_FIRING.set(n, rule=rule)

    # --------------------------------------------------------------- views
    def fired_total(self) -> List[Dict[str, Any]]:
        """Lifetime firing transitions by (rule, severity) — the shape
        bench.py's ``fired_alerts`` artifact section records."""
        with self._lock:
            return [{"rule": r, "severity": s, "count": c}
                    for (r, s), c in sorted(self._fired_total.items())]

    def view(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /alerts`` payload: firing first, then the ruleset
        and the bounded transition history."""
        now = time.time() if now is None else float(now)
        firing = self.evaluate(now)
        with self._lock:
            history = list(self._history)
        return {
            "now": now,
            "firing": sorted(
                firing,
                key=lambda f: (-SEVERITIES.index(f["severity"]),
                               f["rule"], f["rank"])),
            "rules": [r.describe() for r in self.rules],
            "user_rules": list(self.user_rule_names),
            "fired_total": self.fired_total(),
            "history": history,
        }
