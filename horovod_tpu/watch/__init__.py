"""Watch plane: fleet time-series history, declarative alert rules, and
training-quality sentinels (docs/watch.md).

Three legs over the planes already built:

  * :mod:`~horovod_tpu.watch.series` — a bounded, downsampling
    time-series store on the rendezvous KV shard that owns the
    ``metrics`` scope (piggybacks on MetricsPublisher PUTs, survives
    elastic resets), served at ``GET /series``;
  * :mod:`~horovod_tpu.watch.rules` — YAML alert rules ({threshold,
    rate-of-change, MAD-anomaly, absence, nonfinite} with ``for:``
    durations and severities) evaluated by the driver's AlertEngine,
    served at ``GET /alerts``, surfaced as timeline instants and the
    ``hvd_alerts_*`` families, distributed via ``hvdrun --alerts``;
  * :mod:`~horovod_tpu.watch.sentinel` — ``hvd.sentinel``-wrapped train
    steps computing trace-time grad-norm / nonfinite / loss-EMA
    scalars, with a nonfinite step firing an explicit flight dump
    (reason ``nan``) plus the committed critical rule.

:class:`WatchState` is the server-side composition the rendezvous
server installs at start (runner/http_server.py): ingest hooks for the
``metrics`` and ``health`` scopes, rate-limited by the series
resolution, plus the engine the routes evaluate.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from .rules import (AlertEngine, AlertRule, DEFAULT_RULES, KV_KEY,
                    KV_SCOPE, load_rules, loads_rules, merge_rules,
                    parse_rules, rules_to_json, straggler_skew,
                    straggler_verdict)
from .series import SeriesStore
from . import sentinel  # noqa: F401  (hvd.sentinel rides this package)


class WatchState:
    """SeriesStore + AlertEngine behind the rendezvous server's ingest
    hooks.  ``ingest_metrics`` is called from the KV PUT handler for
    every ``metrics``-scope write and rate-limits the (JSON-parse +
    fold) work per rank to the series resolution, so a fast publisher
    costs the server nothing extra."""

    def __init__(self, retention_s: float = 600.0,
                 resolution_s: float = 5.0,
                 rules: Optional[List[AlertRule]] = None,
                 instant_fn=None, log_fn=None):
        self.store = SeriesStore(retention_s=retention_s,
                                 resolution_s=resolution_s)
        self.engine = AlertEngine(self.store, rules=rules,
                                  instant_fn=instant_fn, log_fn=log_fn)
        self._lock = threading.Lock()
        self._last_ingest: Dict[str, float] = {}

    def ingest_metrics(self, key: str, value: bytes,
                       t: Optional[float] = None) -> bool:
        """Fold one metrics-scope PUT into the series store and run an
        evaluation pass.  Returns False when skipped (rate limit or a
        torn payload — telemetry must never fail a KV op)."""
        t = time.time() if t is None else float(t)
        with self._lock:
            last = self._last_ingest.get(key)
            if last is not None and t - last < self.store.resolution:
                return False
            self._last_ingest[key] = t
        try:
            snap = json.loads(value)
            rank = int(snap.get("rank",
                                key.rsplit(".", 1)[-1]))
        except (ValueError, TypeError):
            return False
        self.store.ingest_snapshot(rank, snap, t)
        self.engine.evaluate(t)
        return True

    def note_heartbeat(self, key: str, t: Optional[float] = None) -> None:
        try:
            rank = int(key.rsplit(".", 1)[-1])
        except ValueError:
            return
        self.store.note_heartbeat(rank, t)


def make_watch_state(instant_fn=None, log_fn=None,
                     rules: Optional[List[AlertRule]] = None
                     ) -> WatchState:
    """WatchState from the env knobs — what RendezvousServer.start()
    installs on the ``metrics``-owning shard store."""
    from ..common.knobs import current
    return WatchState(
        retention_s=float(current("HOROVOD_SERIES_RETENTION")),
        resolution_s=float(current("HOROVOD_SERIES_RESOLUTION")),
        rules=rules, instant_fn=instant_fn, log_fn=log_fn)


def validate_watch_knobs(knobs) -> None:
    """Init-time validation of the watch-plane knob surface
    (common/knobs.py contract: a bad value fails hvd.init, never a
    detector mid-run).  Partial-mapping tolerant for old callers."""
    def get(name, default):
        try:
            v = knobs[name]
        except (KeyError, TypeError):
            return default
        return v
    retention = float(get("HOROVOD_SERIES_RETENTION", 600.0))
    resolution = float(get("HOROVOD_SERIES_RESOLUTION", 5.0))
    if retention <= 0:
        raise ValueError(
            f"HOROVOD_SERIES_RETENTION={retention} invalid; the series "
            "store needs a positive history horizon in seconds "
            "(docs/watch.md)")
    if resolution <= 0 or resolution > retention:
        raise ValueError(
            f"HOROVOD_SERIES_RESOLUTION={resolution} invalid; must be "
            "positive and no larger than HOROVOD_SERIES_RETENTION="
            f"{retention} (docs/watch.md)")
    interval = int(get("HOROVOD_SENTINEL_INTERVAL", 1))
    if interval < 1:
        raise ValueError(
            f"HOROVOD_SENTINEL_INTERVAL={interval} invalid; the sentinel "
            "records every Nth step with N >= 1 (docs/watch.md)")
    alerts = str(get("HOROVOD_ALERTS", "") or "")
    if alerts:
        try:
            load_rules(alerts)
        except OSError as e:
            raise ValueError(
                f"HOROVOD_ALERTS={alerts!r} unreadable: {e} "
                "(docs/watch.md#rules)") from e
        except ValueError as e:
            raise ValueError(
                f"HOROVOD_ALERTS={alerts!r} invalid: {e}") from e


__all__ = [
    "AlertEngine", "AlertRule", "DEFAULT_RULES", "KV_KEY", "KV_SCOPE",
    "SeriesStore", "WatchState", "load_rules", "loads_rules",
    "make_watch_state", "merge_rules", "parse_rules", "rules_to_json",
    "sentinel", "straggler_skew", "straggler_verdict",
    "validate_watch_knobs",
]
