"""Fleet time-series store: bounded history of the planes' signals.

Every observability plane so far (metrics PR 1, tracing PR 5, postmortem
PR 6, perf PR 8) answers questions about *now* or about a crash that
already happened; this store keeps the *history* detection needs
(docs/watch.md).  It lives SERVER-side, on the rendezvous KV shard that
owns the ``metrics`` scope (runner/http_server.py), which buys three
properties for free:

  * **zero extra worker traffic** — it piggybacks on the MetricsPublisher
    PUTs workers already send every ``HOROVOD_METRICS_INTERVAL``;
  * **elastic survival** — the rendezvous server (and its shards) live in
    the driver process, which outlives every reset round, so history
    spans fleet incarnations;
  * **one clock** — points are stamped with the server's receipt time,
    the same reference clock the tracing plane aligns against.

Memory is bounded twice over: each ``(rank, family)`` series is a
downsampling ring holding at most ``retention / resolution + 1`` points
(a newer sample inside the same resolution bucket *replaces* the bucket's
point — last-wins, correct for the cumulative counters and gauges that
ride snapshots), and the store caps the total series count — beyond it
new families are counted as dropped, never grown.  Knobs:
``HOROVOD_SERIES_RETENTION`` / ``HOROVOD_SERIES_RESOLUTION``
(common/knobs.py; validated at hvd.init).

Deliberately stdlib-only at module level (lazy package imports inside
methods), mirroring utils/metrics.py: ingest runs inside the KV server's
request handler and must never drag jax in.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# Derived families the ingest computes from snapshots (not raw registry
# families): the negotiation-age p99 per rank and the straggler skew
# ratio the committed `straggler-suspect` default rule thresholds
# (watch/rules.py — ONE detection path with the PR-5 monitor).
NEGOTIATION_AGE_P99 = "hvd_negotiation_age_p99"
STRAGGLER_SKEW = "hvd_straggler_skew"
# Heartbeat liveness series (value = 1 at each receipt): what the
# `heartbeat-stale` default rule's absence kind ages against.
HEARTBEAT_FAMILY = "heartbeat"
# Request-lifecycle attribution (docs/serving.md#request-lifecycle):
# ingest derives one plain p99 gauge series per lifecycle component from
# the hvd_serve_component_seconds histogram buckets, so the committed
# component-regression rules (e.g. `serve-handoff-p99`, watch/rules.py)
# threshold a scalar instead of re-deriving quantiles per evaluation.
SERVE_COMPONENT_FAMILY = "hvd_serve_component_seconds"
SERVE_COMPONENT_P99_FMT = "hvd_serve_{}_p99_seconds"


class SeriesRing:
    """One (rank, family) series: a bounded, downsampling point ring."""

    __slots__ = ("retention", "resolution", "cap", "points")

    def __init__(self, retention_s: float, resolution_s: float):
        self.retention = float(retention_s)
        self.resolution = float(resolution_s)
        # +1: the in-progress resolution bucket rides beside a full
        # retention window of closed buckets.
        self.cap = max(2, int(math.ceil(self.retention / self.resolution))
                       + 1)
        self.points: List[List[float]] = []  # [[t, v], ...] ascending t

    def add(self, t: float, v: float) -> None:
        if self.points and t - self.points[-1][0] < self.resolution:
            # Downsample: last value wins within a resolution bucket
            # (cumulative counters and gauges both want the newest).
            self.points[-1][1] = v
            return
        self.points.append([float(t), float(v)])
        if len(self.points) > self.cap:
            del self.points[0]
        cutoff = t - self.retention
        while len(self.points) > 1 and self.points[0][0] < cutoff:
            del self.points[0]

    def latest(self) -> Optional[Tuple[float, float]]:
        if not self.points:
            return None
        t, v = self.points[-1]
        return t, v

    def window(self, now: float, window_s: float) -> List[List[float]]:
        cutoff = now - float(window_s)
        return [[t, v] for t, v in self.points if t >= cutoff]


class SeriesStore:
    """Per-(rank, family) rings + the snapshot-ingest logic."""

    def __init__(self, retention_s: float = 600.0,
                 resolution_s: float = 5.0, max_series: int = 4096):
        self.retention = float(retention_s)
        self.resolution = float(resolution_s)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[int, str], SeriesRing] = {}
        self.dropped_series = 0

    # ------------------------------------------------------------ raw add
    def add(self, rank: int, family: str, t: float, v: float) -> None:
        key = (int(rank), str(family))
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return  # bounded cardinality: drop, never grow
                ring = SeriesRing(self.retention, self.resolution)
                self._series[key] = ring
            ring.add(t, v)

    def latest(self, rank: int, family: str
               ) -> Optional[Tuple[float, float]]:
        with self._lock:
            ring = self._series.get((int(rank), str(family)))
            return ring.latest() if ring else None

    def ranks(self, family: str) -> List[int]:
        """Ranks that ever produced this family, ascending."""
        with self._lock:
            return sorted(r for r, f in self._series if f == family)

    def families(self) -> List[str]:
        with self._lock:
            return sorted({f for _, f in self._series})

    def points(self, rank: int, family: str, now: float,
               window_s: Optional[float] = None) -> List[List[float]]:
        with self._lock:
            ring = self._series.get((int(rank), str(family)))
            if ring is None:
                return []
            if window_s is None:
                return [list(p) for p in ring.points]
            return ring.window(now, window_s)

    def point_count(self) -> int:
        with self._lock:
            return sum(len(r.points) for r in self._series.values())

    # ------------------------------------------------------ snapshot ingest
    def ingest_snapshot(self, rank: int, snap: Dict[str, Any],
                        t: Optional[float] = None) -> int:
        """Fold one MetricsRegistry.snapshot() into the store: counters
        and gauges as their label-summed value, histograms as their
        observation count, plus the derived negotiation-age p99 and the
        fleet straggler skew.  Returns the number of families stored."""
        t = time.time() if t is None else float(t)
        fams = snap.get("families", {})
        stored = 0
        for name, fam in fams.items():
            kind = fam.get("kind")
            samples = fam.get("samples", [])
            if kind == "histogram":
                v = float(sum(s.get("count", 0) for s in samples))
            else:
                v = float(sum(s.get("value", 0.0) for s in samples))
            self.add(rank, name, t, v)
            stored += 1
        self._ingest_derived(rank, snap, t)
        return stored

    def _ingest_derived(self, rank: int, snap: Dict[str, Any],
                        t: float) -> None:
        """Negotiation-age p99 (shared _age_rows source) + the straggler
        skew of EVERY rank, recomputed from latest p99s — the series the
        committed `straggler-suspect` rule thresholds."""
        fam = snap.get("families", {}).get(SERVE_COMPONENT_FAMILY)
        if isinstance(fam, dict) and fam.get("kind") == "histogram":
            bounds = fam.get("bounds") or []
            for s in fam.get("samples", []):
                comp = (s.get("labels") or {}).get("component")
                count = int(s.get("count") or 0)
                if not comp or not count or not bounds:
                    continue
                # Bucket-upper-bound p99, same math as
                # Histogram.quantile — recomputed here because ingest
                # only sees the snapshot, not the registry object.
                target = 0.99 * count
                cum, p99c = 0, float(bounds[-1])
                for c, bound in zip(s.get("counts") or [], bounds):
                    cum += int(c)
                    if cum >= target:
                        p99c = float(bound)
                        break
                self.add(rank, SERVE_COMPONENT_P99_FMT.format(comp),
                         t, p99c)
        from ..utils.metrics import _age_rows
        rows = _age_rows({int(rank): snap})
        if not rows:
            return
        _, _, p99, _ = rows[0]
        if p99 is None:
            return
        self.add(rank, NEGOTIATION_AGE_P99, t, float(p99))
        p99_by_rank = {}
        for r in self.ranks(NEGOTIATION_AGE_P99):
            latest = self.latest(r, NEGOTIATION_AGE_P99)
            if latest is not None:
                p99_by_rank[r] = latest[1]
        from .rules import straggler_skew
        for r, skew in straggler_skew(p99_by_rank).items():
            self.add(r, STRAGGLER_SKEW, t, skew["ratio"])

    def note_heartbeat(self, rank: int, t: Optional[float] = None) -> None:
        """One heartbeat receipt: the absence-kind liveness series."""
        self.add(rank, HEARTBEAT_FAMILY,
                 time.time() if t is None else float(t), 1.0)

    # -------------------------------------------------------------- query
    def query(self, family: Optional[str] = None,
              rank: Optional[int] = None,
              window_s: Optional[float] = None,
              now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /series`` payload: every matching series with its
        points, plus the store's own bounds so readers know the math."""
        now = time.time() if now is None else float(now)
        out: List[Dict[str, Any]] = []
        with self._lock:
            keys = sorted(self._series)
        for r, f in keys:
            if family is not None and f != family:
                continue
            if rank is not None and r != int(rank):
                continue
            pts = self.points(r, f, now, window_s)
            if pts:
                out.append({"rank": r, "family": f, "points": pts})
        return {"now": now, "retention_s": self.retention,
                "resolution_s": self.resolution,
                "dropped_series": self.dropped_series,
                "series": out}
