"""horovod_tpu: a TPU-native distributed deep-learning training framework.

Capability surface of Horovod (reference: darkjh/horovod v0.22.0), re-designed
TPU-first: XLA collectives over ICI/DCN on a `jax.sharding.Mesh` replace
NCCL/MPI/Gloo; gradient sync is bucket-fused `psum` inside the jitted SPMD
train step; `hvdrun` spawns per-host workers on TPU VM slices with an HTTP
rendezvous; elastic training re-rendezvouses across preemptible slices.

Public API parity (reference: horovod/torch/__init__.py,
horovod/tensorflow/__init__.py):

    import horovod_tpu as hvd
    hvd.init()
    hvd.rank(), hvd.size(), hvd.local_rank(), hvd.local_size()
    hvd.allreduce / allgather / broadcast / alltoall / reducescatter
    hvd.DistributedOptimizer(optax_opt, axis_name='hvd')
    hvd.broadcast_parameters / broadcast_optimizer_state / broadcast_object
    hvd.Compression, hvd.Average / Sum / Adasum / Min / Max / Product
"""

from __future__ import annotations

__version__ = "0.3.0"

from . import runtime as _rt
from .runtime import init, shutdown, is_initialized
from .common.reduce_op import (ReduceOp, Average, Sum, Adasum, Min, Max,
                               Product)
from .common.exceptions import (HorovodInternalError, HostsUpdatedInterrupt,
                                TensorShapeMismatchError,
                                TensorDtypeMismatchError,
                                DuplicateTensorNameError, StallError)
from .ops.collectives import (allreduce, allreduce_async, grouped_allreduce,
                              allgather, allgather_async, allgather_ragged,
                              broadcast, broadcast_async, alltoall,
                              reducescatter, barrier, synchronize, poll,
                              process_allgather, process_local, Handle)
from .ops.compression import Compression
from .ops import spmd
from .ops import wire
from .ops import overlap
from .data.loader import prefetch
from .optimizer import (DistributedOptimizer, distributed_optimizer,
                        sync_gradients, sync_gradients_ef,
                        wire_residual_report, distributed_grad)
from .functions import (broadcast_parameters, broadcast_optimizer_state,
                        broadcast_object, allgather_object)
from .checkpoint import (CheckpointManager, save_checkpoint,
                         restore_checkpoint)
from .ops.flash_attention import flash_attention
from .runner.api import run
from .utils.probe import probe_backend


# ---------------------------------------------------------------- topology API
def rank() -> int:
    """Global worker (chip) rank of this process's first chip."""
    return _rt.get().rank()


def size() -> int:
    """Total number of worker chips in the mesh."""
    return _rt.get().size()


def local_rank() -> int:
    return _rt.get().local_rank()


def local_size() -> int:
    """Chips driven by this process."""
    return _rt.get().local_size()


def cross_rank() -> int:
    """Host/process index (CROSS scope, reference: common.h:119-123)."""
    return _rt.get().cross_rank()


def cross_size() -> int:
    return _rt.get().cross_size()


def process_rank() -> int:
    return _rt.get().process_rank()


def process_size() -> int:
    return _rt.get().process_size()


def mesh():
    """The global `jax.sharding.Mesh` collectives run over."""
    return _rt.get().mesh


def autotuner():
    """The live autotuner when HOROVOD_AUTOTUNE is enabled, else None
    (reference: ParameterManager, parameter_manager.{h,cc}).  Feed it step
    measurements via ``autotuner().measure(nbytes=...)``."""
    return _rt.get().autotuner


def is_homogeneous() -> bool:
    """True when all hosts drive the same number of chips (reference:
    horovod_is_homogeneous, operations.cc:838)."""
    rt = _rt.get()
    return rt.size() == rt.local_size() * rt.process_size()


# ------------------------------------------------------------------ metrics
def metrics_snapshot() -> dict:
    """Point-in-time snapshot of every metric family this process records
    (native controller counters/histograms, collectives/fusion, stall
    inspector, elastic events) as a JSON-able dict — the same payload
    workers publish for the ``/metrics`` fleet view (``docs/metrics.md``)."""
    return _rt.get().metrics_snapshot()


def perf_report() -> dict:
    """This rank's step-time attribution report (``docs/profiling.md``):
    the measured compute / exposed-comm / host-input / stall
    decomposition (summing exactly to measured step time), the roofline
    model's predicted step and its drift, the native per-op-name
    aggregates, and the local bottleneck verdict — the same payload
    workers publish for the ``GET /perf`` fleet view.  Record steps with
    ``hvd.perf.timed_step()`` / ``hvd.perf.record_step``."""
    from .perf import report as _perf_report
    return _perf_report()


# ----------------------------------------------------------- built/enabled API
# Build-capability probes (reference: operations.cc:845-915 horovod_mpi_built
# etc.).  This framework has exactly one data plane: XLA over ICI/DCN.
# CAPABILITY_EXPORTS is the ONE list every frontend re-exports (each
# extends its __all__ from it, so the parity surface cannot drift
# between frontends).
CAPABILITY_EXPORTS = (
    "tpu_built", "xla_built", "mpi_built", "nccl_built", "gloo_built",
    "ccl_built", "ddl_built", "cuda_built", "rocm_built", "mpi_enabled",
    "gloo_enabled", "mpi_threads_supported", "start_timeline",
    "stop_timeline")

def tpu_built() -> bool:
    return True


def xla_built() -> bool:
    return True


def mpi_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def gloo_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False


# ---------------------------------------------------------------- timeline API
def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    """Start writing the Chrome-trace timeline (reference:
    horovod_start_timeline, operations.cc:740-769)."""
    _rt.get().start_timeline(file_path, mark_cycles=mark_cycles)


def stop_timeline() -> None:
    _rt.get().stop_timeline()


# xprof deep-dive profiling (NVTX-ranges analog; utils/profiler.py)
from .utils import profiler  # noqa: E402
# hyperparameter search over the native GP (reference:
# docs/hyperparameter_search.rst's Ray Tune story)
from . import tune  # noqa: E402
# deterministic fault injection (hvdrun --chaos; docs/chaos.md) —
# training loops call hvd.chaos.step(i) to clock scheduled faults
from . import chaos  # noqa: E402
# crash forensics (hvdrun --postmortem / hvdrun doctor;
# docs/postmortem.md) — training loops call
# hvd.postmortem.record_step(i) so heartbeats carry step progress
from . import postmortem  # noqa: E402
# serving plane (hvdrun --serve; docs/serving.md) — continuous-batching
# multi-host inference over the trained models; engine and router load
# lazily inside the subpackage
from . import serve  # noqa: E402
# perf-attribution plane (docs/profiling.md) — roofline cost model +
# step-time decomposition ledger; training loops record steps via
# hvd.perf.timed_step() and read hvd.perf_report()
from . import perf  # noqa: E402
# watch plane (docs/watch.md) — fleet time-series history, declarative
# alert rules (hvdrun --alerts), and training-quality sentinels:
# hvd.sentinel.wrap(step_fn) watches grad-norm/nonfinite/loss-EMA
from . import watch  # noqa: E402
from .watch import sentinel  # noqa: E402


__all__ = [
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "process_rank", "process_size", "mesh", "is_homogeneous",
    "allreduce", "allreduce_async", "grouped_allreduce", "allgather",
    "allgather_async", "allgather_ragged", "broadcast", "broadcast_async",
    "alltoall", "reducescatter", "barrier", "synchronize", "poll",
    "process_allgather", "process_local", "Handle",
    "DistributedOptimizer", "distributed_optimizer", "sync_gradients",
    "sync_gradients_ef", "wire_residual_report", "wire", "overlap",
    "prefetch", "distributed_grad",
    "broadcast_parameters", "broadcast_optimizer_state", "broadcast_object",
    "allgather_object",
    "Compression", "ReduceOp", "Average", "Sum", "Adasum", "Min", "Max",
    "Product", "spmd",
    "HorovodInternalError", "HostsUpdatedInterrupt",
    "tpu_built", "xla_built", "mpi_built", "nccl_built", "gloo_built",
    "ccl_built", "ddl_built", "cuda_built", "rocm_built",
    "mpi_enabled", "gloo_enabled", "mpi_threads_supported",
    "start_timeline", "stop_timeline", "profiler", "tune",
    "CheckpointManager", "save_checkpoint", "restore_checkpoint",
    "flash_attention", "run",
    "__version__", "probe_backend", "metrics_snapshot", "chaos",
    "postmortem", "serve", "perf", "perf_report", "watch", "sentinel",
]
