"""Step-time decomposition ledger: the measured half of the attribution
plane (docs/profiling.md).

Every recorded step is split into four components that sum EXACTLY to the
measured wall time —

  * ``host_input_s``  — measured host-side input wait (the loader's
    ``prefetch`` hook feeds it; ``add_input_wait`` for custom loops);
  * ``compute_s``     — the cost model's FLOPs / chip peak;
  * ``exposed_comm_s``— the cost model's non-overlapped comm bytes over
    the link-class bandwidth (the ``hvd_overlap_*`` gauge model);
  * ``stall_s``       — the residual: time the model cannot attribute
    (scheduler gaps, stragglers, host jitter).

When the model predicts MORE than the measured step leaves room for, the
modeled components are scaled down to fit and the overshoot is recorded
as ``model_drift_ratio`` (> 1 = the model over-predicts) — predicted vs
measured deltas are first-class outputs, so cost-model drift is itself
observable rather than silently corrupting the attribution.

The serving tier's per-request SLO attribution (serve/trace.py
``attribute``) follows the same discipline for request wall time:
measured lifecycle components, a residual leg absorbing the
unattributed remainder, rescale-to-fit on overshoot with the ratio kept
observable (``hvd_serve_trace_overattribution_ratio``) — one
attribution contract across the training and serving planes.

The module-global ledger backs ``hvd.perf_report()`` and the new
``hvd_perf_*`` metric families; :class:`PerfPublisher` PUTs per-rank
reports to the rendezvous KV scope ``perf`` (MetricsPublisher's pattern),
which ``GET /perf`` merges into the fleet view and ``hvdrun doctor
--perf`` renders (runner/http_server.py, runner/doctor.py).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional

PERF_SCOPE = "perf"
REPORT_VERSION = 1

# Bottleneck verdicts, in the order doctor renders them (docs/profiling.md).
VERDICTS = ("compute-bound", "comm-bound", "input-bound", "stall-bound",
            "straggler-bound")


class PerfLedger:
    """Per-process decomposition ledger.  Thread-safe; cheap enough to
    record every step."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._steps = 0
            self._sum = {"step": 0.0, "compute": 0.0, "exposed_comm": 0.0,
                         "host_input": 0.0, "stall": 0.0}
            self._last: Optional[Dict[str, float]] = None
            self._pending_input = 0.0
            self._drift_sum = 0.0
            self._drift_n = 0
            # model inputs (configure()); None = component unmodeled
            self._flops: Optional[float] = None
            self._comm_bytes: Optional[float] = None
            self._overlap_fraction = 0.0
            self._chip = "cpu"
            self._link = "loopback"
            self._zero: Optional[Dict[str, Any]] = None
            self._layout: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------ configure
    def configure(self, *, flops_per_step: Optional[float] = None,
                  comm_bytes_per_step: Optional[float] = None,
                  overlap_fraction: Optional[float] = None,
                  chip: Optional[str] = None,
                  link: Optional[str] = None,
                  zero_model: Optional[Dict[str, Any]] = None,
                  layout_model: Optional[Dict[str, Any]] = None) -> None:
        """Set the cost-model inputs the decomposition prices steps with.
        Unset components stay as they were; an unconfigured model
        attributes everything beyond measured input wait to ``stall``.

        ``zero_model`` describes the weight-update sharding workload —
        ``{"n_params", "world"}`` required, plus optional ``level`` (the
        active one), ``opt_slots``, ``k``, ``wire_format``, ``ef`` — and
        makes :meth:`report` carry the per-ZeRO-level what-if table
        (costmodel.zero_level_table; docs/zero.md).

        ``layout_model`` describes the 3D-layout workload the same way —
        ``{"n_params", "world"}`` required, plus the llama descriptor
        fields (``dim``/``n_layers``/``n_heads``/``n_kv_heads``/
        ``batch``/``seq``/``flops_per_step``; permissive defaults when
        absent) and solver options (``levels``/``wires``/
        ``overlap_depths``/``k``/``n_micro``/``ef``/``mem_cap_bytes``/
        ``active``) — and makes :meth:`report` carry the ranked layout
        candidate table (costmodel.solve_layout;
        docs/parallelism.md)."""
        from .costmodel import LINK_CLASSES
        for what, m in (("zero_model", zero_model),
                        ("layout_model", layout_model)):
            if m is not None:
                for req in ("n_params", "world"):
                    if req not in m:
                        raise ValueError(
                            f"{what} needs {req!r} (docs/zero.md, "
                            f"docs/parallelism.md); got {sorted(m)}")
        with self._lock:
            if zero_model is not None:
                self._zero = dict(zero_model)
            if layout_model is not None:
                self._layout = dict(layout_model)
            if flops_per_step is not None:
                self._flops = float(flops_per_step)
            if comm_bytes_per_step is not None:
                self._comm_bytes = float(comm_bytes_per_step)
            if overlap_fraction is not None:
                if not 0.0 <= overlap_fraction <= 1.0:
                    raise ValueError(f"overlap_fraction {overlap_fraction} "
                                     "outside [0, 1]")
                self._overlap_fraction = float(overlap_fraction)
            if chip is not None:
                self._chip = str(chip)
            if link is not None:
                if link not in LINK_CLASSES:
                    raise ValueError(
                        f"unknown link class {link!r}; valid: "
                        f"{', '.join(LINK_CLASSES)}")
                self._link = str(link)

    def zero_model(self) -> Optional[Dict[str, Any]]:
        """The configured weight-update sharding workload (or None) —
        the geometry the memory plane's attribution and reconciliation
        price (perf/memstats.py)."""
        with self._lock:
            return dict(self._zero) if self._zero else None

    def layout_model(self) -> Optional[Dict[str, Any]]:
        """The configured 3D-layout workload (or None) — what the report
        solves the candidate table from (docs/parallelism.md)."""
        with self._lock:
            return dict(self._layout) if self._layout else None

    def configure_from_overlap_gauges(self) -> bool:
        """Adopt the overlap plane's trace-time byte model (the
        ``hvd_overlap_*`` gauges, ops/overlap.py) as this ledger's comm
        leg: exposed bytes and overlapped fraction of the microbatch
        plane when it recorded anything.  True when gauges were live."""
        from ..utils import metrics as M
        exposed = M.OVERLAP_EXPOSED_BYTES.value(plane="microbatch")
        frac = M.OVERLAP_FRACTION.value(plane="microbatch")
        if exposed <= 0.0 and frac <= 0.0:
            return False
        # The gauge already reports EXPOSED bytes: feed them through with
        # overlap 0 so they are not discounted twice.
        self.configure(comm_bytes_per_step=exposed, overlap_fraction=0.0)
        return True

    # --------------------------------------------------------------- record
    def add_input_wait(self, seconds: float) -> None:
        """Accumulate host-side input wait since the last recorded step
        (fed by data/loader.prefetch; call directly from custom loops)."""
        if seconds > 0:
            with self._lock:
                self._pending_input += float(seconds)

    def record_step(self, step_time_s: float) -> Dict[str, float]:
        """Split one measured step and fold it into the ledger.  Returns
        the step's decomposition (components sum to ``step_time_s``
        exactly — the invariant tests/test_perf.py pins)."""
        from .costmodel import link_bandwidth, peak_flops
        dt = max(float(step_time_s), 0.0)
        with self._lock:
            host_input = min(self._pending_input, dt)
            self._pending_input = 0.0
            compute = (self._flops / peak_flops(self._chip)
                       if self._flops else 0.0)
            comm = ((self._comm_bytes * (1.0 - self._overlap_fraction)
                     / link_bandwidth(self._link))
                    if self._comm_bytes else 0.0)
            modeled = compute + comm
            avail = dt - host_input
            if dt > 0:
                # drift = what the model (plus measured input) prices the
                # step at, over what the wall clock measured.
                self._drift_sum += (modeled + host_input) / dt
                self._drift_n += 1
            if modeled > avail and modeled > 0:
                # Over-prediction: scale the modeled components into the
                # measured budget (the drift ratio above keeps the
                # overshoot observable) instead of letting the
                # components sum past the step.
                scale = max(avail, 0.0) / modeled
                compute *= scale
                comm *= scale
                stall = 0.0
            else:
                stall = avail - modeled
            row = {"step": dt, "compute": compute, "exposed_comm": comm,
                   "host_input": host_input, "stall": stall}
            for k, v in row.items():
                self._sum[k] += v
            self._steps += 1
            self._last = row
        self._update_metrics(row)
        return {f"{k}_s" if k != "step" else "step_time_s": v
                for k, v in row.items()}

    def timed_step(self):
        """``with ledger.timed_step(): <one train step>`` — measures the
        block's wall time and records it."""
        ledger = self

        class _Timer:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                if exc[0] is None:
                    ledger.record_step(time.perf_counter() - self._t0)
                return False

        return _Timer()

    def _update_metrics(self, row: Dict[str, float]) -> None:
        from ..utils import metrics as M
        M.PERF_STEPS.inc()
        M.PERF_STEP_TIME.observe(row["step"])
        for comp in ("compute", "exposed_comm", "host_input", "stall"):
            M.PERF_COMPONENT.set(row[comp], component=comp)
        drift = self.model_drift_ratio()
        if drift is not None:
            M.PERF_MODEL_DRIFT.set(drift)

    # --------------------------------------------------------------- report
    def model_drift_ratio(self) -> Optional[float]:
        """Mean modeled/measured ratio over recorded steps (1.0 = the
        model prices exactly what the wall clock measures)."""
        if not self._drift_n:
            return None
        return self._drift_sum / self._drift_n

    def report(self) -> Dict[str, Any]:
        """The per-rank perf report: measured decomposition means,
        predicted step from the configured model, deltas, and the local
        bottleneck verdict.  JSON-able; this exact payload is what the
        publisher PUTs to KV scope ``perf``."""
        from .costmodel import predicted_step_time, zero_level_table
        with self._lock:
            steps = self._steps
            sums = dict(self._sum)
            last = dict(self._last) if self._last else None
            flops, comm_bytes = self._flops, self._comm_bytes
            overlap, chip, link = (self._overlap_fraction, self._chip,
                                   self._link)
            drift = (self._drift_sum / self._drift_n
                     if self._drift_n else None)
            zero = dict(self._zero) if self._zero else None
            layout = dict(self._layout) if self._layout else None
        mean = {k: (v / steps if steps else 0.0) for k, v in sums.items()}
        decomposition = {
            "compute_s": mean["compute"],
            "exposed_comm_s": mean["exposed_comm"],
            "host_input_s": mean["host_input"],
            "stall_s": mean["stall"],
        }
        fractions = {k: (v / mean["step"] if mean["step"] else 0.0)
                     for k, v in decomposition.items()}
        predicted = predicted_step_time(
            flops or 0.0, comm_bytes or 0.0, chip=chip, link=link,
            overlap_fraction=overlap,
            input_seconds=mean["host_input"]) if steps else None
        report: Dict[str, Any] = {
            "version": REPORT_VERSION,
            "time": time.time(),
            "steps": steps,
            "step_time_s": {"mean": mean["step"],
                            "last": last["step"] if last else None},
            "decomposition": decomposition,
            "fractions": fractions,
            "verdict": local_verdict(fractions) if steps else None,
            "model": {"flops_per_step": flops,
                      "comm_bytes_per_step": comm_bytes,
                      "overlap_fraction": overlap,
                      "chip": chip, "link": link},
            "predicted": predicted,
            "model_drift_ratio": drift,
        }
        if predicted and mean["step"] > 0:
            report["predicted_vs_measured"] = {
                "step_delta_s": predicted["step_s"] - mean["step"],
                "step_ratio": predicted["step_s"] / mean["step"],
            }
        if zero is not None:
            # The "what would ZeRO-N cost me at my topology" table
            # (docs/zero.md): per-level memory + wire bytes + predicted
            # exposed comm on this rank's link class, beside the
            # MEASURED decomposition above so the active level's
            # prediction is confronted with the wall clock.
            report["zero"] = {
                "active_level": zero.get("level"),
                "model": zero,
                "levels": zero_level_table(
                    zero["n_params"], zero["world"],
                    opt_slots=int(zero.get("opt_slots", 2)),
                    k=int(zero.get("k", 1)),
                    wire_format=str(zero.get("wire_format", "none")),
                    ef=bool(zero.get("ef", False)),
                    chip=chip, link=link, flops_per_step=flops),
            }
        ops = native_op_stats()
        if ops:
            report["native_ops"] = ops
        # Memory plane (perf/memstats.py; docs/memory.md): the measured
        # residency beside the zero_memory_bytes prediction — absent
        # until the sampler has run (HOROVOD_MEM off, or no snapshot
        # yet), so old readers see the exact pre-memory payload.
        try:
            from . import memstats
            mem = memstats.report_section()
            if mem is not None:
                report["memory"] = mem
        except Exception:
            pass  # the memory leg must never break the perf report
        if layout is not None:
            # The ranked "which (dp, tp, pp) should this topology run"
            # table (docs/parallelism.md): candidates from
            # costmodel.solve_layout under the measured memory cap
            # (memory.measured.headroom_bytes is the default cap — the
            # PR-16 ledger's answer to 'how much state still fits'),
            # beside the MEASURED decomposition so the chosen layout's
            # predicted step is confronted with the wall clock exactly
            # like the ZeRO table above.
            try:
                report["layout"] = self._layout_section(
                    layout, report, chip, link, flops, mean["step"])
            except Exception:
                pass  # the layout leg must never break the perf report
        return report

    @staticmethod
    def _layout_section(layout: Dict[str, Any], report: Dict[str, Any],
                        chip: str, link: str, flops: Optional[float],
                        mean_step: float) -> Dict[str, Any]:
        from .costmodel import solve_layout
        world = int(layout["world"])
        cap = layout.get("mem_cap_bytes")
        if cap is None:
            cap = (report.get("memory") or {}).get(
                "measured", {}).get("headroom_bytes")
        n_heads = int(layout.get("n_heads", world))
        model = {
            "n_params": layout["n_params"],
            "dim": int(layout.get("dim", 0)),
            "n_layers": int(layout.get("n_layers", world)),
            "n_heads": n_heads,
            "n_kv_heads": int(layout.get("n_kv_heads", n_heads)),
            "batch": int(layout.get("batch", world)),
            "seq": int(layout.get("seq", 1)),
            "itemsize": float(layout.get("itemsize", 4.0)),
            "flops_per_step": float(layout.get("flops_per_step",
                                               flops or 0.0)),
        }
        sol = solve_layout(
            model, world, mem_cap_bytes=cap,
            levels=tuple(layout.get("levels", (1, 2, 3))),
            wires=tuple(layout.get("wires", ("none",))),
            overlap_depths=tuple(layout.get("overlap_depths", (0,))),
            k=int(layout.get("k", 1)),
            n_micro=int(layout.get("n_micro", 4)),
            chip=chip, link=link, ef=bool(layout.get("ef", False)))
        # The ACTIVE row: what this rank actually trains with (bench /
        # HOROVOD_LAYOUT set it) — may rank below the unconstrained
        # winner; its prediction is the one drift is judged against.
        active_req = layout.get("active")
        active = None
        if isinstance(active_req, dict):
            for row in sol["candidates"]:
                if all(row["layout"].get(a) == active_req.get(a)
                       for a in ("dp", "tp", "pp")) and \
                   (active_req.get("zero_level") is None or
                        row["zero_level"] == active_req["zero_level"]):
                    active = row
                    break
        judged = active or sol["chosen"]
        section: Dict[str, Any] = {
            "model": model,
            "world": world,
            "mem_cap_bytes": cap,
            "n_candidates": sol["n_candidates"],
            "chosen": sol["chosen"],
            "active": active,
            "candidates": sol["candidates"][:16],
            "candidates_truncated": sol["n_candidates"] > 16,
        }
        if mean_step > 0:
            section["predicted_vs_measured"] = {
                "step_delta_s": judged["step_s"] - mean_step,
                "step_ratio": (judged["step_s"] / mean_step
                               if mean_step else None),
            }
        from ..utils import metrics as M
        M.LAYOUT_CANDIDATES.set(sol["n_candidates"])
        M.LAYOUT_CHOSEN_RANK.set(judged["rank"])
        M.LAYOUT_PREDICTED_STEP.set(judged["step_s"])
        return section


def local_verdict(fractions: Dict[str, float]) -> str:
    """One rank's bottleneck classification: the dominant component of
    the mean decomposition (straggler-bound is a FLEET verdict — one
    rank cannot see that it is the slow one; merge_perf_reports adds
    it)."""
    order = (("exposed_comm_s", "comm-bound"),
             ("host_input_s", "input-bound"),
             ("stall_s", "stall-bound"),
             ("compute_s", "compute-bound"))
    best = max(order, key=lambda kv: fractions.get(kv[0], 0.0))
    return best[1]


# ------------------------------------------------------------- native leg
def native_op_stats(core=None, top: int = 10) -> List[Dict[str, Any]]:
    """Top per-op-name enqueue→done aggregates from the native core
    (``hvd_core_op_stats``, csrc/c_api.cc), largest total latency first —
    the controller path's share of the attribution.  Empty when no core
    is up (pure SPMD runs negotiate nothing)."""
    if core is None:
        from .. import runtime as _rt
        if not _rt.is_initialized():
            return []
        core = _rt.get().core
    if core is None or not getattr(core, "_h", None):
        return []
    try:
        stats = core.op_stats()
    except Exception:
        return []  # a closing core must not break the report
    rows = sorted(stats.items(), key=lambda kv: -kv[1]["sum_us"])[:top]
    return [{"name": name,
             "count": s["count"],
             "bytes": s["bytes"],
             "mean_us": (s["sum_us"] / s["count"]) if s["count"] else 0.0,
             "max_us": s["max_us"]}
            for name, s in rows]


def import_op_stats(core) -> None:
    """Fold the native per-op aggregates into the ``hvd_perf_native_op_*``
    registry families (called from Runtime.metrics_snapshot, beside
    import_core_metrics).  Cumulative native values import with
    set_total, never re-counted."""
    from ..utils import metrics as M
    for row in native_op_stats(core, top=32):
        M.PERF_NATIVE_OP_US.set_total(row["count"] * row["mean_us"],
                                      name=row["name"])
        M.PERF_NATIVE_OP_BYTES.set_total(row["bytes"], name=row["name"])


# ---------------------------------------------------------- module global
GLOBAL = PerfLedger()


def configure(**kw) -> None:
    GLOBAL.configure(**kw)


def add_input_wait(seconds: float) -> None:
    GLOBAL.add_input_wait(seconds)


def record_step(step_time_s: float) -> Dict[str, float]:
    return GLOBAL.record_step(step_time_s)


def timed_step():
    return GLOBAL.timed_step()


def report() -> Dict[str, Any]:
    return GLOBAL.report()


def reset() -> None:
    GLOBAL.reset()


# -------------------------------------------------------------- publisher
class PerfPublisher:
    """Background thread PUT-ing this rank's perf report to the
    rendezvous KV (scope ``perf``, key ``rank.N``) so ``GET /perf``
    serves the merged fleet view.  MetricsPublisher's shape: plain
    urllib, bounded retry, final publish on close()."""

    SCOPE = PERF_SCOPE

    def __init__(self, addr: str, port: int, rank: int,
                 report_fn: Callable[[], Dict[str, Any]] = report,
                 interval: float = 5.0):
        self.addr = addr
        self.port = int(port)
        self.rank = int(rank)
        self.interval = max(0.1, float(interval))
        self._report_fn = report_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.addr and self.port:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def publish_now(self, retries: int = 3) -> bool:
        if not (self.addr and self.port):
            return False
        try:
            rep = self._report_fn()
            rep["rank"] = self.rank
            body = json.dumps(rep).encode()
            # Sharded KV (docs/control-plane.md): the perf scope may
            # live on a shard server; resolve per publish.
            from ..runner.http_client import resolve_kv_addr
            addr, port, _ = resolve_kv_addr(self.addr, self.port,
                                            self.SCOPE)
            url = (f"http://{addr}:{port}/{self.SCOPE}/"
                   f"rank.{self.rank}")
            delay = 0.1
            for attempt in range(retries + 1):
                try:
                    req = urllib.request.Request(url, data=body,
                                                 method="PUT")
                    with urllib.request.urlopen(req, timeout=5):
                        pass
                    return True
                except Exception:
                    if attempt >= retries:
                        raise
                    time.sleep(delay)
                    delay = min(delay * 2, 1.0)
            return True
        except Exception:
            return False  # attribution must never take the job down

    def _loop(self) -> None:
        self.publish_now()
        while not self._stop.wait(self.interval):
            self.publish_now()

    def close(self) -> None:
        self._stop.set()
        self.publish_now()


# ------------------------------------------------------------- fleet merge
def merge_perf_reports(stored: Dict[str, bytes],
                       straggler_ratio: float = 1.5) -> Dict[str, Any]:
    """The ``GET /perf`` payload: every rank's published report plus the
    FLEET verdict.  Straggler-bound (one rank's mean step time beyond
    ``straggler_ratio`` × the peer median) outranks the component
    verdicts — a fleet paced by one slow rank shows comm-bound
    everywhere else, and naming the rank IS the root cause."""
    ranks: Dict[str, Any] = {}
    for key in sorted(stored):
        try:
            rep = json.loads(stored[key])
        except (ValueError, TypeError):
            continue  # a torn PUT must not 500 the whole view
        rank = str(rep.get("rank", key.rsplit(".", 1)[-1]))
        ranks[rank] = rep
    fleet: Dict[str, Any] = {"verdict": None, "ranks": len(ranks)}
    rows = [(r, rep["step_time_s"]["mean"]) for r, rep in ranks.items()
            if rep.get("steps") and rep.get("step_time_s", {}).get("mean")]
    if rows:
        fleet["step_time_by_rank"] = {r: t for r, t in rows}
        slowest_rank, slowest = max(rows, key=lambda rt: rt[1])
        peers = sorted(t for r, t in rows if r != slowest_rank)
        if peers:
            peer_median = peers[len(peers) // 2]
            if peer_median > 0 and slowest > straggler_ratio * peer_median:
                fleet["verdict"] = "straggler-bound"
                fleet["straggler"] = {"rank": slowest_rank,
                                      "step_time_s": slowest,
                                      "peer_median_s": peer_median}
        if fleet["verdict"] is None:
            # Componentwise fleet mean -> dominant component verdict.
            agg = {"compute_s": 0.0, "exposed_comm_s": 0.0,
                   "host_input_s": 0.0, "stall_s": 0.0}
            n = 0
            for _, rep in ranks.items():
                d = rep.get("decomposition")
                if d:
                    n += 1
                    for k in agg:
                        agg[k] += d.get(k, 0.0)
            if n:
                total = sum(agg.values())
                fleet["verdict"] = local_verdict(
                    {k: (v / total if total else 0.0)
                     for k, v in agg.items()})
                fleet["decomposition"] = {k: v / n for k, v in agg.items()}
    # Fleet memory rollup (docs/memory.md): worst watermark + smallest
    # headroom across ranks — the rank closest to the cap paces when the
    # fleet OOMs, the same way the slowest rank paces the step.
    mem_rows = [(r, rep["memory"]) for r, rep in ranks.items()
                if isinstance(rep.get("memory"), dict)]
    if mem_rows:
        worst_rank, worst = max(
            mem_rows,
            key=lambda rm: rm[1].get("measured", {}).get("watermark", 0.0)
            or 0.0)
        fleet["memory"] = {
            "ranks": len(mem_rows),
            "bytes_in_use_total": sum(
                m.get("measured", {}).get("bytes_in_use", 0) or 0
                for _, m in mem_rows),
            "worst_watermark": {
                "rank": worst_rank,
                "watermark": worst.get("measured", {}).get("watermark"),
                "headroom_bytes": worst.get("measured",
                                            {}).get("headroom_bytes"),
            },
            "drift_ratio_by_rank": {
                r: m.get("model_drift_ratio") for r, m in mem_rows},
        }
    return {"version": REPORT_VERSION, "time": time.time(),
            "fleet": fleet, "ranks": ranks}
