"""Performance-attribution plane: why is the step slow, what would make
it faster (docs/profiling.md).

Four legs over the observability stack the earlier planes built:

  * ``costmodel`` — analytical FLOP/byte accounting and the roofline
    predicted step time (the single source of bench.py's MFU constants);
  * ``ledger`` — the measured step-time decomposition
    (compute / exposed-comm / host-input / stall, summing exactly to the
    measured step), ``hvd.perf_report()``, the ``hvd_perf_*`` metric
    families and the KV publisher behind ``GET /perf``;
  * the native leg — per-op-name enqueue→done aggregates from csrc via
    ``hvd_core_op_stats`` (``ledger.native_op_stats``);
  * ``gate`` — the median±MAD bench-artifact regression gate behind
    ``scripts/perf_gate.py``.

Training loops opt in with two calls:

    hvd.perf.configure(flops_per_step=..., comm_bytes_per_step=...)
    with hvd.perf.timed_step():
        params, opt_state, loss = train_step(...)
    print(hvd.perf_report()["verdict"])
"""

from __future__ import annotations

from .ledger import (GLOBAL, PerfLedger, PerfPublisher, add_input_wait,
                     configure, merge_perf_reports, native_op_stats,
                     record_step, report, reset, timed_step)
from . import memstats  # noqa: F401  (hvd.perf.memstats rides this)
from .memstats import MemSampler, validate_mem_knobs

# perf_report is the hvd-level spelling (hvd.perf_report()); report the
# module-level one (hvd.perf.report()).
perf_report = report


def configure_from_overlap_gauges() -> bool:
    return GLOBAL.configure_from_overlap_gauges()


def validate_perf_knobs(knobs) -> None:
    """Init-time validation of the HOROVOD_PERF_* knob surface (the
    contract every plane follows: an invalid knob fails at hvd.init(),
    not as a late runtime surprise).  Consumed by runtime.Runtime."""
    from .costmodel import LINK_CLASSES
    link = str(knobs["HOROVOD_PERF_LINK"])
    if link != "auto" and link not in LINK_CLASSES:
        raise ValueError(
            f"HOROVOD_PERF_LINK={link!r} invalid; use 'auto' or one of "
            f"{', '.join(LINK_CLASSES)} (docs/profiling.md)")
    if knobs["HOROVOD_PERF_INTERVAL"] <= 0:
        raise ValueError(
            f"HOROVOD_PERF_INTERVAL={knobs['HOROVOD_PERF_INTERVAL']} "
            "invalid; the perf-report publish period must be positive "
            "seconds (docs/profiling.md)")


def resolve_link(knobs, mesh=None) -> str:
    """The link class the roofline prices comm with: the knob when
    explicit, else by topology — a dcn.* mesh axis means the slow fabric
    bounds the sync, a real TPU mesh means ICI, a CPU-virtual mesh means
    loopback."""
    link = str(knobs["HOROVOD_PERF_LINK"])
    if link != "auto":
        return link
    if mesh is not None:
        try:
            if any(str(a).startswith("dcn.") for a in mesh.axis_names):
                return "dcn"
            devs = mesh.devices.flatten()
            if len(devs) and devs[0].platform != "cpu":
                return "ici"
        except Exception:
            pass
    return "loopback"


__all__ = [
    "GLOBAL", "MemSampler", "PerfLedger", "PerfPublisher",
    "add_input_wait", "configure", "configure_from_overlap_gauges",
    "memstats", "merge_perf_reports", "native_op_stats", "perf_report",
    "record_step", "report", "reset", "resolve_link", "timed_step",
    "validate_mem_knobs", "validate_perf_knobs",
]
