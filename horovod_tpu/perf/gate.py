"""Perf regression gate: median±MAD comparison of bench artifacts
against a committed baseline ledger (docs/profiling.md#regression-gate).

The bench trajectory (BENCH_r*.json, sweep_results.jsonl) has so far been
read by humans; this module turns it into a self-tracking gate: every
bench JSON artifact is keyed by its normalized metric + unit, the
baseline ledger stores the last N values per key, and a new artifact
fails the gate when its value sits outside the baseline's median by more
than ``mad_k`` scaled MADs AND more than ``min_rel_delta`` relative —
both conditions, so a noisy baseline (large MAD) tolerates jitter while
a tight baseline still doesn't fire on sub-percent drift.  A genuine 2×
regression trips either way; an unmodified re-run passes (the acceptance
experiment ``scripts/perf_gate.py --smoke`` runs exactly that pair).

Stdlib-only at module level so ``scripts/perf_gate.py`` loads this file
standalone by path (the bench-supervisor/probe.py pattern) — the gate
must run without jax installed in the CI step that consumes it.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

BASELINE_SCHEMA = "hvd-perf-baseline-v1"
MAX_BASELINE_VALUES = 20  # rolling window per key

# 1.4826 rescales the MAD to the standard deviation of a normal
# distribution — the conventional robust sigma estimate.
MAD_SIGMA = 1.4826

# Units where a SMALLER value is better; everything else is
# higher-is-better (tokens/sec, images/sec, GB/s, efficiencies,
# fractions).  Artifact rows may override via "higher_is_better".
LOWER_IS_BETTER_UNITS = ("seconds", "step_time", "bytes", "ratio",
                        "error")


def metric_key(artifact: Dict[str, Any]) -> str:
    """Stable identity of a bench row across runs: the metric string
    with the run-specific parenthetical detail (loss values, chip name,
    per-size rates) stripped, plus the unit."""
    metric = str(artifact.get("metric", ""))
    metric = re.sub(r"\s*\(.*", "", metric).strip()
    metric = re.sub(r"\s+", " ", metric)
    return f"{metric} [{artifact.get('unit', '?')}]"


def higher_is_better(artifact: Dict[str, Any]) -> bool:
    if "higher_is_better" in artifact:
        return bool(artifact["higher_is_better"])
    unit = str(artifact.get("unit", "")).lower()
    return not any(tok in unit for tok in LOWER_IS_BETTER_UNITS)


def median_mad(values: List[float]) -> Tuple[float, float]:
    """(median, MAD) — the robust location/scale pair the gate judges
    with; MAD of a singleton is 0 (the relative floor then carries the
    decision alone)."""
    if not values:
        raise ValueError("median_mad of no values")
    vs = sorted(float(v) for v in values)
    n = len(vs)
    med = vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1] + vs[n // 2])
    devs = sorted(abs(v - med) for v in vs)
    mad = devs[n // 2] if n % 2 else 0.5 * (devs[n // 2 - 1]
                                            + devs[n // 2])
    return med, mad


def compare(baseline_values: List[float], current_values: List[float], *,
            higher_better: bool = True, mad_k: float = 4.0,
            min_rel_delta: float = 0.10) -> Dict[str, Any]:
    """One key's verdict: ``regression`` when the current median moved
    in the WORSE direction past both the ``mad_k``-scaled-MAD band and
    the ``min_rel_delta`` relative floor; ``improved`` symmetric in the
    better direction (informational — improvements never fail);
    ``pass`` otherwise."""
    base_med, base_mad = median_mad(baseline_values)
    cur_med, _ = median_mad(current_values)
    band = mad_k * MAD_SIGMA * base_mad
    floor = min_rel_delta * abs(base_med)
    threshold = max(band, floor)
    delta = cur_med - base_med
    worse = -delta if higher_better else delta
    status = "pass"
    if worse > threshold:
        status = "regression"
    elif -worse > threshold:
        status = "improved"
    return {"status": status,
            "baseline_median": base_med, "baseline_mad": base_mad,
            "current_median": cur_med, "delta": delta,
            "threshold": threshold,
            "ratio": (cur_med / base_med) if base_med else None,
            "n_baseline": len(baseline_values),
            "n_current": len(current_values)}


# ------------------------------------------------------------ ledger file
def empty_baseline() -> Dict[str, Any]:
    return {"schema": BASELINE_SCHEMA, "entries": {}}


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != BASELINE_SCHEMA:
        raise ValueError(f"{path}: unknown baseline schema "
                         f"{doc.get('schema')!r} (want {BASELINE_SCHEMA})")
    return doc


def save_baseline(path: str, doc: Dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def load_artifacts(paths: List[str]) -> List[Dict[str, Any]]:
    """Bench artifacts: each file holds one JSON object (bench.py's one
    printed line) or JSONL (sweep_results.jsonl rows).  A row may carry
    ``sub_rows`` — additional gate-able rows riding the one printed line
    (the bench supervisor forwards only the last stdout line, so
    multi-metric modes like ``--serve`` nest their per-leg rows)."""
    rows: List[Dict[str, Any]] = []

    def add(row: Dict[str, Any]) -> None:
        rows.append(row)
        for sub in row.get("sub_rows") or ():
            if isinstance(sub, dict):
                rows.append(sub)

    for path in paths:
        with open(path) as f:
            text = f.read().strip()
        if not text:
            continue
        try:
            add(json.loads(text))
            continue
        except ValueError:
            pass
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                add(json.loads(line))
    return rows


def gate_value(artifact: Dict[str, Any]) -> Optional[float]:
    """The number the gate judges for one artifact row.  BENCH_INVALID
    rows gate as None (an invalid bench is a separate failure, not a
    perf number)."""
    if "BENCH_INVALID" in str(artifact.get("metric", "")):
        return None
    v = artifact.get("value")
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def update_baseline(doc: Dict[str, Any],
                    artifacts: List[Dict[str, Any]]) -> List[str]:
    """Fold artifact values into the rolling per-key windows; returns
    the keys updated."""
    touched = []
    for art in artifacts:
        v = gate_value(art)
        if v is None:
            continue
        key = metric_key(art)
        entry = doc["entries"].setdefault(
            key, {"unit": art.get("unit"),
                  "higher_is_better": higher_is_better(art),
                  "values": [], "label": art.get("label", "")})
        entry["values"] = (entry["values"] + [v])[-MAX_BASELINE_VALUES:]
        touched.append(key)
    return touched


def check_artifacts(doc: Dict[str, Any],
                    artifacts: List[Dict[str, Any]], *,
                    mad_k: float = 4.0,
                    min_rel_delta: float = 0.10) -> Dict[str, Any]:
    """Gate a set of artifacts against a baseline ledger.  Keys absent
    from the baseline report ``no-baseline`` (a NEW bench mode must not
    fail the gate before it has history — run ``update`` to adopt it).
    Overall ``failed`` is true iff any key regressed."""
    by_key: Dict[str, List[float]] = {}
    for art in artifacts:
        v = gate_value(art)
        if v is not None:
            by_key.setdefault(metric_key(art), []).append(v)
    results: Dict[str, Any] = {}
    failed = False
    for key, values in sorted(by_key.items()):
        entry = doc["entries"].get(key)
        if not entry or not entry.get("values"):
            results[key] = {"status": "no-baseline",
                            "current_median": median_mad(values)[0]}
            continue
        res = compare(entry["values"], values,
                      higher_better=bool(entry.get("higher_is_better",
                                                   True)),
                      mad_k=mad_k, min_rel_delta=min_rel_delta)
        results[key] = res
        failed = failed or res["status"] == "regression"
    return {"failed": failed, "results": results}
