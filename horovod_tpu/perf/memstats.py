"""Memory plane: the measured fleet memory ledger (docs/memory.md).

Every memory number in the repo before this module was *predicted*
(costmodel.zero_memory_bytes, bench --zero's analytical peak_bytes).
This module closes the predict-vs-measure loop the way PR 14 did for
comm bytes — ledger-proven:

  * :class:`MemSampler` — the per-rank measured ledger.  Sources, in
    preference order (docs/memory.md#sources):
      1. ``device.memory_stats()`` — ``bytes_in_use`` /
         ``peak_bytes_in_use`` / ``bytes_limit`` where the backend
         provides them (TPU/GPU);
      2. CPU-virtual fallback — the aggregate live-array size
         (``jax.live_arrays()``; device leg) + ``/proc/self/status``
         VmRSS (host leg), labeled ``source: live_buffers`` so no
         reader mistakes it for a real device cap.
    Bytes are attributed to planes from geometry the repo already
    knows: params/grads/opt-state/EF-residual from the ZeRO level +
    bucket plan (the ledger's configured zero model), the serve KV pool
    from :class:`~horovod_tpu.serve.engine.BlockAllocator` occupancy
    (``blocks x block_bytes``, used/free/shared split), the
    fusion/overlap working set from threshold x depth, and the native
    core's own footprint from the versioned ``hvd_core_mem`` C API
    (TraceRing, MetricsWindowRing, response cache, peak RSS — stamped
    by the cycle loop beside ``hvd_core_metrics``).
  * **reconciliation** — ``hvd_mem_model_drift_ratio`` = measured
    bytes-in-use over the ``zero_memory_bytes`` predicted total; the
    section :func:`report_section` builds rides ``hvd.perf_report()``
    and ``GET /perf`` and is rendered by ``hvdrun doctor --perf``.  The
    ``headroom_bytes`` it carries is the cap-headroom input ROADMAP
    item 2's layout solver consumes.
  * **OOM-proximity sentinel** — crossing
    ``HOROVOD_MEM_HIGH_WATERMARK`` fires ONCE per transition: the
    ``hvd_mem_pressure_events_total`` counter (the committed
    ``mem-pressure-high`` rule's context), a timeline instant, and an
    explicit native flight dump reason ``mem`` (path suffix ``.mem``) —
    the black box taken *before* the kernel's SIGKILL.

Knobs: ``HOROVOD_MEM`` (kill switch), ``HOROVOD_MEM_INTERVAL``
(sample rate limit), ``HOROVOD_MEM_HIGH_WATERMARK`` (the sentinel
threshold, also stamped into heartbeats for the postmortem ``oom``
classifier).  All init-validated (:func:`validate_mem_knobs`).

Stdlib-only at module level (jax and the metrics registry import
lazily), the utils/metrics.py discipline: sampling runs inside the
metrics publisher's snapshot path and must never take the job down.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, Optional

# Plane keys of the geometry attribution, in render order.  kv_spill
# is HOST bytes (the serve tier's spilled cold KV blocks; it shows up
# in host RSS, not the device cap) — kept in the same ledger so the
# spill tier's cost is accounted where operators already look.
PLANES = ("params", "grads", "opt_state", "ef_residual", "kv_pool",
          "kv_spill", "fusion_overlap", "native_core")


def _knob(name: str):
    from ..common.knobs import current
    return current(name)


def enabled() -> bool:
    return bool(_knob("HOROVOD_MEM"))


def validate_mem_knobs(knobs) -> None:
    """Init-time validation of the HOROVOD_MEM_* knob surface
    (common/knobs.py contract: a bad value fails hvd.init, never the
    sampler mid-run).  Consumed by runtime.Runtime."""
    interval = float(knobs["HOROVOD_MEM_INTERVAL"])
    if interval < 0:
        raise ValueError(
            f"HOROVOD_MEM_INTERVAL={interval} invalid; the memory "
            "sampler rate limit must be >= 0 seconds (docs/memory.md)")
    wm = float(knobs["HOROVOD_MEM_HIGH_WATERMARK"])
    if not 0.0 < wm <= 1.0:
        raise ValueError(
            f"HOROVOD_MEM_HIGH_WATERMARK={wm} invalid; the OOM-"
            "proximity threshold is a fraction of the device cap in "
            "(0, 1] (docs/memory.md#oom)")


# ------------------------------------------------------------ measurement
def read_host_rss_bytes() -> int:
    """Host resident set from /proc/self/status VmRSS (kB lines); 0
    where procfs is unavailable — report what you measure, never guess.
    """
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return 0


def measure_device(device: Any = None) -> Dict[str, Any]:
    """One device-side measurement: ``{"source", "bytes_in_use",
    "peak_bytes_in_use", "cap_bytes"}``.  ``memory_stats()`` returning
    None (the CPU backend) or raising falls back to the aggregate
    ``jax.live_arrays()`` size without raising — the backend-matrix
    contract docs/memory.md#sources documents."""
    stats = None
    try:
        import jax
        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats:
        return {
            "source": "device",
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(
                stats.get("peak_bytes_in_use",
                          stats.get("bytes_in_use", 0))),
            "cap_bytes": int(stats.get("bytes_limit", 0)),
        }
    live = 0
    try:
        import jax
        for buf in jax.live_arrays():
            try:
                live += int(buf.nbytes)
            except Exception:
                continue
    except Exception:
        live = 0
    return {"source": "live_buffers", "bytes_in_use": int(live),
            "peak_bytes_in_use": None, "cap_bytes": 0}


def native_mem(core: Any = None) -> Optional[Dict[str, int]]:
    """The csrc leg: ``hvd_core_mem`` parsed (common/basics.py
    ``mem()``), or None when no core is up or the loaded library
    predates the memory plane — graceful absence, never an error."""
    if core is None:
        try:
            from .. import runtime as _rt
            if _rt.is_initialized():
                core = _rt.get().core
        except Exception:
            core = None
    if core is None or not getattr(core, "_h", None):
        return None
    try:
        return core.mem()
    except Exception:
        return None  # pre-memory-plane .so or a closing core


# ------------------------------------------------------- kv-pool provider
_kv_pool_fn: Optional[Callable[[], Dict[str, Any]]] = None


def set_kv_pool_provider(fn: Optional[Callable[[], Dict[str, Any]]]
                         ) -> None:
    """Register the serve engine's BlockAllocator occupancy source
    (serve/engine.py registers ``allocator.occupancy`` at scheduler
    construction; None unregisters on shutdown)."""
    global _kv_pool_fn
    _kv_pool_fn = fn


def kv_pool_stats() -> Optional[Dict[str, Any]]:
    fn = _kv_pool_fn
    if fn is None:
        return None
    try:
        return fn()
    except Exception:
        return None  # a closing engine must not break the sampler


# ---------------------------------------------------------------- sampler
class MemSampler:
    """Per-rank measured memory ledger + OOM-proximity sentinel.

    ``sample()`` is called from Runtime.metrics_snapshot (the
    MetricsPublisher cadence), rate-limited by HOROVOD_MEM_INTERVAL;
    the latest sample is what the heartbeat stamps and
    :func:`report_section` reconciles."""

    def __init__(self):
        self.lock = threading.Lock()
        self.last: Optional[Dict[str, Any]] = None
        self.peak_seen = 0        # running max under the CPU fallback
        self.pressure_above = False   # fire-once transition latch
        self.pressure_events = 0
        self.dump_paths: list = []    # test-visible: flight dumps written
        self._last_t: Optional[float] = None

    # ------------------------------------------------------------ geometry
    def _predicted(self) -> Optional[Dict[str, int]]:
        """zero_memory_bytes for the ledger's configured zero model —
        the predicted side of the reconciliation (None unconfigured)."""
        from .ledger import GLOBAL
        zero = GLOBAL.zero_model()
        if not zero:
            return None
        from .costmodel import zero_memory_bytes
        try:
            return zero_memory_bytes(
                int(zero.get("level", 1) or 0), zero["n_params"],
                zero["world"], opt_slots=int(zero.get("opt_slots", 2)),
                ef=bool(zero.get("ef", False)))
        except (ValueError, KeyError):
            return None

    def _planes(self, core: Any) -> Dict[str, int]:
        """Geometry-attributed bytes by plane (docs/memory.md
        #attribution): the training-state planes from the zero model,
        the serve KV pool from BlockAllocator occupancy, the fusion/
        overlap working set from threshold x depth, the native core
        from hvd_core_mem."""
        planes: Dict[str, int] = {}
        pred = self._predicted()
        if pred:
            for key in ("params", "grads", "opt_state", "ef_residual"):
                planes[key] = int(pred[f"{key}_bytes"])
        kv = kv_pool_stats()
        if kv:
            planes["kv_pool"] = int(kv.get("pool_bytes", 0))
            sp = kv.get("spill")
            if isinstance(sp, dict):
                planes["kv_spill"] = int(sp.get("held_bytes_est", 0))
        try:
            threshold = int(_knob("HOROVOD_FUSION_THRESHOLD"))
            depth = max(1, int(_knob("HOROVOD_OVERLAP_DEPTH")))
            planes["fusion_overlap"] = threshold * depth
        except Exception:
            pass
        nm = native_mem(core)
        if nm:
            planes["native_core"] = int(
                nm.get("trace_ring_bytes", 0)
                + nm.get("window_ring_bytes", 0)
                + nm.get("response_cache_bytes", 0))
        return planes

    # -------------------------------------------------------------- sample
    def sample(self, core: Any = None, device: Any = None,
               now: Optional[float] = None,
               cap_bytes: Optional[int] = None,
               force: bool = False) -> Optional[Dict[str, Any]]:
        """Take (or rate-limit-skip) one measurement: update the
        hvd_mem_* families, the transition latch, and ``self.last``.
        ``cap_bytes`` overrides the backend cap (tests; the CPU
        fallback reports none).  Returns the sample row, or None when
        disabled/rate-limited."""
        if not enabled():
            return None
        now = time.time() if now is None else float(now)
        interval = float(_knob("HOROVOD_MEM_INTERVAL"))
        with self.lock:
            if (not force and interval > 0 and self._last_t is not None
                    and now - self._last_t < interval):
                return None
            self._last_t = now
        measured = measure_device(device)
        host_rss = read_host_rss_bytes()
        if cap_bytes is not None:
            measured["cap_bytes"] = int(cap_bytes)
        with self.lock:
            self.peak_seen = max(self.peak_seen, measured["bytes_in_use"])
            if measured["peak_bytes_in_use"] is None:
                measured["peak_bytes_in_use"] = self.peak_seen
        cap = int(measured["cap_bytes"] or 0)
        watermark = (measured["bytes_in_use"] / cap) if cap > 0 else 0.0
        planes = self._planes(core)
        pred = self._predicted()
        drift = None
        if pred and pred.get("total_bytes", 0) > 0 \
                and measured["bytes_in_use"] > 0:
            drift = measured["bytes_in_use"] / pred["total_bytes"]
        nm = native_mem(core)
        kv = kv_pool_stats()
        row: Dict[str, Any] = {
            "time": now,
            "source": measured["source"],
            "bytes_in_use": measured["bytes_in_use"],
            "peak_bytes_in_use": measured["peak_bytes_in_use"],
            "cap_bytes": cap,
            "host_rss_bytes": host_rss,
            "watermark": watermark,
            "headroom_bytes": (cap - measured["bytes_in_use"]) if cap > 0
            else None,
            "planes": planes,
            "predicted": pred,
            "model_drift_ratio": drift,
            "native": nm,
            "kv_pool": kv,
        }
        self._update_gauges(row)
        self._check_pressure(row, core=core)
        with self.lock:
            self.last = row
        return row

    def _update_gauges(self, row: Dict[str, Any]) -> None:
        try:
            from ..utils import metrics as M
        except ImportError:
            return
        M.MEM_BYTES_IN_USE.set(row["bytes_in_use"])
        M.MEM_PEAK_BYTES.set(row["peak_bytes_in_use"] or 0)
        M.MEM_CAP_BYTES.set(row["cap_bytes"])
        M.MEM_HOST_RSS.set(row["host_rss_bytes"])
        M.MEM_WATERMARK.set(row["watermark"])
        if row["model_drift_ratio"] is not None and \
                math.isfinite(row["model_drift_ratio"]):
            M.MEM_MODEL_DRIFT.set(row["model_drift_ratio"])
        for plane, b in row["planes"].items():
            M.MEM_PLANE_BYTES.set(b, plane=plane)
        nm = row.get("native")
        if nm:
            for key, kind in (("rss_bytes", "rss"),
                              ("peak_rss_bytes", "peak_rss"),
                              ("trace_ring_bytes", "trace_ring"),
                              ("window_ring_bytes", "window_ring"),
                              ("response_cache_bytes", "response_cache")):
                if key in nm:
                    M.MEM_NATIVE_BYTES.set(nm[key], kind=kind)
        kv = row.get("kv_pool")
        if kv:
            used = int(kv.get("used_blocks", 0))
            free = int(kv.get("free_blocks", 0))
            M.MEM_KV_BLOCKS_USED.set(used)
            M.MEM_KV_BLOCKS_FREE.set(free)
            M.MEM_KV_BLOCKS_SHARED.set(kv.get("shared_blocks", 0))
            if used + free > 0:
                M.MEM_KV_UTIL.set(used / (used + free))

    # ------------------------------------------------------------ sentinel
    def _check_pressure(self, row: Dict[str, Any], core: Any = None
                        ) -> None:
        """The OOM-proximity sentinel: fire ONCE per below->above
        transition of the watermark (a rank hovering at the threshold
        must not page every sample); dropping below re-arms."""
        if row["cap_bytes"] <= 0:
            return  # no cap known: proximity is undefined, stay quiet
        high = float(_knob("HOROVOD_MEM_HIGH_WATERMARK"))
        above = row["watermark"] >= high
        with self.lock:
            fire = above and not self.pressure_above
            self.pressure_above = above
            if fire:
                self.pressure_events += 1
        if not fire:
            return
        try:
            from ..utils import metrics as M
            M.MEM_PRESSURE_EVENTS.inc()
        except ImportError:
            pass
        dump = self._flight_dump(row, core=core)
        try:
            from ..utils.timeline import trace_instant
            trace_instant("alerts", "mem.pressure",
                          args={"watermark": round(row["watermark"], 4),
                                "bytes_in_use": row["bytes_in_use"],
                                "cap_bytes": row["cap_bytes"]})
        except Exception:
            pass
        try:
            from ..common import hvdlogging as log
            log.warning(
                "memstats: device memory watermark %.1f%% crossed the "
                "high watermark %.1f%% (%d / %d bytes)%s — "
                "docs/memory.md#oom", row["watermark"] * 100, high * 100,
                row["bytes_in_use"], row["cap_bytes"],
                f"; flight dump: {dump}" if dump else "")
        except Exception:
            pass

    def _flight_dump(self, row: Dict[str, Any], core: Any = None
                     ) -> Optional[str]:
        """Explicit native flight dump, reason ``mem`` — the black box
        taken before the kernel kills the process.  Path derives from
        HOROVOD_FLIGHT_RECORD with a ``.mem`` suffix so a later crash
        record never overwrites the pressure evidence (the sentinel
        ``.nan`` pattern, watch/sentinel.py)."""
        path = str(_knob("HOROVOD_FLIGHT_RECORD") or "")
        if core is None:
            try:
                from .. import runtime as _rt
                if _rt.is_initialized():
                    core = _rt.get().core
            except Exception:
                core = None
        if core is None or not getattr(core, "_h", True):
            return None
        if not path:
            return None
        path = f"{path}.mem"
        try:
            if core.flight_dump(
                    path, reason=f"mem watermark="
                    f"{row['watermark']:.4f}"):
                with self.lock:
                    self.dump_paths.append(path)
                return path
        except Exception:
            pass  # forensics must never take the training loop down
        return None

    # -------------------------------------------------------------- report
    def report_section(self) -> Optional[Dict[str, Any]]:
        """The ``memory`` section of ``hvd.perf_report()`` (and thus
        ``GET /perf``): the last sample's measured residency beside the
        per-plane prediction, the drift ratio, and the cap headroom
        ROADMAP item 2's layout solver consumes.  None before the first
        sample (or with HOROVOD_MEM off)."""
        with self.lock:
            row = dict(self.last) if self.last else None
            events = self.pressure_events
        if row is None:
            return None
        pred = row.get("predicted") or {}
        table = {}
        for key in ("params", "grads", "opt_state", "ef_residual"):
            if f"{key}_bytes" in pred or key in row["planes"]:
                table[key] = {
                    "predicted_bytes": int(pred.get(f"{key}_bytes", 0)),
                    "attributed_bytes": int(row["planes"].get(key, 0)),
                }
        for key in ("kv_pool", "fusion_overlap", "native_core"):
            if key in row["planes"]:
                table[key] = {"predicted_bytes": None,
                              "attributed_bytes": row["planes"][key]}
        return {
            "source": row["source"],
            "measured": {
                "bytes_in_use": row["bytes_in_use"],
                "peak_bytes_in_use": row["peak_bytes_in_use"],
                "cap_bytes": row["cap_bytes"],
                "host_rss_bytes": row["host_rss_bytes"],
                "watermark": row["watermark"],
                "headroom_bytes": row["headroom_bytes"],
            },
            "predicted_total_bytes": int(pred["total_bytes"])
            if pred else None,
            "model_drift_ratio": row["model_drift_ratio"],
            "planes": table,
            "native": row.get("native"),
            "kv_pool": row.get("kv_pool"),
            "pressure_events": events,
            "time": row["time"],
        }


# ---------------------------------------------------------- module global
GLOBAL = MemSampler()


def reset() -> None:
    """Test hook: forget samples, peaks and the pressure latch
    (module-global state), and unregister the KV-pool provider."""
    global GLOBAL
    GLOBAL = MemSampler()
    set_kv_pool_provider(None)


def sample(**kw) -> Optional[Dict[str, Any]]:
    return GLOBAL.sample(**kw)


def report_section() -> Optional[Dict[str, Any]]:
    return GLOBAL.report_section()


def last_sample() -> Optional[Dict[str, Any]]:
    with GLOBAL.lock:
        return dict(GLOBAL.last) if GLOBAL.last else None
