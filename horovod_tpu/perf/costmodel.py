"""Analytical cost model: the predicted half of the attribution plane.

The Horovod paper justified tensor fusion by characterizing where step
time went BY HAND with its timeline (arxiv 1802.05799 §4); arxiv
1810.11112 argues that characterization must be systematic.  This module
is the systematic half: trace-time FLOP/byte accounting that yields a
roofline-style *predicted* step time per link class, which the ledger
(``perf/ledger.py``) holds against the *measured* decomposition — so the
model's own drift is observable (``docs/profiling.md``).

One source of truth: ``bench.py``'s MFU math (``PEAK_TFLOPS``, the
6·N FLOPs/token convention) lives HERE and is imported by the bench, the
ledger and the tests — the constants can no longer fork.

Deliberately stdlib-only at module level (no jax, no package-relative
imports), so ``bench.py``'s light supervisor and ``scripts/perf_gate.py``
can load this file standalone by path, the way ``bench.py`` loads
``utils/probe.py``.  Functions that consume jax objects (bucket plans,
compiled programs) import lazily inside their bodies.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------- hardware
# bf16 peak TFLOP/s per chip by TPU generation (public spec sheets).
# 'cpu' is nominal so CPU-virtual smoke runs produce a finite ratio.
PEAK_TFLOPS: Dict[str, float] = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
    "cpu": 0.5,
}

# Per-chip link bandwidth by fabric class, GB/s (order-of-magnitude public
# figures: ICI ~ hundreds of GB/s per chip, DCN ~ tens, loopback is a
# same-host memcpy).  The roofline uses these to turn modeled wire bytes
# into seconds; absolute accuracy matters less than the ICI/DCN ratio —
# the quantity that decides comm-bound vs compute-bound.
LINK_GBPS: Dict[str, float] = {
    "ici": 100.0,
    "dcn": 6.25,       # ~50 Gbit/s per host
    "loopback": 10.0,  # CPU-virtual: one-process memcpy "fabric"
}
LINK_CLASSES = tuple(sorted(LINK_GBPS))


def peak_flops(chip: str) -> float:
    """Chip name -> peak FLOP/s (falls back to v5e like bench.py)."""
    return PEAK_TFLOPS.get(chip, PEAK_TFLOPS["v5e"]) * 1e12


def link_bandwidth(link: str) -> float:
    """Link class name -> bytes/s."""
    if link not in LINK_GBPS:
        raise ValueError(
            f"unknown link class {link!r}; valid: {', '.join(LINK_CLASSES)} "
            "(HOROVOD_PERF_LINK, docs/profiling.md)")
    return LINK_GBPS[link] * 1e9


# ------------------------------------------------------------------- flops
def train_flops_per_token(n_params: int,
                          attention: Optional[Dict[str, Any]] = None
                          ) -> float:
    """Training FLOPs per token.

    Baseline convention (what bench.py's MFU always used): ``6·N`` —
    2·N for the forward matmuls, 4·N for backward, attention score/value
    matmuls EXCLUDED.  This is the standard, conservative MFU convention.

    ``attention={"n_layers", "dim", "seq", "causal"}`` adds the attention
    term: per layer and token the score (q·Kᵀ) and value (p·V) matmuls
    are 2·2·seq·dim MACs = 4·seq·dim forward FLOPs, tripled for the
    backward pass -> ``12·n_layers·seq·dim`` per token; ``causal=True``
    (default) halves it, since position t attends to t+1 of seq keys on
    average.  MFU computed with the attention term included is reported
    as ``mfu_attn`` beside the conservative ``mfu`` (docs/profiling.md).
    """
    flops = 6.0 * float(n_params)
    if attention:
        layers = float(attention["n_layers"])
        dim = float(attention["dim"])
        seq = float(attention["seq"])
        attn = 12.0 * layers * seq * dim
        if attention.get("causal", True):
            attn *= 0.5
        flops += attn
    return flops


# ------------------------------------------------------------ param counts
def llama_param_count(vocab: int, dim: int, n_layers: int, n_heads: int,
                      n_kv_heads: int, ffn_dim: int) -> int:
    """Exact parameter count of ``models/llama.py`` init() from config
    shapes — no device allocation needed, so golden tests and the cost
    model can price the bench configs analytically."""
    head_dim = dim // n_heads
    per_layer = (
        dim * n_heads * head_dim          # wq
        + 2 * dim * n_kv_heads * head_dim  # wk, wv
        + n_heads * head_dim * dim         # wo
        + 3 * dim * ffn_dim                # w_gate, w_up, w_down
        + 2 * dim                          # attn_norm, ffn_norm
    )
    return (vocab * dim                    # embed
            + n_layers * per_layer
            + dim                          # final_norm
            + dim * vocab)                 # lm_head


def moe_llama_param_count(vocab: int, dim: int, n_layers: int,
                          n_heads: int, n_kv_heads: int, moe_hidden: int,
                          n_experts: int) -> int:
    """Exact parameter count of ``models/moe_llama.py`` init(): llama
    attention blocks with the dense FFN replaced by router + stacked
    expert FFNs (``parallel/expert.py`` init_moe_params layout)."""
    head_dim = dim // n_heads
    per_layer = (
        dim * n_heads * head_dim
        + 2 * dim * n_kv_heads * head_dim
        + n_heads * head_dim * dim
        + 2 * dim                                  # attn_norm, ffn_norm
        + dim * n_experts                          # router
        + 2 * n_experts * dim * moe_hidden         # wi, wo
    )
    return vocab * dim + n_layers * per_layer + dim + dim * vocab


def moe_llama_active_param_count(vocab: int, dim: int, n_layers: int,
                                 n_heads: int, n_kv_heads: int,
                                 moe_hidden: int, n_experts: int,
                                 experts_per_token: int) -> int:
    """Parameters a single token's forward pass actually touches (the N
    that belongs in 6·N for MoE MFU): all non-expert weights plus
    ``experts_per_token`` expert FFNs per layer."""
    total = moe_llama_param_count(vocab, dim, n_layers, n_heads,
                                  n_kv_heads, moe_hidden, n_experts)
    inactive_experts = n_experts - experts_per_token
    return total - n_layers * 2 * inactive_experts * dim * moe_hidden


# ---------------------------------------------------------------- roofline
def ring_wire_bytes(nelems: int, itemsize: float, n: int) -> float:
    """Per-chip wire bytes of one ring allreduce (the same model as
    ``ops/wire.modeled_wire_bytes``'s flat case, restated stdlib-only:
    each chip sends 2(n-1) chunks of ceil(nelems/n) elements)."""
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) * math.ceil(nelems / n) * itemsize


def predicted_step_time(flops: float, comm_bytes: float, *,
                        chip: str = "cpu", link: str = "loopback",
                        overlap_fraction: float = 0.0,
                        input_seconds: float = 0.0) -> Dict[str, float]:
    """Roofline-style predicted step decomposition, in seconds.

    ``compute`` = flops / chip peak; ``exposed_comm`` = the
    non-overlapped share of comm bytes over the link-class bandwidth
    (overlapped comm hides behind compute by construction, so only the
    exposed share lands on the critical path); ``step`` adds the
    host-input term.  A prediction, not a measurement — the ledger
    records the deltas against measured time so model drift is itself
    observable (``hvd_perf_model_drift_ratio``)."""
    if not 0.0 <= overlap_fraction <= 1.0:
        raise ValueError(
            f"overlap_fraction {overlap_fraction} outside [0, 1]")
    compute = float(flops) / peak_flops(chip)
    exposed = (float(comm_bytes) * (1.0 - overlap_fraction)
               / link_bandwidth(link))
    return {
        "compute_s": compute,
        "exposed_comm_s": exposed,
        "host_input_s": float(input_seconds),
        "step_s": compute + exposed + float(input_seconds),
        "chip": chip,
        "link": link,
    }


# ------------------------------------------------------- ZeRO what-if model
# Wire itemsize per RS-leg format (bytes/element on the wire) — the
# stdlib restatement of ops/wire.py's table, so the zero chain's
# trace-time gauges and this prediction cannot fork.
WIRE_ITEMSIZE: Dict[str, float] = {
    "none": 4.0, "bf16": 2.0, "fp16": 2.0,
    "int8_ring": 1.0, "dcn_int8": 1.0,
}
ZERO_LEVELS = (0, 1, 2, 3)


def _ring_half_leg(n: int, nelems: float, itemsize: float) -> float:
    """One reduce_scatter OR all_gather leg of the standard ring, per
    chip: (n-1) chunks of ceil(nelems/n) elements (half of
    :func:`ring_wire_bytes`'s full allreduce)."""
    if n <= 1:
        return 0.0
    return (n - 1) * math.ceil(nelems / n) * itemsize


def zero_comm_bytes(nelems: float, world: int, level: int, *,
                    k: int = 1, wire_format: str = "none",
                    itemsize: float = 4.0) -> Dict[str, float]:
    """Per-chip modeled wire bytes of ONE optimizer step of the ZeRO
    chain (parallel/zero.py; docs/zero.md) — the RS and AG legs priced
    separately, per level:

      level 0  plain DP: accumulate k microbatches locally, ONE
               allreduce (both ring phases at the wire itemsize, the
               ops/wire.py allreduce model);
      level 1  k per-microbatch syncs; at k > 1 each shard is gathered
               back to keep the full gradient accumulator (the
               redundancy level 2 deletes), plus the update all_gather;
      level 2  k reduce_scatters onto the resident shard + one update
               all_gather;
      level 3  k reduce_scatters + one PARAM all_gather at step start —
               the same bytes as level 2 (RS+AG == AR at k=1: the
               ZeRO/arXiv:2004.13336 equal-wire-bytes claim).

    The RS leg carries ``wire_format``'s itemsize; AG legs are exact
    (``itemsize``) — gathered payloads are master state with no EF
    channel (docs/zero.md#wire-composition).
    """
    if level not in ZERO_LEVELS:
        raise ValueError(f"zero level {level} invalid; must be one of "
                         f"{ZERO_LEVELS}")
    n = int(world)
    enc = WIRE_ITEMSIZE.get(wire_format, itemsize)
    rs = _ring_half_leg(n, nelems, enc)
    ag = _ring_half_leg(n, nelems, itemsize)
    if level == 0:
        rs_total, ag_total = rs, _ring_half_leg(n, nelems, enc)
    elif level == 1:
        rs_total = k * rs
        ag_total = (k + 1) * ag if k > 1 else ag
    else:
        rs_total, ag_total = k * rs, ag
    return {"rs_bytes": rs_total, "ag_bytes": ag_total,
            "total_bytes": rs_total + ag_total}


def zero_memory_bytes(level: int, n_params: float, world: int, *,
                      opt_slots: int = 2, ef: bool = False,
                      itemsize: float = 4.0) -> Dict[str, int]:
    """Analytical PER-RANK resident bytes of the training state under a
    ZeRO level (docs/zero.md#memory-math): params, the gradient
    accumulator, optimizer state (``opt_slots`` params-shaped buffers —
    2 for adam's moments) and the EF residual (full-size per rank when a
    lossy wire format is error-compensated; inherent to EF-on-RS).
    Level 0 = plain data parallelism, the reduction baseline."""
    if level not in ZERO_LEVELS:
        raise ValueError(f"zero level {level} invalid; must be one of "
                         f"{ZERO_LEVELS}")
    n = max(int(world), 1)
    p = float(n_params) * itemsize
    out = {
        "params_bytes": p / n if level >= 3 else p,
        "grads_bytes": p / n if level >= 2 else p,
        "opt_state_bytes": (p * opt_slots / n if level >= 1
                           else p * opt_slots),
        "ef_residual_bytes": p if ef else 0.0,
    }
    out = {key: int(v) for key, v in out.items()}
    out["total_bytes"] = sum(out.values())
    return out


def zero_level_table(n_params: float, world: int, *,
                     opt_slots: int = 2, k: int = 1,
                     wire_format: str = "none", ef: bool = False,
                     chip: str = "cpu", link: str = "loopback",
                     flops_per_step: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
    """The "what would ZeRO-N cost me at my topology" table
    (docs/zero.md): one row per level with the analytical per-rank
    memory, the per-step wire bytes split RS/AG, the exposed-comm
    seconds on ``link``, and — when ``flops_per_step`` is known — the
    roofline predicted step.  Rendered by ``hvd.perf_report()`` /
    ``GET /perf`` / ``hvdrun doctor --perf``; the ledger measures the
    active level's drift against it."""
    rows = []
    for level in ZERO_LEVELS:
        comm = zero_comm_bytes(n_params, world, level, k=k,
                               wire_format=wire_format)
        row: Dict[str, Any] = {
            "level": level,
            "memory": zero_memory_bytes(level, n_params, world,
                                        opt_slots=opt_slots, ef=ef),
            "comm": {key: int(v) for key, v in comm.items()},
            "exposed_comm_s": comm["total_bytes"] / link_bandwidth(link),
        }
        if flops_per_step:
            row["predicted"] = predicted_step_time(
                flops_per_step, comm["total_bytes"], chip=chip, link=link)
        rows.append(row)
    return rows


# ----------------------------------------------- plan-cache comm accounting
def plan_comm_bytes(plan, policy: str, axis_sizes: Dict[str, int],
                    op=None) -> Dict[str, Any]:
    """Per-fusion-bucket comm bytes of one gradient sync under a wire
    policy: the plan cache's bucket plan × the wire-policy format of each
    bucket × the ring model, summed per fabric — the analytical comm leg
    of the predicted step (uses ``ops/wire.py`` as the byte-model source
    of truth; imported lazily, this is the one jax-touching entry point).
    """
    from ..common.reduce_op import ReduceOp
    from ..ops import wire

    op = ReduceOp.AVERAGE if op is None else op
    axis_name = ("dcn.data", "ici.data") if "dcn" in axis_sizes else "data"
    pol = wire.get_policy(policy)
    total = 0.0
    per_fabric: Dict[str, float] = {}
    per_format: Dict[str, float] = {}
    for b in plan.buckets:
        import numpy as np
        fmt = wire.resolve_format(pol(b.nbytes, b.dtype, axis_name),
                                  b.dtype, axis_name, op)
        m = wire.modeled_wire_bytes(sum(b.sizes),
                                    np.dtype(b.dtype).itemsize, fmt,
                                    axis_sizes)
        total += m["bottleneck"]
        per_format[fmt] = per_format.get(fmt, 0.0) + m["bottleneck"]
        for fabric, v in m["per_fabric"].items():
            per_fabric[fabric] = per_fabric.get(fabric, 0.0) + v
    return {"bottleneck": int(total),
            "per_fabric": {k: int(v) for k, v in sorted(per_fabric.items())},
            "per_format": {k: int(v) for k, v in sorted(per_format.items())}}


def compiled_flops(fn, *args, **kwargs) -> Optional[float]:
    """FLOPs of one call of ``fn(*args)`` from XLA's own
    ``cost_analysis()`` where the backend provides it (jit lower ->
    compile -> cost_analysis), None otherwise — callers fall back to the
    6·N analytical model (``train_flops_per_token``), which stays the
    single convention the MFU numbers are defined by."""
    try:
        import jax
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else None
        if not ca:
            return None
        flops = ca.get("flops")
        return float(flops) if flops and flops > 0 else None
    except Exception:
        return None
