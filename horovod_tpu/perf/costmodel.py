"""Analytical cost model: the predicted half of the attribution plane.

The Horovod paper justified tensor fusion by characterizing where step
time went BY HAND with its timeline (arxiv 1802.05799 §4); arxiv
1810.11112 argues that characterization must be systematic.  This module
is the systematic half: trace-time FLOP/byte accounting that yields a
roofline-style *predicted* step time per link class, which the ledger
(``perf/ledger.py``) holds against the *measured* decomposition — so the
model's own drift is observable (``docs/profiling.md``).

One source of truth: ``bench.py``'s MFU math (``PEAK_TFLOPS``, the
6·N FLOPs/token convention) lives HERE and is imported by the bench, the
ledger and the tests — the constants can no longer fork.

Deliberately stdlib-only at module level (no jax, no package-relative
imports), so ``bench.py``'s light supervisor and ``scripts/perf_gate.py``
can load this file standalone by path, the way ``bench.py`` loads
``utils/probe.py``.  Functions that consume jax objects (bucket plans,
compiled programs) import lazily inside their bodies.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------- hardware
# bf16 peak TFLOP/s per chip by TPU generation (public spec sheets).
# 'cpu' is nominal so CPU-virtual smoke runs produce a finite ratio.
PEAK_TFLOPS: Dict[str, float] = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
    "cpu": 0.5,
}

# Per-chip link bandwidth by fabric class, GB/s (order-of-magnitude public
# figures: ICI ~ hundreds of GB/s per chip, DCN ~ tens, loopback is a
# same-host memcpy).  The roofline uses these to turn modeled wire bytes
# into seconds; absolute accuracy matters less than the ICI/DCN ratio —
# the quantity that decides comm-bound vs compute-bound.
LINK_GBPS: Dict[str, float] = {
    "ici": 100.0,
    "dcn": 6.25,       # ~50 Gbit/s per host
    "loopback": 10.0,  # CPU-virtual: one-process memcpy "fabric"
}
LINK_CLASSES = tuple(sorted(LINK_GBPS))


def peak_flops(chip: str) -> float:
    """Chip name -> peak FLOP/s (falls back to v5e like bench.py)."""
    return PEAK_TFLOPS.get(chip, PEAK_TFLOPS["v5e"]) * 1e12


def link_bandwidth(link: str) -> float:
    """Link class name -> bytes/s."""
    if link not in LINK_GBPS:
        raise ValueError(
            f"unknown link class {link!r}; valid: {', '.join(LINK_CLASSES)} "
            "(HOROVOD_PERF_LINK, docs/profiling.md)")
    return LINK_GBPS[link] * 1e9


# ------------------------------------------------------------------- flops
def train_flops_per_token(n_params: int,
                          attention: Optional[Dict[str, Any]] = None
                          ) -> float:
    """Training FLOPs per token.

    Baseline convention (what bench.py's MFU always used): ``6·N`` —
    2·N for the forward matmuls, 4·N for backward, attention score/value
    matmuls EXCLUDED.  This is the standard, conservative MFU convention.

    ``attention={"n_layers", "dim", "seq", "causal"}`` adds the attention
    term: per layer and token the score (q·Kᵀ) and value (p·V) matmuls
    are 2·2·seq·dim MACs = 4·seq·dim forward FLOPs, tripled for the
    backward pass -> ``12·n_layers·seq·dim`` per token; ``causal=True``
    (default) halves it, since position t attends to t+1 of seq keys on
    average.  MFU computed with the attention term included is reported
    as ``mfu_attn`` beside the conservative ``mfu`` (docs/profiling.md).
    """
    flops = 6.0 * float(n_params)
    if attention:
        layers = float(attention["n_layers"])
        dim = float(attention["dim"])
        seq = float(attention["seq"])
        attn = 12.0 * layers * seq * dim
        if attention.get("causal", True):
            attn *= 0.5
        flops += attn
    return flops


# ------------------------------------------------------------ param counts
def llama_param_count(vocab: int, dim: int, n_layers: int, n_heads: int,
                      n_kv_heads: int, ffn_dim: int) -> int:
    """Exact parameter count of ``models/llama.py`` init() from config
    shapes — no device allocation needed, so golden tests and the cost
    model can price the bench configs analytically."""
    head_dim = dim // n_heads
    per_layer = (
        dim * n_heads * head_dim          # wq
        + 2 * dim * n_kv_heads * head_dim  # wk, wv
        + n_heads * head_dim * dim         # wo
        + 3 * dim * ffn_dim                # w_gate, w_up, w_down
        + 2 * dim                          # attn_norm, ffn_norm
    )
    return (vocab * dim                    # embed
            + n_layers * per_layer
            + dim                          # final_norm
            + dim * vocab)                 # lm_head


def moe_llama_param_count(vocab: int, dim: int, n_layers: int,
                          n_heads: int, n_kv_heads: int, moe_hidden: int,
                          n_experts: int) -> int:
    """Exact parameter count of ``models/moe_llama.py`` init(): llama
    attention blocks with the dense FFN replaced by router + stacked
    expert FFNs (``parallel/expert.py`` init_moe_params layout)."""
    head_dim = dim // n_heads
    per_layer = (
        dim * n_heads * head_dim
        + 2 * dim * n_kv_heads * head_dim
        + n_heads * head_dim * dim
        + 2 * dim                                  # attn_norm, ffn_norm
        + dim * n_experts                          # router
        + 2 * n_experts * dim * moe_hidden         # wi, wo
    )
    return vocab * dim + n_layers * per_layer + dim + dim * vocab


def moe_llama_active_param_count(vocab: int, dim: int, n_layers: int,
                                 n_heads: int, n_kv_heads: int,
                                 moe_hidden: int, n_experts: int,
                                 experts_per_token: int) -> int:
    """Parameters a single token's forward pass actually touches (the N
    that belongs in 6·N for MoE MFU): all non-expert weights plus
    ``experts_per_token`` expert FFNs per layer."""
    total = moe_llama_param_count(vocab, dim, n_layers, n_heads,
                                  n_kv_heads, moe_hidden, n_experts)
    inactive_experts = n_experts - experts_per_token
    return total - n_layers * 2 * inactive_experts * dim * moe_hidden


# ---------------------------------------------------------------- roofline
def ring_wire_bytes(nelems: int, itemsize: float, n: int) -> float:
    """Per-chip wire bytes of one ring allreduce (the same model as
    ``ops/wire.modeled_wire_bytes``'s flat case, restated stdlib-only:
    each chip sends 2(n-1) chunks of ceil(nelems/n) elements)."""
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) * math.ceil(nelems / n) * itemsize


def predicted_step_time(flops: float, comm_bytes: float, *,
                        chip: str = "cpu", link: str = "loopback",
                        overlap_fraction: float = 0.0,
                        input_seconds: float = 0.0) -> Dict[str, float]:
    """Roofline-style predicted step decomposition, in seconds.

    ``compute`` = flops / chip peak; ``exposed_comm`` = the
    non-overlapped share of comm bytes over the link-class bandwidth
    (overlapped comm hides behind compute by construction, so only the
    exposed share lands on the critical path); ``step`` adds the
    host-input term.  A prediction, not a measurement — the ledger
    records the deltas against measured time so model drift is itself
    observable (``hvd_perf_model_drift_ratio``)."""
    if not 0.0 <= overlap_fraction <= 1.0:
        raise ValueError(
            f"overlap_fraction {overlap_fraction} outside [0, 1]")
    compute = float(flops) / peak_flops(chip)
    exposed = (float(comm_bytes) * (1.0 - overlap_fraction)
               / link_bandwidth(link))
    return {
        "compute_s": compute,
        "exposed_comm_s": exposed,
        "host_input_s": float(input_seconds),
        "step_s": compute + exposed + float(input_seconds),
        "chip": chip,
        "link": link,
    }


# ------------------------------------------------------- ZeRO what-if model
# Wire itemsize per RS-leg format (bytes/element on the wire) — the
# stdlib restatement of ops/wire.py's table, so the zero chain's
# trace-time gauges and this prediction cannot fork.
WIRE_ITEMSIZE: Dict[str, float] = {
    "none": 4.0, "bf16": 2.0, "fp16": 2.0,
    "int8_ring": 1.0, "dcn_int8": 1.0,
}
ZERO_LEVELS = (0, 1, 2, 3)


def _ring_half_leg(n: int, nelems: float, itemsize: float) -> float:
    """One reduce_scatter OR all_gather leg of the standard ring, per
    chip: (n-1) chunks of ceil(nelems/n) elements (half of
    :func:`ring_wire_bytes`'s full allreduce)."""
    if n <= 1:
        return 0.0
    return (n - 1) * math.ceil(nelems / n) * itemsize


def zero_comm_bytes(nelems: float, world: int, level: int, *,
                    k: int = 1, wire_format: str = "none",
                    itemsize: float = 4.0) -> Dict[str, float]:
    """Per-chip modeled wire bytes of ONE optimizer step of the ZeRO
    chain (parallel/zero.py; docs/zero.md) — the RS and AG legs priced
    separately, per level:

      level 0  plain DP: accumulate k microbatches locally, ONE
               allreduce (both ring phases at the wire itemsize, the
               ops/wire.py allreduce model);
      level 1  k per-microbatch syncs; at k > 1 each shard is gathered
               back to keep the full gradient accumulator (the
               redundancy level 2 deletes), plus the update all_gather;
      level 2  k reduce_scatters onto the resident shard + one update
               all_gather;
      level 3  k reduce_scatters + one PARAM all_gather at step start —
               the same bytes as level 2 (RS+AG == AR at k=1: the
               ZeRO/arXiv:2004.13336 equal-wire-bytes claim).

    The RS leg carries ``wire_format``'s itemsize; AG legs are exact
    (``itemsize``) — gathered payloads are master state with no EF
    channel (docs/zero.md#wire-composition).
    """
    if level not in ZERO_LEVELS:
        raise ValueError(f"zero level {level} invalid; must be one of "
                         f"{ZERO_LEVELS}")
    n = int(world)
    enc = WIRE_ITEMSIZE.get(wire_format, itemsize)
    rs = _ring_half_leg(n, nelems, enc)
    ag = _ring_half_leg(n, nelems, itemsize)
    if level == 0:
        rs_total, ag_total = rs, _ring_half_leg(n, nelems, enc)
    elif level == 1:
        rs_total = k * rs
        ag_total = (k + 1) * ag if k > 1 else ag
    else:
        rs_total, ag_total = k * rs, ag
    return {"rs_bytes": rs_total, "ag_bytes": ag_total,
            "total_bytes": rs_total + ag_total}


def zero_memory_bytes(level: int, n_params: float, world: int, *,
                      opt_slots: int = 2, ef: bool = False,
                      itemsize: float = 4.0) -> Dict[str, int]:
    """Analytical PER-RANK resident bytes of the training state under a
    ZeRO level (docs/zero.md#memory-math): params, the gradient
    accumulator, optimizer state (``opt_slots`` params-shaped buffers —
    2 for adam's moments) and the EF residual (full-size per rank when a
    lossy wire format is error-compensated; inherent to EF-on-RS).
    Level 0 = plain data parallelism, the reduction baseline."""
    if level not in ZERO_LEVELS:
        raise ValueError(f"zero level {level} invalid; must be one of "
                         f"{ZERO_LEVELS}")
    n = max(int(world), 1)
    p = float(n_params) * itemsize
    out = {
        "params_bytes": p / n if level >= 3 else p,
        "grads_bytes": p / n if level >= 2 else p,
        "opt_state_bytes": (p * opt_slots / n if level >= 1
                           else p * opt_slots),
        "ef_residual_bytes": p if ef else 0.0,
    }
    out = {key: int(v) for key, v in out.items()}
    out["total_bytes"] = sum(out.values())
    return out


def zero_level_table(n_params: float, world: int, *,
                     opt_slots: int = 2, k: int = 1,
                     wire_format: str = "none", ef: bool = False,
                     chip: str = "cpu", link: str = "loopback",
                     flops_per_step: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
    """The "what would ZeRO-N cost me at my topology" table
    (docs/zero.md): one row per level with the analytical per-rank
    memory, the per-step wire bytes split RS/AG, the exposed-comm
    seconds on ``link``, and — when ``flops_per_step`` is known — the
    roofline predicted step.  Rendered by ``hvd.perf_report()`` /
    ``GET /perf`` / ``hvdrun doctor --perf``; the ledger measures the
    active level's drift against it."""
    rows = []
    for level in ZERO_LEVELS:
        comm = zero_comm_bytes(n_params, world, level, k=k,
                               wire_format=wire_format)
        row: Dict[str, Any] = {
            "level": level,
            "memory": zero_memory_bytes(level, n_params, world,
                                        opt_slots=opt_slots, ef=ef),
            "comm": {key: int(v) for key, v in comm.items()},
            "exposed_comm_s": comm["total_bytes"] / link_bandwidth(link),
        }
        if flops_per_step:
            row["predicted"] = predicted_step_time(
                flops_per_step, comm["total_bytes"], chip=chip, link=link)
        rows.append(row)
    return rows


# ------------------------------------------------------- 3D layout solver
# The whole-parallelism-space extrapolation of the ZeRO what-if table
# above (ROADMAP item 2; docs/parallelism.md): enumerate (dp, tp, pp,
# zero_level, wire, overlap_depth) factorizations of the topology, price
# each with the SAME roofline primitives the ledger validates
# (ring_wire_bytes / zero_comm_bytes / zero_memory_bytes), filter by a
# per-chip memory cap, rank by predicted step time.  Stdlib-only like
# everything else here so bench.py can load it standalone.
LAYOUT_AXES = ("dp", "tp", "pp")

# Live activation bytes per token per resident layer, in units of
# dim * itemsize: residual stream + normed input + attn output + ffn
# intermediate held for the backward pass.  A deliberate small-constant
# model (docs/parallelism.md#memory-cap), not a measurement — the bench
# reports the measured peak beside it so the gap stays observable.
ACTIVATION_MULT = 4.0


def tp_comm_bytes(tp: int, tokens: float, dim: int, n_layers: int, *,
                  itemsize: float = 4.0) -> float:
    """Per-chip wire bytes of Megatron-style tensor parallelism for one
    step: each transformer layer all_reduces the [tokens, dim] residual
    activation twice in the forward (attention wo and FFN down row-
    parallel psums) and twice in the backward (the conjugate f-operator
    psums at the column-parallel block inputs) -> 4 ring allreduces per
    layer over the tp group (parallel/layout.py places exactly these)."""
    if tp <= 1:
        return 0.0
    return 4.0 * n_layers * ring_wire_bytes(tokens * dim, itemsize, tp)


def pp_comm_bytes(pp: int, n_micro: int, mb_tokens: float, dim: int, *,
                  itemsize: float = 4.0) -> float:
    """Per-chip wire bytes of the GPipe schedule for one step: one
    ppermute shift of a [mb_tokens, dim] activation per tick, with
    ``n_micro + pp - 1`` ticks, forward and backward (ppermute's
    transpose is the reverse shift, same payload)."""
    if pp <= 1:
        return 0.0
    return 2.0 * (n_micro + pp - 1) * mb_tokens * dim * itemsize


def _effective_microbatches(local_batch: int, requested: int) -> int:
    """Largest divisor of ``local_batch`` that is <= ``requested`` — the
    GPipe microbatch count a (dp, pp) candidate can actually run."""
    m = max(1, min(int(requested), int(local_batch)))
    while m > 1 and local_batch % m:
        m -= 1
    return m


def layout_memory_bytes(model: Dict[str, Any], dp: int, tp: int, pp: int,
                        *, zero_level: int = 1, ef: bool = False,
                        opt_slots: int = 2) -> Dict[str, int]:
    """Per-chip resident bytes under a (dp, tp, pp) layout: the ZeRO
    state triangle priced on this chip's ``n_params / (tp*pp)`` slice
    with the RS/AG group = the dp subgroup, plus the activation term
    (batch/dp rows x the layers resident on this pipeline stage; the
    residual stream is replicated across tp so tp does not divide it)."""
    itemsize = float(model.get("itemsize", 4.0))
    n_local = float(model["n_params"]) / (tp * pp)
    out = dict(zero_memory_bytes(zero_level, n_local, dp,
                                 opt_slots=opt_slots, ef=ef,
                                 itemsize=itemsize))
    total = out.pop("total_bytes")
    batch = float(model.get("batch", dp))
    seq = float(model.get("seq", 1))
    n_layers = float(model.get("n_layers", 1))
    act = (batch / dp) * seq * (n_layers / pp) \
        * float(model.get("dim", 0)) * ACTIVATION_MULT * itemsize
    out["activation_bytes"] = int(act)
    out["total_bytes"] = int(total + act)
    return out


def layout_step_time(model: Dict[str, Any], dp: int, tp: int, pp: int, *,
                     zero_level: int = 1, k: int = 1,
                     wire_format: str = "none", overlap_depth: int = 0,
                     n_micro: int = 4, chip: str = "cpu",
                     link: str = "loopback", ef: bool = False,
                     opt_slots: int = 2) -> Dict[str, Any]:
    """Predicted step decomposition of one (dp, tp, pp) candidate:

      compute        model FLOPs spread over all dp*tp*pp chips;
      tp_comm        4 activation allreduces per layer over the tp ring;
      pp_comm        the GPipe ppermute stream;
      bubble         (S-1)/(M+S-1) inflates compute + tp comm (those run
                     inside the pipelined region; docs/parallelism.md);
      zero_comm      RS/AG legs of the chain priced on the n_params/(tp*pp)
                     slice over the DP SUBGROUP only — level-3 param
                     all_gathers hide behind forward compute with a
                     prefetch window, so depth d exposes ag/d.

    All terms land on one ``link`` class (per-link-class roofline);
    memory comes from :func:`layout_memory_bytes`."""
    itemsize = float(model.get("itemsize", 4.0))
    bw = link_bandwidth(link)
    seq = float(model.get("seq", 1))
    batch = float(model.get("batch", dp))
    n_layers = int(model.get("n_layers", 1))
    dim = int(model.get("dim", 0))
    local_rows = batch / dp
    m = _effective_microbatches(int(local_rows), n_micro) if pp > 1 else 1
    compute_s = (float(model.get("flops_per_step", 0.0))
                 / (peak_flops(chip) * dp * tp * pp))
    # Every microbatch passes through this chip's resident n_layers/pp
    # layers, so the tp rings see all local tokens per step.
    tp_s = tp_comm_bytes(tp, local_rows * seq, dim,
                         n_layers // pp if pp > 1 else n_layers,
                         itemsize=itemsize) / bw
    pp_s = pp_comm_bytes(pp, m, (local_rows / m) * seq, dim,
                         itemsize=itemsize) / bw
    bubble = (pp - 1) / (m + pp - 1) if pp > 1 else 0.0
    comm = zero_comm_bytes(float(model["n_params"]) / (tp * pp), dp,
                           zero_level, k=k, wire_format=wire_format,
                           itemsize=itemsize)
    rs_s = comm["rs_bytes"] / bw
    ag_s = comm["ag_bytes"] / bw
    if zero_level >= 3 and overlap_depth > 0:
        ag_s /= overlap_depth
    zero_s = rs_s + ag_s
    step_s = (compute_s + tp_s) / (1.0 - bubble) + pp_s + zero_s
    return {
        "layout": {"dp": dp, "tp": tp, "pp": pp},
        "zero_level": int(zero_level),
        "wire_format": wire_format,
        "overlap_depth": int(overlap_depth),
        "n_micro": int(m),
        "bubble_fraction": bubble,
        "compute_s": compute_s,
        "tp_comm_s": tp_s,
        "pp_comm_s": pp_s,
        "zero_comm_s": zero_s,
        "step_s": step_s,
        "memory": layout_memory_bytes(model, dp, tp, pp,
                                      zero_level=zero_level, ef=ef,
                                      opt_slots=opt_slots),
        "chip": chip,
        "link": link,
    }


def _factorizations(world: int):
    for dp in range(1, world + 1):
        if world % dp:
            continue
        rest = world // dp
        for tp in range(1, rest + 1):
            if rest % tp:
                continue
            yield dp, tp, rest // tp


def enumerate_layouts(model: Dict[str, Any], world: int, *,
                      levels=(1, 2, 3), wires=("none",),
                      overlap_depths=(0,), k: int = 1, n_micro: int = 4,
                      chip: str = "cpu", link: str = "loopback",
                      ef: bool = False) -> List[Dict[str, Any]]:
    """All VALID (dp, tp, pp, zero_level, wire, overlap_depth) candidates
    at ``world`` chips: dp*tp*pp == world, tp divides n_heads AND
    n_kv_heads (contiguous GQA head slices stay aligned), pp divides
    n_layers, dp divides the global batch.  ``overlap_depths`` only fans
    out at level 3 (prefetch is a level-3 knob; docs/zero.md)."""
    n_heads = int(model.get("n_heads", 1))
    n_kv = int(model.get("n_kv_heads", n_heads))
    n_layers = int(model.get("n_layers", 1))
    batch = int(model.get("batch", world))
    rows = []
    for dp, tp, pp in _factorizations(int(world)):
        if n_heads % tp or n_kv % tp or n_layers % pp or batch % dp:
            continue
        for level in levels:
            for wire in wires:
                depths = overlap_depths if level >= 3 else (0,)
                for depth in depths:
                    rows.append(layout_step_time(
                        model, dp, tp, pp, zero_level=level, k=k,
                        wire_format=wire, overlap_depth=depth,
                        n_micro=n_micro, chip=chip, link=link, ef=ef))
    return rows


def solve_layout(model: Dict[str, Any], world: int, *,
                 mem_cap_bytes: Optional[float] = None,
                 levels=(1, 2, 3), wires=("none",), overlap_depths=(0,),
                 k: int = 1, n_micro: int = 4, chip: str = "cpu",
                 link: str = "loopback", ef: bool = False
                 ) -> Dict[str, Any]:
    """The auto-layout decision (HOROVOD_LAYOUT=auto; ROADMAP item 2):
    rank :func:`enumerate_layouts` candidates memory-fits-first then by
    predicted step time (ties -> fewer pipeline stages, then less tensor
    parallelism — pure dp wins when the model says it's free).  The
    default ``mem_cap_bytes`` callers pass is the memory plane's measured
    ``headroom_bytes`` (PR 16).  Returns the full ranked table plus the
    chosen row; ``chosen["fits"]`` is False only when NOTHING fits — the
    least-infeasible candidate is still surfaced so doctor can say why."""
    rows = enumerate_layouts(model, world, levels=levels, wires=wires,
                             overlap_depths=overlap_depths, k=k,
                             n_micro=n_micro, chip=chip, link=link, ef=ef)
    if not rows:
        raise ValueError(
            f"no valid (dp, tp, pp) factorization of world={world} for "
            f"this model (check n_heads/n_kv_heads/n_layers/batch "
            "divisibility; docs/parallelism.md#constraints)")
    for row in rows:
        row["fits"] = (mem_cap_bytes is None
                       or row["memory"]["total_bytes"] <= mem_cap_bytes)
    rows.sort(key=lambda r: (not r["fits"], r["step_s"],
                             r["layout"]["pp"], r["layout"]["tp"]))
    for rank, row in enumerate(rows, start=1):
        row["rank"] = rank
    return {
        "world": int(world),
        "mem_cap_bytes": (int(mem_cap_bytes)
                          if mem_cap_bytes is not None else None),
        "n_candidates": len(rows),
        "chosen": rows[0],
        "candidates": rows,
    }


def llama_layout_model(*, vocab: int, dim: int, n_layers: int,
                       n_heads: int, n_kv_heads: int, ffn_dim: int,
                       batch: int, seq: int,
                       itemsize: float = 4.0) -> Dict[str, Any]:
    """The model descriptor :func:`solve_layout` consumes, built from
    llama config shapes with the module's own exact param count and the
    6·N FLOPs convention — so the solver, the bench MFU and the ledger
    all price the same model."""
    n_params = llama_param_count(vocab, dim, n_layers, n_heads,
                                 n_kv_heads, ffn_dim)
    return {
        "family": "llama",
        "n_params": n_params,
        "dim": dim,
        "n_layers": n_layers,
        "n_heads": n_heads,
        "n_kv_heads": n_kv_heads,
        "batch": batch,
        "seq": seq,
        "itemsize": itemsize,
        "flops_per_step": train_flops_per_token(n_params) * batch * seq,
    }


# ----------------------------------------------- plan-cache comm accounting
def plan_comm_bytes(plan, policy: str, axis_sizes: Dict[str, int],
                    op=None) -> Dict[str, Any]:
    """Per-fusion-bucket comm bytes of one gradient sync under a wire
    policy: the plan cache's bucket plan × the wire-policy format of each
    bucket × the ring model, summed per fabric — the analytical comm leg
    of the predicted step (uses ``ops/wire.py`` as the byte-model source
    of truth; imported lazily, this is the one jax-touching entry point).
    """
    from ..common.reduce_op import ReduceOp
    from ..ops import wire

    op = ReduceOp.AVERAGE if op is None else op
    axis_name = ("dcn.data", "ici.data") if "dcn" in axis_sizes else "data"
    pol = wire.get_policy(policy)
    total = 0.0
    per_fabric: Dict[str, float] = {}
    per_format: Dict[str, float] = {}
    for b in plan.buckets:
        import numpy as np
        fmt = wire.resolve_format(pol(b.nbytes, b.dtype, axis_name),
                                  b.dtype, axis_name, op)
        m = wire.modeled_wire_bytes(sum(b.sizes),
                                    np.dtype(b.dtype).itemsize, fmt,
                                    axis_sizes)
        total += m["bottleneck"]
        per_format[fmt] = per_format.get(fmt, 0.0) + m["bottleneck"]
        for fabric, v in m["per_fabric"].items():
            per_fabric[fabric] = per_fabric.get(fabric, 0.0) + v
    return {"bottleneck": int(total),
            "per_fabric": {k: int(v) for k, v in sorted(per_fabric.items())},
            "per_format": {k: int(v) for k, v in sorted(per_format.items())}}


def compiled_flops(fn, *args, **kwargs) -> Optional[float]:
    """FLOPs of one call of ``fn(*args)`` from XLA's own
    ``cost_analysis()`` where the backend provides it (jit lower ->
    compile -> cost_analysis), None otherwise — callers fall back to the
    6·N analytical model (``train_flops_per_token``), which stays the
    single convention the MFU numbers are defined by."""
    try:
        import jax
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else None
        if not ca:
            return None
        flops = ca.get("flops")
        return float(flops) if flops and flops > 0 else None
    except Exception:
        return None
