"""Elastic state: commit/restore/sync protocol.

Mirrors the reference's elastic State machinery (reference:
horovod/common/elastic.py:99-150 State.save/restore/commit/sync;
torch/elastic/state.py:27-140 per-type handlers): user state (params,
optimizer state, epoch/batch counters) is snapshotted on ``commit()``,
restored after a hard reset, and broadcast from the new rank 0 on
``sync()`` so late joiners converge.

TPU caveat (SURVEY.md §7 hard part (c)): losing a chip usually kills the
whole slice process, so a hard reset often means process restart — state
therefore optionally persists to a host-local file on commit
(``commit_to_disk``), which the reference leaves to user checkpoints.
"""

from __future__ import annotations

import copy
import os
import pickle
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..common.exceptions import HostsUpdatedInterrupt


class State:
    """Base elastic state (reference: common/elastic.py:99-150)."""

    def __init__(self, **kwargs: Any):
        self._saved: Dict[str, Any] = {}
        self._host_updated: Callable[[], bool] = lambda: False
        self._reset_callbacks: list = []
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._fields = list(kwargs.keys())

    # -- reset plumbing -----------------------------------------------------
    def register_host_update_check(self, fn: Callable[[], bool]) -> None:
        self._host_updated = fn

    def register_reset_callbacks(self, callbacks) -> None:
        """Callbacks invoked after every elastic reset, before training
        resumes (reference: common/elastic.py State.register_reset_callbacks
        — the canonical use is rescaling the LR to the new world size)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        """Run registered reset callbacks (called by the @run wrapper after
        a hard or soft reset re-formed the mesh)."""
        for cb in self._reset_callbacks:
            cb()

    def check_host_updates(self) -> None:
        """Raise HostsUpdatedInterrupt when membership changed (reference:
        common/elastic.py:60-97)."""
        if self._host_updated():
            raise HostsUpdatedInterrupt()

    # -- snapshot protocol --------------------------------------------------
    def save(self) -> None:
        for f in self._fields:
            self._saved[f] = copy.deepcopy(getattr(self, f))

    def restore(self) -> None:
        for f, v in self._saved.items():
            setattr(self, f, copy.deepcopy(v))

    def commit(self) -> None:
        """Snapshot + host-update checkpoint boundary (reference:
        common/elastic.py:118-131: commit then check_host_updates)."""
        self.save()
        self.on_commit()
        self.check_host_updates()

    def on_commit(self) -> None:
        """Hook for subclasses (disk persistence etc.)."""

    def sync(self) -> None:
        """Broadcast state from rank 0 so all workers agree (reference:
        broadcast-based sync, tensorflow/elastic.py:31-90).  Base class has
        nothing to broadcast but must still snapshot: a hard reset right
        after sync() must roll back to this point."""
        self.save()


class ObjectState(State):
    """Arbitrary picklable attributes, synced via broadcast_object
    (reference: horovod/common/elastic.py ObjectState)."""

    def sync(self) -> None:
        from ..functions import broadcast_object
        values = {f: getattr(self, f) for f in self._fields}
        values = broadcast_object(values, root_rank=0)
        for f, v in values.items():
            setattr(self, f, v)
        self.save()


class JaxState(State):
    """Elastic state for jax training: params/opt_state pytrees + scalars.

    The analog of TorchState's model/optimizer handlers (reference:
    torch/elastic/state.py:27-140).  Pytrees are synced leaf-wise with
    broadcast (root 0); plain attributes via broadcast_object.
    """

    PYTREE_FIELDS = ("params", "opt_state")

    def __init__(self, params: Any = None, opt_state: Any = None,
                 commit_path: Optional[str] = None,
                 sharded_commit_dir: Optional[str] = None,
                 **scalars: Any):
        self.commit_path = commit_path
        # Orbax-backed sharded commits: every host writes ITS HBM shards in
        # parallel instead of pickling a full host copy (the scalable path
        # SURVEY §5 calls for; commit_path's pickle stays for tiny states).
        self.sharded_commit_dir = sharded_commit_dir
        self._ckpt_mgr = None
        self._commit_step = 0
        super().__init__(params=params, opt_state=opt_state, **scalars)

    def sync(self) -> None:
        from ..functions import broadcast_parameters, broadcast_object
        if self.params is not None:
            self.params = broadcast_parameters(self.params, root_rank=0)
        if self.opt_state is not None:
            self.opt_state = broadcast_parameters(self.opt_state,
                                                  root_rank=0)
        scalars = {f: getattr(self, f) for f in self._fields
                   if f not in ("params", "opt_state")}
        if scalars:
            synced = broadcast_object(scalars, root_rank=0)
            for k, v in synced.items():
                setattr(self, k, v)
        self.save()

    def _manager(self):
        if self._ckpt_mgr is None:
            from ..checkpoint import CheckpointManager
            self._ckpt_mgr = CheckpointManager(self.sharded_commit_dir,
                                               max_to_keep=2)
        return self._ckpt_mgr

    def on_commit(self) -> None:
        if self.sharded_commit_dir:
            scalars = {f: getattr(self, f) for f in self._fields
                       if f not in ("params", "opt_state")}
            mgr = self._manager()
            mgr.save(self._commit_step, params=self.params,
                     opt_state=self.opt_state, meta=scalars, force=True)
            # commit() promises durability: a preemption right after this
            # call must restore THIS step, so flush the async writers.
            mgr.wait()
            self._commit_step += 1
        if self.commit_path:
            tmp = self.commit_path + ".tmp"
            with open(tmp, "wb") as f:
                host_state = {
                    f2: jax.tree_util.tree_map(np.asarray, getattr(self, f2))
                    for f2 in self._fields}
                pickle.dump(host_state, f)
            os.replace(tmp, self.commit_path)

    def load_from_disk(self) -> bool:
        """Restore a commit written by a previous incarnation of this
        process (TPU slice restart path).  The sharded orbax commit wins
        when both stores exist; the current params/opt_state act as the
        restore templates (shapes + shardings)."""
        if self.sharded_commit_dir:
            mgr = self._manager()
            step = mgr.latest_step()
            if step is not None:
                out = mgr.restore(step, params=self.params,
                                  opt_state=self.opt_state)
                if "params" in out:
                    self.params = out["params"]
                if "opt_state" in out:
                    self.opt_state = out["opt_state"]
                for k, v in (out.get("meta") or {}).items():
                    setattr(self, k, v)
                self._commit_step = step + 1
                self.save()
                return True
        if not (self.commit_path and os.path.exists(self.commit_path)):
            return False
        with open(self.commit_path, "rb") as f:
            host_state = pickle.load(f)
        for k, v in host_state.items():
            setattr(self, k, v)
        self.save()
        return True
