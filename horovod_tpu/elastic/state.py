"""Elastic state: commit/restore/sync protocol.

Mirrors the reference's elastic State machinery (reference:
horovod/common/elastic.py:99-150 State.save/restore/commit/sync;
torch/elastic/state.py:27-140 per-type handlers): user state (params,
optimizer state, epoch/batch counters) is snapshotted on ``commit()``,
restored after a hard reset, and broadcast from the new rank 0 on
``sync()`` so late joiners converge.

TPU caveat (SURVEY.md §7 hard part (c)): losing a chip usually kills the
whole slice process, so a hard reset often means process restart — state
therefore optionally persists to a host-local file on commit
(``commit_to_disk``), which the reference leaves to user checkpoints.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..common.exceptions import HostsUpdatedInterrupt
from ..utils import metrics as _metrics


class State:
    """Base elastic state (reference: common/elastic.py:99-150)."""

    def __init__(self, **kwargs: Any):
        self._saved: Dict[str, Any] = {}
        self._host_updated: Callable[[], bool] = lambda: False
        self._reset_callbacks: list = []
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._fields = list(kwargs.keys())

    # -- reset plumbing -----------------------------------------------------
    def register_host_update_check(self, fn: Callable[[], bool]) -> None:
        self._host_updated = fn

    def register_reset_callbacks(self, callbacks) -> None:
        """Callbacks invoked after every elastic reset, before training
        resumes (reference: common/elastic.py State.register_reset_callbacks
        — the canonical use is rescaling the LR to the new world size)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        """Run registered reset callbacks (called by the @run wrapper after
        a hard or soft reset re-formed the mesh)."""
        for cb in self._reset_callbacks:
            cb()

    def check_host_updates(self) -> None:
        """Raise HostsUpdatedInterrupt when membership changed (reference:
        common/elastic.py:60-97)."""
        if self._host_updated():
            raise HostsUpdatedInterrupt()

    # -- snapshot protocol --------------------------------------------------
    def save(self) -> None:
        for f in self._fields:
            self._saved[f] = copy.deepcopy(getattr(self, f))

    def restore(self) -> None:
        _metrics.ELASTIC_RESTORES.inc()
        for f, v in self._saved.items():
            setattr(self, f, copy.deepcopy(v))

    def commit(self) -> None:
        """Snapshot + host-update checkpoint boundary (reference:
        common/elastic.py:118-131: commit then check_host_updates)."""
        t0 = time.monotonic()
        self.save()
        self.on_commit()
        _metrics.ELASTIC_COMMITS.inc()
        _metrics.ELASTIC_COMMIT_DURATION.observe(time.monotonic() - t0)
        self.check_host_updates()

    def on_commit(self) -> None:
        """Hook for subclasses (disk persistence etc.)."""

    def sync(self) -> None:
        """Broadcast state from rank 0 so all workers agree (reference:
        broadcast-based sync, tensorflow/elastic.py:31-90).  Base class has
        nothing to broadcast but must still snapshot: a hard reset right
        after sync() must roll back to this point."""
        self.save()


class ObjectState(State):
    """Arbitrary picklable attributes, synced via broadcast_object
    (reference: horovod/common/elastic.py ObjectState)."""

    def sync(self) -> None:
        from ..functions import broadcast_object
        values = {f: getattr(self, f) for f in self._fields}
        values = broadcast_object(values, root_rank=0)
        for f, v in values.items():
            setattr(self, f, v)
        self.save()


class JaxState(State):
    """Elastic state for jax training: params/opt_state pytrees + scalars.

    The analog of TorchState's model/optimizer handlers (reference:
    torch/elastic/state.py:27-140).  Pytrees are synced leaf-wise with
    broadcast (root 0); plain attributes via broadcast_object.
    """

    PYTREE_FIELDS = ("params", "opt_state")

    def __init__(self, params: Any = None, opt_state: Any = None,
                 commit_path: Optional[str] = None,
                 sharded_commit_dir: Optional[str] = None,
                 commit_format: str = "fast",
                 **scalars: Any):
        self.commit_path = commit_path
        # Sharded commits: every host writes ITS HBM shards in parallel
        # instead of pickling a full host copy (the scalable path SURVEY
        # §5 calls for; commit_path's pickle stays for tiny states).
        # "fast" = raw shard blobs (fastcommit.py) restoring at disk
        # speed for the same-topology restart this path serves;
        # "orbax" = the portable tensorstore layout (also readable after
        # topology changes via checkpoint.CheckpointManager).
        self.sharded_commit_dir = sharded_commit_dir
        if commit_format not in ("fast", "orbax"):
            raise ValueError(f"unknown commit_format {commit_format!r}")
        self.commit_format = commit_format
        self._ckpt_mgr = None
        self._fast_store = None
        self._commit_step = 0
        super().__init__(params=params, opt_state=opt_state, **scalars)

    def sync(self) -> None:
        from ..functions import broadcast_parameters, broadcast_object
        if self.params is not None:
            self.params = broadcast_parameters(self.params, root_rank=0)
        if self.opt_state is not None:
            self.opt_state = broadcast_parameters(self.opt_state,
                                                  root_rank=0)
        scalars = {f: getattr(self, f) for f in self._fields
                   if f not in ("params", "opt_state")}
        if scalars:
            synced = broadcast_object(scalars, root_rank=0)
            for k, v in synced.items():
                setattr(self, k, v)
        self.save()

    def _manager(self):
        if self._ckpt_mgr is None:
            from ..checkpoint import CheckpointManager
            self._ckpt_mgr = CheckpointManager(self.sharded_commit_dir,
                                               max_to_keep=2)
        return self._ckpt_mgr

    def _fast(self):
        if self._fast_store is None:
            from .fastcommit import FastCommitStore
            # Subdir: orbax's manager scans sharded_commit_dir for ITS
            # step layout and must not trip over the raw step_N dirs.
            self._fast_store = FastCommitStore(
                os.path.join(self.sharded_commit_dir, "fastcommit"),
                max_to_keep=2)
        return self._fast_store


    def _orbax_steps_may_exist(self) -> bool:
        """Cheap listdir check for orbax's numeric step dirs, so the
        fast path never pays the orbax import just to learn there are no
        orbax commits."""
        try:
            return any(n.split(".")[0].isdigit()
                       for n in os.listdir(self.sharded_commit_dir))
        except OSError:
            return False

    def _agreed_restore_plan(self, fast):
        """(fast_step, use_fast, agreed_orbax_step) — decided from the
        SAME data on every process, in ONE gather (the restart path is
        latency-sensitive and split rounds widen the pre-bring-up
        fallback window).

        Rules, applied identically everywhere: the agreed fast step is
        the newest commit EVERY host holds (per-host markers can
        disagree after a mid-commit preemption — restoring different
        steps would diverge params and loop counters); an orbax store
        the hosts DISAGREE about (a replaced host sees no or different
        steps) is unusable, since its collective restore would hang or
        diverge; between the stores the newest commit wins by the max
        timestamp any host observed, with exact timestamp ties
        (coarse-mtime filesystems) breaking toward the configured
        commit_format."""
        own_steps = {s: fast.marker_mtime(s) for s in fast.steps()}
        orbax_step = orbax_t = None
        if self._orbax_steps_may_exist():
            # Fast-only deployments skip the orbax import + manager
            # construction entirely on this latency-sensitive path.
            mgr = self._manager()
            orbax_step = mgr.latest_step()
            orbax_t = (mgr.step_mtime(orbax_step)
                       if orbax_step is not None else None)
        local = (own_steps, orbax_step, orbax_t)
        views = [local]
        if jax.process_count() > 1:
            try:
                from ..functions import allgather_object
                views = allgather_object(local)
            except Exception:
                pass  # pre-bring-up: own view is the best available
        common = set(views[0][0])
        for v in views[1:]:
            common &= set(v[0])
        fast_step = max(common) if common else None
        orbax_views = {v[1] for v in views}
        agreed_orbax = (orbax_step if orbax_views == {orbax_step}
                        and orbax_step is not None else None)
        if fast_step is None:
            return None, False, agreed_orbax
        if agreed_orbax is None:
            return fast_step, True, None
        max_fast_t = max((v[0].get(fast_step) or 0) for v in views)
        max_orbax_t = max((v[2] for v in views if v[2] is not None),
                          default=0)
        if max_fast_t == max_orbax_t:
            return fast_step, self.commit_format == "fast", agreed_orbax
        return fast_step, max_fast_t > max_orbax_t, agreed_orbax

    def on_commit(self) -> None:
        if self.sharded_commit_dir:
            scalars = {f: getattr(self, f) for f in self._fields
                       if f not in ("params", "opt_state")}
            if self.commit_format == "fast":
                # Durable on return (tmp+rename+marker inside).
                self._fast().save(self._commit_step,
                                  {"params": self.params,
                                   "opt_state": self.opt_state},
                                  meta=scalars)
            else:
                mgr = self._manager()
                mgr.save(self._commit_step, params=self.params,
                         opt_state=self.opt_state, meta=scalars,
                         force=True)
                # commit() promises durability: a preemption right after
                # this call must restore THIS step, so flush the async
                # writers.
                mgr.wait()
            self._commit_step += 1
        if self.commit_path:
            tmp = self.commit_path + ".tmp"
            with open(tmp, "wb") as f:
                host_state = {
                    f2: jax.tree_util.tree_map(np.asarray, getattr(self, f2))
                    for f2 in self._fields}
                pickle.dump(host_state, f)
            os.replace(tmp, self.commit_path)

    @staticmethod
    def _all_hosts_agree(ok: bool) -> bool:
        """All-or-nothing on a local outcome: one host restoring while a
        peer fails would diverge params and hang the next collective."""
        if jax.process_count() > 1:
            try:
                from ..functions import allgather_object
                return all(allgather_object(bool(ok)))
            except Exception:
                pass  # pre-bring-up: local outcome is the best available
        return ok

    def _apply_restored(self, out: Dict[str, Any], step: int) -> None:
        if out.get("params") is not None:
            self.params = out["params"]
        if out.get("opt_state") is not None:
            self.opt_state = out["opt_state"]
        for k, v in (out.get("meta") or {}).items():
            setattr(self, k, v)
        self._commit_step = step + 1
        self.save()

    def _restore_fast(self, fast, step: int) -> bool:
        out = fast.restore(step, {"params": self.params,
                                  "opt_state": self.opt_state})
        if not self._all_hosts_agree(out is not None):
            return False
        self._apply_restored(out, step)
        return True

    def _restore_orbax(self, step: int) -> bool:
        try:
            out = self._manager().restore(step, params=self.params,
                                          opt_state=self.opt_state)
        except Exception:
            # Unmappable commit (templates changed shape/dtype/
            # structure): report a failed load, per the load_from_disk
            # contract — the caller decides, it must not crash here.
            out = None
        if not self._all_hosts_agree(out is not None):
            return False
        self._apply_restored(out, step)
        return True

    def load_from_disk(self) -> bool:
        """Restore a commit written by a previous incarnation of this
        process (TPU slice restart path).  The current params/opt_state
        act as the restore templates (shapes + shardings).

        Precedence: the NEWEST durable commit wins, judged by commit
        wall-clock across both sharded stores (step counters restart per
        incarnation, so they cannot order commits across stores).  If
        that newest commit cannot be restored — typically a fast commit
        after a topology or dtype change — load_from_disk does NOT fall
        back to an older commit (silently rolling training back is worse
        than reporting failure); it returns False and the caller decides
        (cold-start from a real checkpoint via
        checkpoint.CheckpointManager, or the commit_path pickle if
        configured, which is consulted last and carries the same commit
        freshness)."""
        if self.sharded_commit_dir:
            fast = self._fast()
            fast_step, use_fast, agreed_orbax = \
                self._agreed_restore_plan(fast)
            if use_fast:
                if self._restore_fast(fast, fast_step):
                    return True
                # Newest commit unrestorable (topology/dtype change):
                # older orbax steps stay off-limits; only the pickle
                # below (same commit freshness) may still serve.
            elif agreed_orbax is not None:
                if self._restore_orbax(agreed_orbax):
                    return True
                # Same rule as above: no rollback to older commits;
                # fall through to the pickle.
        host_state = None
        if self.commit_path and os.path.exists(self.commit_path):
            try:
                with open(self.commit_path, "rb") as f:
                    host_state = pickle.load(f)
            except Exception:
                host_state = None
        # The pickle has no sharding metadata, but it must not resurrect
        # state the validating stores just refused: any live template is
        # a layout contract (structure + shapes + dtypes) here too.
        ok = host_state is not None and all(
            self._pickle_matches_template(getattr(self, name, None),
                                          host_state.get(name))
            for name in self.PYTREE_FIELDS)
        # Same all-or-nothing rule as the sharded stores: one host
        # loading its pickle while a peer's is missing/mismatched would
        # diverge.  (Hosts whose pickles hold different commit points
        # converge at the sync() that follows restore — rank 0
        # broadcasts.)
        if not self._all_hosts_agree(ok):
            return False
        for k, v in host_state.items():
            setattr(self, k, v)
        self.save()
        return True

    @staticmethod
    def _pickle_matches_template(template: Any, stored: Any) -> bool:
        """No template (None) accepts anything; otherwise the stored
        tree must match the template leaf-for-leaf in shape and dtype."""
        if template is None or stored is None:
            return True
        t_leaves, t_def = jax.tree_util.tree_flatten(template)
        s_leaves, s_def = jax.tree_util.tree_flatten(stored)
        if t_def != s_def or len(t_leaves) != len(s_leaves):
            return False
        for t, s in zip(t_leaves, s_leaves):
            if tuple(np.shape(t)) != tuple(np.shape(s)):
                return False
            if np.dtype(getattr(t, "dtype", np.asarray(t).dtype)) != \
                    np.dtype(getattr(s, "dtype", np.asarray(s).dtype)):
                return False
        return True
