"""Worker-side elastic plumbing: host-update notifications + the run()
wrapper.

Reference: the WorkerNotificationManager listens for HostsUpdatedRequest
(reference: horovod/runner/elastic/worker.py:32-119) and the
`@hvd.elastic.run` decorator implements the reset loop (reference:
horovod/common/elastic.py:151-175):

  loop:
    state.sync() after (re)init
    try: user train fn
    except HorovodInternalError: hard reset — shutdown, re-rendezvous,
        re-init, state.restore()
    except HostsUpdatedInterrupt: soft reset — keep live state, re-sync.

Host updates arrive via the rendezvous KV store (the driver bumps a
counter key) instead of a per-worker socket service.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Optional

from ..common import hvdlogging as log
from ..common.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..common.knobs import Knobs
from ..runner.http_client import get_kv

HOST_UPDATE_SCOPE = "elastic"
HOST_UPDATE_KEY = "host_update_counter"


class WorkerNotificationManager:
    """Polls the rendezvous KV for membership-change bumps (reference:
    worker.py:46-118, transport swapped for the KV store)."""

    def __init__(self, addr: Optional[str] = None,
                 port: Optional[int] = None,
                 poll_interval: float = 1.0):
        knobs = Knobs()
        self.addr = addr if addr is not None else \
            knobs["HOROVOD_RENDEZVOUS_ADDR"]
        self.port = port if port is not None else \
            knobs["HOROVOD_RENDEZVOUS_PORT"]
        self.poll_interval = poll_interval
        self._last_seen = self._read()
        self._updated = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.addr and self.port:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def _read(self) -> int:
        if not (self.addr and self.port):
            return 0
        try:
            v = get_kv(self.addr, self.port, HOST_UPDATE_SCOPE,
                       HOST_UPDATE_KEY, timeout=0)  # poll, never block
            return int(v) if v else 0
        except Exception:
            return 0

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            if self._read() > self._last_seen:
                self._updated.set()

    def host_updated(self) -> bool:
        return self._updated.is_set()

    def acknowledge(self) -> None:
        self._last_seen = self._read()
        self._updated.clear()

    def stop(self) -> None:
        self._stop.set()


def run(func: Callable) -> Callable:
    """``@hvd.elastic.run`` (reference: common/elastic.py:151-175).

    The wrapped function must take the elastic ``state`` as its first
    argument.  On TPU, a hard reset usually arrives as a process restart
    (slice loss); in-process HorovodInternalError still gets the
    shutdown/re-init/restore treatment for surviving processes.
    """
    @functools.wraps(func)
    def wrapper(state, *args: Any, **kwargs: Any):
        from .. import chaos as _chaos
        from .. import runtime as _rt
        # Chaos plane: make sure this rank's injector exists even when the
        # wrapped fn runs before hvd.init() (spec distributed by the
        # elastic driver's rendezvous; see docs/chaos.md).  Training loops
        # call hvd.chaos.step(i) to give kill/stall events a step clock.
        _chaos.ensure_installed()
        notifier = WorkerNotificationManager()
        state.register_host_update_check(notifier.host_updated)
        reset_limit = Knobs()["HOROVOD_ELASTIC_RESET_LIMIT"]
        resets = 0
        state.sync()
        while True:
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError as e:
                log.warning("elastic: hard reset after internal error: %s",
                            e)
                _rt.shutdown()
                _rt.init()
                state.restore()
            except HostsUpdatedInterrupt:
                log.info("elastic: soft reset (hosts updated)")
                notifier.acknowledge()
            resets += 1
            if reset_limit and resets > reset_limit:
                raise RuntimeError(
                    f"elastic reset limit {reset_limit} exceeded "
                    "(reference: --reset-limit semantics)")
            if hasattr(state, "on_reset"):
                state.on_reset()  # user hooks, e.g. LR rescale to new size
            state.sync()
    return wrapper
