"""Raw shard-file commit store: the fast path for elastic durable commits.

Why not orbax here: profiling (scripts/profile_restore.py, docs/
benchmarks.md) pinned elastic restore at 3-8x slower than save at every
orbax knob setting — tensorstore's read+decompress+place pipeline is
chunk-serial per array.  The reference's bar is an in-memory broadcast
(reference: horovod/common/elastic.py:99-150), near-instant; a restart
restore that takes minutes at pod scale defeats elastic's purpose.

The elastic restart path needs none of orbax's generality: the SAME
process layout that wrote the commit restores it (a TPU slice restart
reuses the topology), templates with the target shardings are in hand,
and the files are host-local.  So each process writes its addressable
shards as ONE flat binary blob plus a manifest, and restores them with a
thread pool — zero-copy reads from an mmap, one device_put per shard,
no codec in between.  Cross-topology moves stay on the orbax path
(checkpoint.CheckpointManager); `restore` detects a layout mismatch and
returns None so callers can fall back.

Durability protocol: data + manifest land via tmp+rename, then a marker
file; a step without this process's marker is ignored at restore, so a
crash mid-commit can never be read back (the same promise State.commit
documents).
"""

from __future__ import annotations

import mmap
import os
import pickle
import re
import shutil
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)$")


def _chaos_crash(point: str, step: int) -> None:
    """Chaos crash hooks at the durability protocol's exact weak spots
    (between data and marker): a crash_commit event hard-exits here, and
    the restore path must never see the torn step (docs/chaos.md)."""
    from .. import chaos
    chaos.crash_point(point, step)


def _open_in_step_dir(d: str, path: str):
    """open(path, 'wb') that survives a peer racing the directory away:
    a sibling host's purge/GC may rmdir a just-created (still empty)
    step dir between our makedirs and the first open — re-create and
    retry once.  Own files are never touched by peers, so only the
    directory can vanish."""
    try:
        return open(path, "wb")
    except FileNotFoundError:
        os.makedirs(d, exist_ok=True)
        return open(path, "wb")


def _pwrite_all(fd: int, buf, offset: int) -> None:
    """pwrite the WHOLE buffer: a single pwrite may write short (and is
    capped at ~2 GiB on Linux), which would leave silent zero tails in
    the pre-truncated file."""
    view = memoryview(buf).cast("B")
    while len(view):
        n = os.pwrite(fd, view, offset)
        view = view[n:]
        offset += n


def _index_spec(index) -> Tuple:
    """A shard's global index (tuple of slices) as plain picklable data."""
    return tuple((s.start, s.stop, s.step) for s in index)


def _leaf_shards(leaf) -> List[Tuple[Tuple, Any]]:
    """(index_spec, device_shard) for every DISTINCT shard this process
    owns — no host copy yet; the save workers materialize each shard so
    D2H copies pipeline with the writes.  Replicated axes give several
    devices the same global index — write one copy, not one per replica
    (DP-replicated params would otherwise blow the commit up by the
    replica count)."""
    if hasattr(leaf, "addressable_shards"):
        out, seen = [], set()
        for s in leaf.addressable_shards:
            spec = _index_spec(s.index)
            if spec not in seen:
                seen.add(spec)
                out.append((spec, s.data))
        return out
    arr = np.asarray(leaf)
    full = tuple((0, n, None) for n in arr.shape)
    return [(full, arr)]


class FastCommitStore:
    """Per-host raw shard blobs with a manifest; same-layout restore."""

    def __init__(self, directory: str, max_to_keep: int = 2,
                 fsync: bool = False):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        # fsync=True survives a whole-MACHINE crash but costs physical
        # disk bandwidth per commit (~8x at 1 GB scale).  The elastic
        # failure mode this store serves is a killed/preempted PROCESS,
        # where the page cache survives — and the reference's bar is
        # in-memory state that survives neither.  tmp+rename ordering is
        # kept either way, so a torn commit is never visible.
        self.fsync = fsync
        self._proc = jax.process_index()
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------------ save
    def save(self, step: int, trees: Dict[str, Any],
             meta: Optional[Dict[str, Any]] = None) -> None:
        """Write this host's shards of every leaf; durable on return."""
        # A commit counter that restarted below steps still on disk
        # (failed/skipped load_from_disk) begins a NEW timeline: the
        # stale higher-numbered steps would both shadow latest_step()
        # and make _gc delete the commit being written, so purge them
        # before anything else.  Markerless leftovers of a crashed
        # commit (data written, marker not) are invisible to steps() but
        # hold state-sized blobs — reap those too.
        for s in self.steps():
            if s >= step:
                self._remove_step(s)
        self._purge_incomplete()
        d = os.path.join(self.directory, f"step_{step}")
        os.makedirs(d, exist_ok=True)
        manifest: Dict[str, Any] = {
            "process_index": self._proc,
            "process_count": jax.process_count(),
            "meta": meta or {},
            "trees": {},
        }
        # Lay out every shard's byte range from metadata only (shape +
        # dtype come without a host copy), so the workers below can
        # pipeline the device->host copy of one shard with the pwrite of
        # another, and resident host memory stays bounded by the
        # in-flight window rather than the whole state.
        jobs = []  # (offset, device_shard)
        offset = 0
        for name, tree in trees.items():
            if tree is None:
                manifest["trees"][name] = None
                continue
            leaves = jax.tree_util.tree_leaves(tree)
            entries = []
            for leaf in leaves:
                shards = []
                for spec, data in _leaf_shards(leaf):
                    shape = tuple(data.shape)
                    dt = np.dtype(data.dtype)
                    nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
                    shards.append({"index": spec, "offset": offset,
                                   "nbytes": nbytes, "shape": shape,
                                   "dtype": str(dt)})
                    jobs.append((offset, data))
                    offset += nbytes
                gshape = tuple(getattr(leaf, "shape", np.shape(leaf)))
                entries.append({"gshape": gshape, "shards": shards})
            manifest["trees"][name] = entries

        data_path = os.path.join(d, f"host_{self._proc}.bin")
        tmp = data_path + ".tmp"
        with _open_in_step_dir(d, tmp) as f:
            f.truncate(offset)
            fd = f.fileno()
            def write_shard(job):
                off, data = job
                host = np.ascontiguousarray(np.asarray(data))
                # uint8 view: numpy's buffer protocol refuses extension
                # dtypes (bfloat16/fp8 — the usual TPU dtypes), so the
                # raw bytes go out under a dtype it always accepts.
                _pwrite_all(fd, host.reshape(-1).view(np.uint8), off)

            with ThreadPoolExecutor(max_workers=8) as pool:
                list(pool.map(write_shard, jobs))
            f.flush()
            if self.fsync:
                os.fsync(fd)
        os.replace(tmp, data_path)
        _chaos_crash("fastcommit.pre_manifest", step)

        man_path = os.path.join(d, f"host_{self._proc}.manifest")
        with open(man_path + ".tmp", "wb") as f:
            pickle.dump(manifest, f)
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(man_path + ".tmp", man_path)
        _chaos_crash("fastcommit.pre_marker", step)
        # The marker is what restore trusts; everything above is invisible
        # until it exists.
        marker = os.path.join(d, f"COMMIT_{self._proc}")
        with open(marker, "w") as f:
            f.write("ok")
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        if self.fsync:
            # The renames and the marker are directory entries; without a
            # directory fsync a machine crash can lose them even though
            # the data blocks are on disk.
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        self._gc()

    def _remove_step(self, s: int) -> None:
        d = os.path.join(self.directory, f"step_{s}")
        # Marker FIRST: a kill mid-removal must leave the step
        # invisible, never marker-bearing with missing data.
        for fn in (f"COMMIT_{self._proc}",
                   f"host_{self._proc}.bin",
                   f"host_{self._proc}.manifest"):
            try:
                os.remove(os.path.join(d, fn))
            except OSError:
                pass
        try:  # last host out removes the dir
            os.rmdir(d)
        except OSError:
            pass

    def _purge_incomplete(self) -> None:
        """Remove this process's files from step dirs that lack its
        durability marker: leftovers of a commit that crashed between
        data and marker.  Only our own files — another process may be
        mid-commit in the same dir."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for n in names:
            if not _STEP_RE.match(n):
                continue
            d = os.path.join(self.directory, n)
            if os.path.exists(os.path.join(d, f"COMMIT_{self._proc}")):
                continue
            for fn in (f"host_{self._proc}.bin",
                       f"host_{self._proc}.bin.tmp",
                       f"host_{self._proc}.manifest",
                       f"host_{self._proc}.manifest.tmp"):
                try:
                    os.remove(os.path.join(d, fn))
                except OSError:
                    pass
            try:
                os.rmdir(d)
            except OSError:
                pass

    def _gc(self) -> None:
        steps = sorted(self.steps())
        for s in steps[:-self.max_to_keep] if self.max_to_keep else []:
            self._remove_step(s)

    # --------------------------------------------------------------- restore
    def steps(self) -> List[int]:
        """Steps with THIS process's durability marker."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for n in names:
            m = _STEP_RE.match(n)
            if m and os.path.exists(os.path.join(
                    self.directory, n, f"COMMIT_{self._proc}")):
                out.append(int(m.group(1)))
        return out

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return max(steps) if steps else None

    def marker_mtime(self, step: int) -> Optional[float]:
        """When this process's commit of `step` became durable (used to
        order commits ACROSS stores, where step counters don't share a
        timeline)."""
        try:
            return os.path.getmtime(os.path.join(
                self.directory, f"step_{step}", f"COMMIT_{self._proc}"))
        except OSError:
            return None

    def restore(self, step: int, templates: Dict[str, Any]
                ) -> Optional[Dict[str, Any]]:
        """Rebuild the committed trees onto the templates' shardings.

        Returns None when the commit cannot be mapped onto the current
        layout (different process count, leaf count, shapes, or shard
        partitioning) — the caller falls back to a portable path.
        """
        d = os.path.join(self.directory, f"step_{step}")
        man_path = os.path.join(d, f"host_{self._proc}.manifest")
        data_path = os.path.join(d, f"host_{self._proc}.bin")
        if not (os.path.exists(os.path.join(d, f"COMMIT_{self._proc}"))
                and os.path.exists(man_path)
                and os.path.exists(data_path)):
            return None
        try:
            with open(man_path, "rb") as f:
                manifest = pickle.load(f)
        except (OSError, EOFError, pickle.UnpicklingError, ValueError):
            return None  # corrupt commit: let the caller fall back
        if manifest["process_count"] != jax.process_count():
            return None

        f = open(data_path, "rb")
        try:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:  # zero-length data file (empty trees)
            mm = b""
        view = memoryview(mm)

        def build_leaf(tmpl, entry):
            """One leaf: place every shard, assemble the global array."""
            if tuple(entry["gshape"]) != tuple(
                    getattr(tmpl, "shape", np.shape(tmpl))):
                raise _LayoutMismatch()
            tmpl_dtype = np.dtype(getattr(tmpl, "dtype",
                                          np.asarray(tmpl).dtype))
            if any(np.dtype(sh["dtype"]) != tmpl_dtype
                   for sh in entry["shards"]):
                # A precision change is a layout change: silently
                # restoring the old dtype would retrace or train wrong.
                raise _LayoutMismatch()
            sharding = getattr(tmpl, "sharding", None)
            raw = {}
            for sh in entry["shards"]:
                buf = np.frombuffer(
                    view[sh["offset"]:sh["offset"] + sh["nbytes"]],
                    dtype=np.dtype(sh["dtype"])).reshape(sh["shape"])
                raw[sh["index"]] = buf
            if sharding is None or not hasattr(tmpl, "addressable_shards"):
                if len(raw) != 1:
                    raise _LayoutMismatch()
                buf = np.array(next(iter(raw.values())))
                if buf.size != int(np.prod(entry["gshape"],
                                           dtype=np.int64)):
                    raise _LayoutMismatch()
                # reshape: 0-d shards were stored as (1,)
                return buf.reshape(entry["gshape"])
            tmpl_specs = {_index_spec(s.index)
                          for s in tmpl.addressable_shards}
            if tmpl_specs != set(raw):  # replicas share one stored copy
                raise _LayoutMismatch()
            singles = []
            for s in tmpl.addressable_shards:
                buf = raw[_index_spec(s.index)]
                # Compare by element count: ascontiguousarray at save
                # time renders 0-d shards as (1,), so shapes can differ
                # spuriously while the data is identical.
                if buf.size != int(np.prod(s.data.shape)):
                    raise _LayoutMismatch()
                singles.append(jax.device_put(
                    buf.reshape(tuple(s.data.shape)), s.device))
            return jax.make_array_from_single_device_arrays(
                tuple(entry["gshape"]), sharding, singles)

        out: Dict[str, Any] = {"meta": manifest.get("meta") or {}}
        try:
            for name, tmpl_tree in templates.items():
                entries = manifest["trees"].get(name)
                if entries is None or tmpl_tree is None:
                    out[name] = None
                    continue
                leaves, treedef = jax.tree_util.tree_flatten(tmpl_tree)
                if len(leaves) != len(entries):
                    raise _LayoutMismatch()
                with ThreadPoolExecutor(max_workers=8) as pool:
                    rebuilt = list(pool.map(build_leaf, leaves, entries))
                out[name] = jax.tree_util.tree_unflatten(treedef, rebuilt)
            jax.block_until_ready([out[n] for n in templates
                                   if out.get(n) is not None])
        except _LayoutMismatch:
            return None
        except (ValueError, OSError, KeyError, IndexError, TypeError):
            # A marker-bearing commit with an unreadable data blob
            # (machine crash under fsync=False, disk corruption): the
            # contract is "None means fall back", never an exception —
            # and in multi-host restarts an exception here would leave
            # peers hanging in the outcome-agreement collective.
            return None
        finally:
            # Never mmap.close() here: on CPU backends device_put is
            # zero-copy, so restored arrays ALIAS the mapping — numpy's
            # buffer refs keep it (and the dup'd fd) alive exactly as
            # long as needed.  Commits never mutate old step files in
            # place, so aliased pages stay valid.  Only drop our handle.
            f.close()
        return out

    def clear(self) -> None:
        shutil.rmtree(self.directory, ignore_errors=True)


class _LayoutMismatch(Exception):
    """Commit does not map onto the live topology; use the portable path."""
