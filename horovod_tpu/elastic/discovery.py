"""Host discovery for elastic training.

Reference: horovod/runner/elastic/discovery.py — a user-provided discovery
script prints the current 'host:slots' list (discovery.py:146+); the driver
polls it and diffs against the active set; failing hosts are blacklisted
(discovery.py:80-134).
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..runner import hosts as hosts_mod


class HostDiscovery:
    def find_available_hosts(self) -> List[hosts_mod.HostInfo]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user script; one 'host[:slots]' per line (reference:
    discovery.py:146-186)."""

    def __init__(self, script_path: str, default_slots: int = 1):
        self.script_path = script_path
        self.default_slots = default_slots

    def find_available_hosts(self) -> List[hosts_mod.HostInfo]:
        out = subprocess.run([self.script_path], capture_output=True,
                             text=True, timeout=30)
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed rc={out.returncode}: "
                f"{out.stderr[:200]}")
        hosts: List[hosts_mod.HostInfo] = []
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" not in line:
                line = f"{line}:{self.default_slots}"
            hosts.append(hosts_mod.HostInfo.from_string(line))
        return hosts


class FixedHosts(HostDiscovery):
    """Static host set wrapped in the discovery interface (tests, and
    static fallback)."""

    def __init__(self, hosts: List[hosts_mod.HostInfo]):
        self._hosts = hosts

    def set(self, hosts: List[hosts_mod.HostInfo]) -> None:
        self._hosts = hosts

    def find_available_hosts(self) -> List[hosts_mod.HostInfo]:
        return list(self._hosts)


class HostManager:
    """Tracks available vs blacklisted hosts (reference:
    discovery.py:80-134 HostManager + blacklist)."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._blacklist: Set[str] = set()
        self._lock = threading.Lock()

    def blacklist(self, hostname: str) -> None:
        with self._lock:
            self._blacklist.add(hostname)

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return hostname in self._blacklist

    def current_hosts(self) -> List[hosts_mod.HostInfo]:
        hosts = self._discovery.find_available_hosts()
        with self._lock:
            return [h for h in hosts if h.hostname not in self._blacklist]

    def update_available_hosts(
            self, prev: List[hosts_mod.HostInfo]
    ) -> Tuple[List[hosts_mod.HostInfo], bool]:
        """Returns (hosts, changed)."""
        cur = self.current_hosts()
        changed = ({h.hostname: h.slots for h in cur} !=
                   {h.hostname: h.slots for h in prev})
        return cur, changed
