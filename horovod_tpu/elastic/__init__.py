"""Elastic training: fault-tolerant, dynamically-resizable jobs.

Public surface mirrors ``horovod.elastic`` (reference:
horovod/common/elastic.py, horovod/runner/elastic/*):

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    state = elastic.JaxState(params=params, opt_state=opt_state, epoch=0)

    @elastic.run
    def train(state):
        ...
        state.commit()
"""

from .state import State, ObjectState, JaxState
from .worker import run, WorkerNotificationManager
from .discovery import (HostDiscovery, HostDiscoveryScript, FixedHosts,
                        HostManager)
from .driver import ElasticDriver, WorkerStateRegistry, run_elastic

__all__ = [
    "State", "ObjectState", "JaxState", "run", "WorkerNotificationManager",
    "HostDiscovery", "HostDiscoveryScript", "FixedHosts", "HostManager",
    "ElasticDriver", "WorkerStateRegistry", "run_elastic",
]
