"""horovod_tpu.elastic subpackage."""
