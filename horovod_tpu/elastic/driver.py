"""Elastic driver: discovery polling, rank-preserving reassignment, worker
lifecycle, reset rounds.

Reference: horovod/runner/elastic/driver.py:68-314 — a poll thread watches
the discovery script (driver.py:181-202); on membership change or worker
failure the driver recomputes slot assignments *preserving existing ranks*
(driver.py:233-276), blacklists hosts whose workers failed
(registration.py:51-130), bumps the rendezvous and restarts workers; it
stops when min_np can't be met or the reset limit is hit.

TPU adaptation: a membership change requires rebuilding the jax.distributed
mesh, so every reset round restarts *all* worker processes with fresh
HOROVOD_SIZE/RANK env (the reference restarts only affected workers because
gloo can re-form in-process).  Worker state survives via
JaxState(commit_path=...) disk commits plus rank-0 broadcast on sync.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..common import hvdlogging as log
from ..common.knobs import Knobs
from ..runner import hosts as hosts_mod
from ..runner.http_server import RendezvousServer
from ..utils import metrics as _metrics
from .discovery import HostDiscovery, HostDiscoveryScript, HostManager
from .worker import HOST_UPDATE_SCOPE, HOST_UPDATE_KEY


class WorkerStateRegistry:
    """Counts worker outcomes per reset round (reference:
    registration.py:28-130)."""

    SUCCESS, FAILURE = "success", "failure"

    def __init__(self):
        self._results: Dict[int, str] = {}
        self._lock = threading.Lock()

    def record(self, rank: int, outcome: str) -> None:
        with self._lock:
            self._results[rank] = outcome

    def failures(self) -> List[int]:
        with self._lock:
            return [r for r, o in self._results.items()
                    if o == self.FAILURE]

    def successes(self) -> List[int]:
        with self._lock:
            return [r for r, o in self._results.items()
                    if o == self.SUCCESS]

    def reset(self) -> None:
        with self._lock:
            self._results.clear()


class ElasticDriver:
    def __init__(self, discovery: HostDiscovery, min_np: int, max_np: int,
                 command: List[str],
                 env: Optional[Dict[str, str]] = None,
                 elastic_timeout: float = 600.0,
                 reset_limit: int = 0,
                 coordinator_port: int = 29500,
                 controller_port: int = 29499,
                 discovery_interval: float = 1.0,
                 output_filename: Optional[str] = None,
                 network_interface: Optional[str] = None,
                 prefix_output_with_timestamp: bool = False,
                 metrics_port: Optional[int] = None,
                 kv_shards: int = 1):
        self.host_manager = HostManager(discovery)
        self.min_np = min_np
        self.max_np = max_np
        self.command = command
        self.extra_env = env or {}
        self.elastic_timeout = elastic_timeout
        self.reset_limit = reset_limit
        self.coordinator_port = coordinator_port
        self.controller_port = controller_port
        self.discovery_interval = discovery_interval
        self.output_filename = output_filename
        self.network_interface = network_interface
        self.prefix_output_with_timestamp = prefix_output_with_timestamp
        self._spawned_ranks: set = set()
        self._round = 0  # reset-round number, exported to workers

        self.registry = WorkerStateRegistry()
        # Sharded KV (docs/control-plane.md): the shard servers live in
        # THIS driver process like the primary, so they survive reset
        # rounds with the journal and in-flight client streams.
        self.kv_shards = max(1, int(kv_shards))
        self.rendezvous = RendezvousServer(port=metrics_port or 0,
                                           shards=self.kv_shards)
        self.rdv_port = self.rendezvous.start()
        self._host_update_counter = 0
        self._current_hosts: List[hosts_mod.HostInfo] = []
        self._prev_assignment: Dict[str, List[int]] = {}
        self._procs: Dict[int, subprocess.Popen] = {}
        self._stop = threading.Event()
        self._hosts_changed = threading.Event()
        self._discovery_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- discovery
    def start_discovery(self) -> None:
        """Poll thread (reference: driver.py:181-202, 1 s interval)."""
        def loop():
            while not self._stop.wait(self.discovery_interval):
                try:
                    cur, changed = self.host_manager.update_available_hosts(
                        self._current_hosts)
                except Exception as e:
                    log.warning("elastic discovery failed: %s", e)
                    continue
                if changed:
                    prev = {h.hostname for h in self._current_hosts}
                    now = {h.hostname for h in cur}
                    if now - prev:
                        _metrics.ELASTIC_HOSTS_ADDED.inc(len(now - prev))
                    if prev - now:
                        _metrics.ELASTIC_HOSTS_REMOVED.inc(len(prev - now))
                    self._current_hosts = cur
                    self._hosts_changed.set()
                    self._notify_host_update()
        self._discovery_thread = threading.Thread(target=loop, daemon=True)
        self._discovery_thread.start()

    def _notify_host_update(self) -> None:
        self._host_update_counter += 1
        self.rendezvous.put(HOST_UPDATE_SCOPE, HOST_UPDATE_KEY,
                            str(self._host_update_counter).encode())

    def wait_for_available_slots(self, min_np: int) -> List[hosts_mod.HostInfo]:
        """Block until enough slots exist (reference: driver.py:145-180)."""
        deadline = time.time() + self.elastic_timeout
        while time.time() < deadline:
            hosts = self.host_manager.current_hosts()
            if sum(h.slots for h in hosts) >= min_np:
                self._current_hosts = hosts
                return hosts
            time.sleep(0.5)
        raise TimeoutError(
            f"timed out waiting for {min_np} slots "
            f"(HOROVOD_ELASTIC_TIMEOUT={self.elastic_timeout:.0f}s)")

    # ----------------------------------------------------------- assignment
    def compute_assignments(
            self, hosts: List[hosts_mod.HostInfo]) -> List[hosts_mod.SlotInfo]:
        """Rank-preserving assignment (reference: driver.py:233-276): hosts
        that already held ranks keep their previous *order* so rank 0 (the
        broadcast root) stays on a surviving host when possible."""
        order: Dict[str, int] = {}
        for h, ranks in self._prev_assignment.items():
            if ranks:
                order[h] = min(ranks)
        hosts_sorted = sorted(
            hosts, key=lambda h: (order.get(h.hostname, 1 << 30),
                                  h.hostname))
        np_ = min(self.max_np, sum(h.slots for h in hosts_sorted))
        slots = hosts_mod.get_host_assignments(hosts_sorted, np_)
        self._prev_assignment = {}
        for s in slots:
            self._prev_assignment.setdefault(s.hostname, []).append(s.rank)
        return slots

    # -------------------------------------------------------------- workers
    def _spawn_worker(self, slot: hosts_mod.SlotInfo,
                      coord_host: str) -> subprocess.Popen:
        from ..runner.launch import build_worker_command
        updates = dict(self.extra_env)
        updates.update(slot.to_env())
        updates["HOROVOD_RENDEZVOUS_ADDR"] = coord_host
        updates["HOROVOD_RENDEZVOUS_PORT"] = str(self.rdv_port)
        updates["HOROVOD_CONTROLLER_PORT"] = str(self.controller_port)
        # Reset-round stamp: the serving plane fences its plan-stream
        # epoch on it so a restarted fleet can never replay stale
        # serve_plan keys (serve/worker.py; docs/serving.md).
        updates["HOROVOD_ELASTIC_ROUND"] = str(self._round)
        from ..runner.launch import stamp_kv_shard_env
        stamp_kv_shard_env(updates, coord_host, self.rendezvous,
                           self.kv_shards)
        if slot.size > 1:
            updates["HOROVOD_COORDINATOR_ADDR"] = \
                f"{coord_host}:{self.coordinator_port}"
        env = dict(os.environ)
        env.update(updates)
        cmd = build_worker_command(slot, self.command, updates,
                                   ssh_port=None, ssh_identity=None)
        from ..runner.launch import spawn_with_output
        # Truncate on a rank's FIRST spawn of this driver run (a stale
        # log from a previous run must not leak in); append on reset
        # rounds so a restarted rank's log continues.
        mode = "ab" if slot.rank in self._spawned_ranks else "wb"
        self._spawned_ranks.add(slot.rank)
        return spawn_with_output(
            cmd, env, self.output_filename, slot.rank, mode=mode,
            prefix_timestamp=self.prefix_output_with_timestamp)

    def _terminate_all(self) -> None:
        for p in self._procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in self._procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
            # Reset-round survivors die at the driver's hand: taxonomy
            # "terminated", never a failure attribution.
            _metrics.WORKER_EXITS.inc(cause="terminated")
            from ..runner.launch import join_output_pumps
            join_output_pumps(p, timeout=2.0)
        self._procs.clear()

    # --------------------------------------------------------- supervision
    def _health_monitor(self):
        """Per-round health supervision (the serve-plane remediation:
        a wedged engine gets SIGABRT -> elastic restart instead of job
        death).  Only armed when the heartbeat plane is on (hvdrun
        --serve implies it); a fresh monitor per round because the
        scope is cleared at every reset."""
        enabled = (self.extra_env.get("HOROVOD_HEARTBEAT")
                   or os.environ.get("HOROVOD_HEARTBEAT", ""))
        if enabled in ("", "0", "false"):
            return None
        from ..utils.health import HealthMonitor, fleet_health
        timeout = float(self.extra_env.get("HOROVOD_HEARTBEAT_TIMEOUT")
                        or os.environ.get("HOROVOD_HEARTBEAT_TIMEOUT")
                        or 10)
        return HealthMonitor(
            lambda: fleet_health(
                self.rendezvous.scope_items("health"),
                self.rendezvous.scope_receipt_times("health"),
                stale_after=timeout),
            timeout=timeout)

    # ------------------------------------------------------------------ run
    def run(self) -> int:
        """Reset-round loop (reference: driver.py run/reset +
        launch.py:621-670 semantics)."""
        self.start_discovery()
        resets = 0
        try:
            while True:
                hosts = self.wait_for_available_slots(self.min_np)
                slots = self.compute_assignments(hosts)
                from ..runner.launch import _is_local, resolve_coord_host
                coord_host = resolve_coord_host(
                    slots[0].hostname, self.network_interface,
                    warn=log.warning,
                    has_remote_workers=any(
                        not _is_local(s.hostname) for s in slots))
                if self.kv_shards > 1:
                    # Idempotent per round; coord_host can only be
                    # known once the round's slots are.
                    self.rendezvous.publish_shard_map(coord_host)
                self._hosts_changed.clear()
                self.registry.reset()
                self._round = resets
                # Round-scoped heartbeats: a dead incarnation's stale
                # entries would read as instant heartbeat-loss for the
                # ranks of the new round.
                self.rendezvous.clear_scope("health")
                health_mon = self._health_monitor()
                log.info("elastic round %d: %d workers on %s", resets,
                         len(slots),
                         ",".join(h.hostname for h in hosts))
                round_start = time.monotonic()
                self._procs = {s.rank: self._spawn_worker(s, coord_host)
                               for s in slots}

                round_failed = False
                while self._procs:
                    if health_mon is not None:
                        # Wedged-rank remediation: SIGABRT trips the
                        # armed flight recorder, the nonzero exit below
                        # classifies as a failure, and the reset round
                        # restarts the fleet (docs/serving.md).
                        for r, cause in health_mon.verdicts(
                                list(self._procs)).items():
                            p = self._procs.get(r)
                            if p is not None and p.poll() is None:
                                log.warning(
                                    "elastic: rank %d %s beyond %.0fs — "
                                    "SIGABRT for forensics, then reset",
                                    r, cause, health_mon.timeout)
                                p.send_signal(signal.SIGABRT)
                    done = [(r, p) for r, p in self._procs.items()
                            if p.poll() is not None]
                    for r, p in done:
                        del self._procs[r]
                        from ..runner.launch import join_output_pumps
                        join_output_pumps(p, timeout=5.0)
                        outcome = (WorkerStateRegistry.SUCCESS
                                   if p.returncode == 0
                                   else WorkerStateRegistry.FAILURE)
                        self.registry.record(r, outcome)
                        # Postmortem-plane exit taxonomy: every worker
                        # exit lands in hvd_worker_exits_total{cause=...}
                        # (visible at /metrics; docs/postmortem.md).
                        from ..postmortem import classify_exit
                        _metrics.WORKER_EXITS.inc(
                            cause=classify_exit(p.returncode))
                        if outcome == WorkerStateRegistry.FAILURE:
                            _metrics.ELASTIC_FAILURES.inc()
                            host = next((s.hostname for s in slots
                                         if s.rank == r), None)
                            if host:
                                self.host_manager.blacklist(host)
                                log.warning(
                                    "elastic: rank %d on %s failed "
                                    "(rc=%s); host blacklisted", r, host,
                                    p.returncode)
                            round_failed = True
                    if round_failed or self._hosts_changed.is_set():
                        break
                    time.sleep(0.2)

                _metrics.ELASTIC_ROUND_DURATION.observe(
                    time.monotonic() - round_start)
                if not self._procs and not round_failed and \
                        not self._hosts_changed.is_set():
                    return 0  # clean finish
                # reset round: stop everything, re-rendezvous
                self._terminate_all()
                resets += 1
                _metrics.ELASTIC_RESETS.inc()
                if self.reset_limit and resets > self.reset_limit:
                    log.error("elastic: reset limit %d exceeded",
                              self.reset_limit)
                    return 1
        finally:
            self._stop.set()
            self._terminate_all()
            enabled = (self.extra_env.get("HOROVOD_METRICS")
                       or os.environ.get("HOROVOD_METRICS", ""))
            if enabled not in ("", "0", "false"):
                from ..runner.launch import report_stragglers
                report_stragglers(self.rendezvous)
            self.rendezvous.stop()


def run_elastic(args, command: List[str]) -> int:
    """CLI entry from hvdrun (reference: _run_elastic launch.py:621-670)."""
    knobs = Knobs()
    if not args.host_discovery_script:
        raise SystemExit(
            "elastic mode requires --host-discovery-script "
            "(reference: launch.py elastic validation)")
    discovery = HostDiscoveryScript(args.host_discovery_script,
                                    default_slots=getattr(args, "slots",
                                                          None) or 1)
    min_np = args.min_np or args.num_proc or 1
    max_np = args.max_np or args.num_proc or (1 << 30)
    from ..runner.launch import (args_to_env, resolve_kv_shards,
                                 resolve_serve_port)
    # --serve pins the rendezvous (= router) port exactly like the
    # static path; the driver's server survives reset rounds, so the
    # journal, the in-flight client streams and the /generate front
    # door all ride across fleet restarts (docs/serving.md).
    pinned_port = (getattr(args, "metrics_port", None)
                   or resolve_serve_port(args) or None)
    driver = ElasticDriver(
        discovery, min_np, max_np, command, env=args_to_env(args),
        elastic_timeout=args.elastic_timeout or
        knobs["HOROVOD_ELASTIC_TIMEOUT"],
        reset_limit=args.reset_limit
        if args.reset_limit is not None
        else knobs["HOROVOD_ELASTIC_RESET_LIMIT"],
        coordinator_port=args.coordinator_port,
        controller_port=args.controller_port,
        output_filename=getattr(args, "output_filename", None),
        network_interface=getattr(args, "network_interface", None),
        prefix_output_with_timestamp=getattr(
            args, "prefix_output_with_timestamp", False),
        metrics_port=pinned_port,
        kv_shards=resolve_kv_shards(args))
    if getattr(args, "serve", None):
        import socket
        print(f"[hvdrun] elastic serving {args.serve}: POST http://"
              f"{socket.gethostname()}:{driver.rdv_port}/generate  "
              "(stats: GET /serve/stats, drain: POST /admin/drain, "
              "metrics: GET /metrics)", file=sys.stderr, flush=True)
    # Chaos plane: the spec rides the driver's rendezvous KV so every
    # incarnation of every worker (reset rounds included) installs the
    # same seeded plan (runner/launch.py publish_chaos_spec).
    from ..runner.launch import (
        install_alert_rules, publish_chaos_spec, publish_scenario_spec)
    publish_chaos_spec(args, driver.rendezvous)
    publish_scenario_spec(args, driver.rendezvous)
    # Watch plane: the alert engine + series store live in THIS driver's
    # rendezvous server, so fleet history and rule state span reset
    # rounds — a run that goes bad across a reset is still one series
    # (runner/launch.py install_alert_rules; docs/watch.md).
    install_alert_rules(args, driver.rendezvous)
    return driver.run()
