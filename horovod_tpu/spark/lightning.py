"""Lightning estimator: train a ``LightningModule`` on Spark-managed data.

Reference: horovod/spark/lightning/estimator.py:100+ (TorchEstimator on
pytorch_lightning) + remote.py RemoteTrainer — the estimator ships a
LightningModule to every worker, trains it under horovod with the
module's own ``configure_optimizers``/``training_step`` hooks, and
returns a servable model.

TPU-native reshape: the train task drives the LightningModule *protocol*
directly (``configure_optimizers`` -> wrapped optimizer,
``training_step`` -> loss, ``on_train_epoch_end`` hook) over parquet
shards with per-batch fused gradient averaging on the XLA data plane —
the same flow every estimator in this package uses.  Because only the
protocol is used, any object with those methods trains identically: real
``pytorch_lightning.LightningModule`` subclasses work when lightning is
installed, and lightning is NOT required otherwise (the reference hard-
depends on it; here the Trainer's role is played by the task loop).
"""

from __future__ import annotations

import os
from typing import Callable

import numpy as np

from ..data.loader import StreamingParquetDataLoader  # noqa: F401
from .estimator import (Estimator, _assemble_batch, _epoch_driver,
                        _iter_train, _make_train_loader,
                        _grad_sync_fn, _torch_eval_predict,
                        _torch_predict_fn, _torch_sync_grads,
                        _torch_sync_params)
from .store import Store


def _is_optimizer(obj) -> bool:
    return hasattr(obj, "param_groups")


def _unwrap_scheduler(sched):
    """Lightning allows scheduler *configs* — dicts like
    ``{"scheduler": sched, "interval": "step", "frequency": N}`` —
    wherever a scheduler goes; normalize to a (scheduler, interval,
    frequency) triple (or None) so the trainer loop honors the declared
    cadence instead of silently stepping per epoch."""
    if sched is None:
        return None
    if isinstance(sched, dict):
        inner = sched.get("scheduler")
        if inner is None:
            return None
        return (inner, sched.get("interval", "epoch"),
                int(sched.get("frequency", 1)))
    return sched, "epoch", 1


def _first_optimizer(configured):
    """``configure_optimizers`` may return an optimizer, a list/tuple of
    them, a (optimizers, schedulers) pair, or the dict form
    ``{"optimizer": ..., "lr_scheduler": ...}`` (lightning's contract).
    Returns (optimizer, scheduler_config) where scheduler_config is the
    :func:`_unwrap_scheduler` triple or None.  A 2-tuple of OPTIMIZERS is
    the multi-optimizer form, not an (optimizer, scheduler) pair —
    stepping an optimizer as if it were a scheduler would apply stale
    gradients."""
    if configured is None:
        raise NotImplementedError(
            "configure_optimizers returned None (lightning manual "
            "optimization); LightningEstimator drives automatic "
            "optimization — return an optimizer")
    if isinstance(configured, dict):
        return (configured["optimizer"],
                _unwrap_scheduler(configured.get("lr_scheduler")))
    sched = None
    if isinstance(configured, tuple) and len(configured) == 2 and \
            not _is_optimizer(configured[1]):
        opts, scheds = configured
        opt = opts[0] if isinstance(opts, (list, tuple)) else opts
        if isinstance(scheds, (list, tuple)) and scheds:
            sched = scheds[0]
        elif scheds is not None and not isinstance(scheds, (list, tuple)):
            sched = scheds
        return opt, _unwrap_scheduler(sched)
    if isinstance(configured, (list, tuple)):
        first = configured[0]
        if isinstance(first, dict):  # list of dict configs
            return (first["optimizer"],
                    _unwrap_scheduler(first.get("lr_scheduler")))
        return first, None
    return configured, None


class LightningEstimator(Estimator):
    """Estimator over a LightningModule factory (reference:
    spark/lightning/estimator.py TorchEstimator(model=...)).

    ``model_fn`` builds the module per worker (factories keep the fit
    payload small and make re-instantiation after elastic resets safe —
    the reference serializes the module itself for the same purpose).
    """

    def __init__(self, store: Store, model_fn: Callable, num_proc: int = 1,
                 **kwargs):
        super().__init__(store, num_proc=num_proc, **kwargs)
        if self.sample_weight_col:
            raise ValueError(
                "LightningEstimator does not support sample_weight_col: "
                "training_step owns the loss — weight it inside the "
                "module")
        self.model_fn = model_fn

    def _make_train_task(self) -> Callable:
        return _LightningTrainTask(self.store, self.run_id, self.model_fn,
                                   self.feature_cols, self.label_cols,
                                   self.batch_size, self.epochs,
                                   metrics=self.metrics,
                                   opts=self._data_opts())

    def _load_model(self, payload: bytes) -> Callable:
        return _torch_predict_fn(self.model_fn, payload)


class _LightningTrainTask:
    """Picklable per-worker trainer: the Trainer-role loop over the
    LightningModule protocol (reference: lightning/remote.py
    RemoteTrainer's train function)."""

    def __init__(self, store, run_id, model_fn, feature_cols, label_cols,
                 batch_size, epochs, metrics=(), opts=None):
        self.opts = dict(opts or {})
        self.store = store
        self.run_id = run_id
        self.model_fn = model_fn
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs
        self.metrics = list(metrics)

    def __call__(self, train_path: str, val_path=None):
        import io
        import torch
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = _make_train_loader(self.store, train_path,
                                    self.batch_size, rank, size, self.opts)
        module = self.model_fn()
        opt, sched_cfg = _first_optimizer(module.configure_optimizers())
        sched, interval, freq = sched_cfg or (None, "epoch", 1)
        step_counter = {"global_step": 0}

        def restore(payload: bytes) -> None:
            module.load_state_dict(torch.load(io.BytesIO(payload),
                                              weights_only=True))

        def serialize() -> bytes:
            # per-epoch checkpoint (reference: remote.py ModelCheckpoint
            # every epoch)
            buf = io.BytesIO()
            torch.save(module.state_dict(), buf)
            return buf.getvalue()

        def train_epoch(epoch: int) -> float:
            module.train()
            epoch_loss, nb = 0.0, 0
            for i, batch in enumerate(_iter_train(loader, epoch,
                                                  self.opts)):
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                bt = (torch.from_numpy(np.ascontiguousarray(x, np.float32)),
                      torch.from_numpy(np.ascontiguousarray(y, np.float32)))
                opt.zero_grad()
                out = module.training_step(bt, i)
                if out is None:
                    continue  # lightning's skip-this-batch signal
                loss = out["loss"] if isinstance(out, dict) else out
                loss.backward()
                if size > 1:
                    _torch_sync_grads(module, sync)
                opt.step()
                epoch_loss += float(loss.detach())
                nb += 1
                step_counter["global_step"] += 1
                if sched is not None and interval == "step" and \
                        step_counter["global_step"] % freq == 0:
                    sched.step()
            if sched is not None and interval == "epoch" and \
                    (epoch + 1) % freq == 0:
                sched.step()
            if hasattr(module, "on_train_epoch_end"):
                module.on_train_epoch_end()
            return epoch_loss / max(nb, 1)

        history = _epoch_driver(
            self.store, self.run_id, self.epochs, self.metrics,
            self.batch_size, self.feature_cols, self.label_cols,
            rank, size, sync, val_path,
            opts=self.opts,
            restore=restore, serialize=serialize, train_epoch=train_epoch,
            predict=lambda x: _torch_eval_predict(module, x),
            cold_start=(lambda: _torch_sync_params(module, sync))
            if size > 1 else None)
        return history["train_loss"][-1] if history["train_loss"] else 0.0


__all__ = ["LightningEstimator"]
