"""Lightning estimator: train a ``LightningModule`` on Spark-managed data.

Reference: horovod/spark/lightning/estimator.py:100+ (TorchEstimator on
pytorch_lightning) + remote.py RemoteTrainer — the estimator ships a
LightningModule to every worker, trains it under horovod with the
module's own ``configure_optimizers``/``training_step`` hooks, and
returns a servable model.

TPU-native reshape: the train task drives the LightningModule *protocol*
directly (``configure_optimizers`` -> wrapped optimizer,
``training_step`` -> loss, ``on_train_epoch_end`` hook) over parquet
shards with per-batch fused gradient averaging on the XLA data plane —
the same flow every estimator in this package uses.  Because only the
protocol is used, any object with those methods trains identically: real
``pytorch_lightning.LightningModule`` subclasses work when lightning is
installed, and lightning is NOT required otherwise (the reference hard-
depends on it; here the Trainer's role is played by the task loop).
"""

from __future__ import annotations

import os
from typing import Callable

import numpy as np

from ..data.loader import StreamingParquetDataLoader  # noqa: F401
from .estimator import (Estimator, _assemble_batch, _epoch_driver,
                        _iter_train, _make_train_loader,
                        _grad_sync_fn, _torch_eval_predict,
                        _torch_predict_fn, _torch_sync_grads,
                        _torch_sync_params)
from .store import Store


def _is_optimizer(obj) -> bool:
    return hasattr(obj, "param_groups")


def _unwrap_scheduler(sched):
    """Lightning allows scheduler *configs* — dicts like
    ``{"scheduler": sched, "interval": "step", "frequency": N}`` —
    wherever a scheduler goes; normalize to a (scheduler, interval,
    frequency) triple (or None) so the trainer loop honors the declared
    cadence instead of silently stepping per epoch."""
    if sched is None:
        return None
    if isinstance(sched, dict):
        inner = sched.get("scheduler")
        if inner is None:
            return None
        return (inner, sched.get("interval", "epoch"),
                int(sched.get("frequency", 1)))
    return sched, "epoch", 1


def _first_optimizer(configured):
    """``configure_optimizers`` may return an optimizer, a list/tuple of
    them, a (optimizers, schedulers) pair, or the dict form
    ``{"optimizer": ..., "lr_scheduler": ...}`` (lightning's contract).
    Returns (optimizer, scheduler_config) where scheduler_config is the
    :func:`_unwrap_scheduler` triple or None.  A 2-tuple of OPTIMIZERS is
    the multi-optimizer form, not an (optimizer, scheduler) pair —
    stepping an optimizer as if it were a scheduler would apply stale
    gradients."""
    if configured is None:
        raise NotImplementedError(
            "configure_optimizers returned None (lightning manual "
            "optimization); LightningEstimator drives automatic "
            "optimization — return an optimizer")
    if isinstance(configured, dict):
        return (configured["optimizer"],
                _unwrap_scheduler(configured.get("lr_scheduler")))
    sched = None
    if isinstance(configured, tuple) and len(configured) == 2 and \
            not _is_optimizer(configured[1]):
        opts, scheds = configured
        opt = opts[0] if isinstance(opts, (list, tuple)) else opts
        if isinstance(scheds, (list, tuple)) and scheds:
            sched = scheds[0]
        elif scheds is not None and not isinstance(scheds, (list, tuple)):
            sched = scheds
        return opt, _unwrap_scheduler(sched)
    if isinstance(configured, (list, tuple)):
        first = configured[0]
        if isinstance(first, dict):  # list of dict configs
            return (first["optimizer"],
                    _unwrap_scheduler(first.get("lr_scheduler")))
        return first, None
    return configured, None


class _TrainerProxy:
    """The slim stand-in handed to callbacks where lightning passes its
    Trainer (reference: remote.py builds a full pl.Trainer).  Carries
    the attributes well-behaved callbacks read: current_epoch,
    global_step, callback_metrics (the module.log sink), should_stop
    (writable — EarlyStopping's stop signal), and is_global_zero."""

    def __init__(self, rank: int):
        self.current_epoch = 0
        self.global_step = 0
        self.callback_metrics: dict = {}
        self.should_stop = False
        self.is_global_zero = rank == 0
        # widely-read flags, so simple real-lightning callbacks that
        # check them don't crash (a FULL pl.Trainer surface is out of
        # scope — see the estimator docstring)
        self.sanity_checking = False
        self.fast_dev_run = False


class _CallbackList:
    """Duck-typed lightning Callback dispatch: each hook fires when the
    callback defines it (reference: estimator.py `callbacks` param,
    forwarded to the Trainer)."""

    def __init__(self, callbacks, proxy, module):
        self.cbs = list(callbacks or ())
        self.proxy = proxy
        self.module = module

    def fire(self, hook: str, *args) -> None:
        for cb in self.cbs:
            fn = getattr(cb, hook, None)
            if fn is not None:
                fn(self.proxy, self.module, *args)


class LightningEstimator(Estimator):
    """Estimator over a LightningModule factory (reference:
    spark/lightning/estimator.py TorchEstimator(model=...)).

    ``model_fn`` builds the module per worker (factories keep the fit
    payload small and make re-instantiation after elastic resets safe —
    the reference serializes the module itself for the same purpose).

    Param-surface delta vs the reference lightning estimator
    (estimator.py:203-240): the shared data/fit knobs (validation,
    batch sizes, steps caps, shuffle_buffer_size, transformation_fn,
    verbose) live on the base Estimator; this class adds the
    lightning-specific surface —

    * ``callbacks``: lightning-style Callback objects; the trainer loop
      fires on_train_start/on_train_epoch_start/on_train_batch_end/
      on_train_epoch_end/on_validation_epoch_end/on_train_end with a
      Trainer PROXY (current_epoch, global_step, callback_metrics,
      writable should_stop — cross-worker-synced).  EarlyStopping-style
      callbacks that read callback_metrics and set should_stop work;
      pytorch_lightning's own EarlyStopping class expects a full
      pl.Trainer (trainer.state etc.) and needs a thin duck-typed
      equivalent instead.
    * ``logger`` + ``log_every_n_steps``: anything with
      ``log_metrics(dict, step)`` (lightning Logger protocol);
      ``self.log(...)`` calls inside training_step/validation_step are
      captured and flushed on the cadence, rank 0 only.
    * ``validation_step`` protocol: when the module defines it and a
      validation set exists, it runs per epoch and its mean outputs
      land in history as ``val_loss`` (plus any logged metrics) —
      the reference's val dataloader path.
    * ``gradient_clip_val``: the Trainer knob (clip-by-norm before
      every step, reference Trainer(gradient_clip_val=...)).

    Knobs with no analog here: reference's num_gpus/backend (TPU mesh
    is the backend), train_minibatch_fn (training_step owns the step),
    inmemory_cache_all/reader-pool knobs (streaming loaders read row
    groups directly), profiler/terminate_on_nan (use the framework
    timeline/xprof; non-finite losses raise in the metrics path).
    """

    def __init__(self, store: Store, model_fn: Callable, num_proc: int = 1,
                 callbacks=(), logger=None, log_every_n_steps: int = 50,
                 gradient_clip_val: float = None,
                 **kwargs):
        super().__init__(store, num_proc=num_proc, **kwargs)
        if self.sample_weight_col:
            raise ValueError(
                "LightningEstimator does not support sample_weight_col: "
                "training_step owns the loss — weight it inside the "
                "module")
        from .estimator import _resolve_metrics
        if any(name == "loss"
               for name, _ in _resolve_metrics(self.metrics)):
            # _eval_metrics would emit 'val_loss' for it, colliding with
            # the validation_step series of the same name — two appends
            # per epoch to one history key.
            raise ValueError(
                "a metric named 'loss' collides with validation_step's "
                "val_loss history series; rename the metric")
        self.model_fn = model_fn
        self.callbacks = list(callbacks or ())
        self.logger = logger
        self.log_every_n_steps = int(log_every_n_steps)
        self.gradient_clip_val = gradient_clip_val

    def _make_train_task(self) -> Callable:
        return _LightningTrainTask(self.store, self.run_id, self.model_fn,
                                   self.feature_cols, self.label_cols,
                                   self.batch_size, self.epochs,
                                   metrics=self.metrics,
                                   opts=self._data_opts(),
                                   callbacks=self.callbacks,
                                   logger=self.logger,
                                   log_every_n_steps=self.log_every_n_steps,
                                   gradient_clip_val=self.gradient_clip_val)

    def _load_model(self, payload: bytes) -> Callable:
        return _torch_predict_fn(self.model_fn, payload)


class _LightningTrainTask:
    """Picklable per-worker trainer: the Trainer-role loop over the
    LightningModule protocol (reference: lightning/remote.py
    RemoteTrainer's train function)."""

    def __init__(self, store, run_id, model_fn, feature_cols, label_cols,
                 batch_size, epochs, metrics=(), opts=None,
                 callbacks=(), logger=None, log_every_n_steps=50,
                 gradient_clip_val=None):
        self.opts = dict(opts or {})
        self.store = store
        self.run_id = run_id
        self.model_fn = model_fn
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs
        self.metrics = list(metrics)
        self.callbacks = list(callbacks or ())
        self.logger = logger
        self.log_every_n_steps = int(log_every_n_steps)
        self.gradient_clip_val = gradient_clip_val

    def __call__(self, train_path: str, val_path=None):
        import io
        import torch
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = _make_train_loader(self.store, train_path,
                                    self.batch_size, rank, size, self.opts)
        module = self.model_fn()
        opt, sched_cfg = _first_optimizer(module.configure_optimizers())
        sched, interval, freq = sched_cfg or (None, "epoch", 1)
        step_counter = {"global_step": 0}

        proxy = _TrainerProxy(rank)
        cbs = _CallbackList(self.callbacks, proxy, module)
        logger = self.logger if rank == 0 else None
        pending_logs: dict = {}

        val_ctx = {"active": False, "bs": 0, "accum": {}}

        def log_shim(name, value, *args, **kwargs):
            # LightningModule.log without a Trainer attached: capture
            # into callback_metrics (for callbacks like EarlyStopping)
            # and the logger flush buffer.  Inside validation, ACCUMULATE
            # instead (lightning's on_epoch=True default): the epoch
            # value is the row-weighted mean over every batch and every
            # worker, not the last batch rank-0 happened to see.
            v = float(value.detach() if hasattr(value, "detach")
                      else value)
            if val_ctx["active"]:
                s, n = val_ctx["accum"].get(name, (0.0, 0.0))
                val_ctx["accum"][name] = (s + v * val_ctx["bs"],
                                          n + val_ctx["bs"])
                return
            proxy.callback_metrics[name] = v
            pending_logs[name] = v

        module.log = log_shim  # instance attr shadows the real method

        def flush_logs(force=False):
            if logger is None or not pending_logs:
                return
            # cadence <= 0 means "epoch boundaries only" (guards the
            # modulo too); forced flushes always go through
            every = self.log_every_n_steps
            if force or (every > 0 and proxy.global_step % every == 0):
                logger.log_metrics(dict(pending_logs),
                                   step=proxy.global_step)
                pending_logs.clear()

        def synced_should_stop() -> bool:
            # lightning allreduces should_stop; an unsynced rank-local
            # decision (e.g. set only under trainer.is_global_zero)
            # would break one rank out of the epoch loop while the rest
            # block in the next grad sync.
            flag = 1.0 if proxy.should_stop else 0.0
            if size > 1:
                flag = float(np.asarray(
                    sync([np.array([flag], np.float64)])[0]).max())
            proxy.should_stop = flag > 0.0
            return proxy.should_stop

        def restore(payload: bytes) -> None:
            module.load_state_dict(torch.load(io.BytesIO(payload),
                                              weights_only=True))

        def serialize() -> bytes:
            # per-epoch checkpoint (reference: remote.py ModelCheckpoint
            # every epoch)
            buf = io.BytesIO()
            torch.save(module.state_dict(), buf)
            return buf.getvalue()

        started = {"done": False}

        def train_epoch(epoch: int) -> float:
            if not started["done"]:  # after a possible resume-restore
                started["done"] = True
                if epoch > 0 and step_counter["global_step"] == 0:
                    # Resume: rebuild an (approximate) monotonic step
                    # count so logger series don't restart at 0 and
                    # step-interval schedulers keep their cadence
                    # position.  Exact per-epoch counts aren't in the
                    # envelope; uniform epochs make this exact.
                    per = len(loader)
                    cap = self.opts.get("train_steps_per_epoch")
                    if cap:
                        per = min(per, int(cap))
                    step_counter["global_step"] = epoch * per
                    proxy.global_step = step_counter["global_step"]
                cbs.fire("on_train_start")
            proxy.current_epoch = epoch
            cbs.fire("on_train_epoch_start")
            module.train()
            epoch_loss, nb = 0.0, 0
            for i, batch in enumerate(_iter_train(loader, epoch,
                                                  self.opts)):
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                bt = (torch.from_numpy(np.ascontiguousarray(x, np.float32)),
                      torch.from_numpy(np.ascontiguousarray(y, np.float32)))
                opt.zero_grad()
                out = module.training_step(bt, i)
                if out is None:
                    continue  # lightning's skip-this-batch signal
                loss = out["loss"] if isinstance(out, dict) else out
                loss.backward()
                if size > 1:
                    _torch_sync_grads(module, sync)
                if self.gradient_clip_val:
                    torch.nn.utils.clip_grad_norm_(
                        module.parameters(), self.gradient_clip_val)
                opt.step()
                epoch_loss += float(loss.detach())
                nb += 1
                step_counter["global_step"] += 1
                proxy.global_step = step_counter["global_step"]
                cbs.fire("on_train_batch_end", out, bt, i)
                flush_logs()
                if sched is not None and interval == "step" and \
                        step_counter["global_step"] % freq == 0:
                    sched.step()
            if sched is not None and interval == "epoch" and \
                    (epoch + 1) % freq == 0:
                sched.step()
            if hasattr(module, "on_train_epoch_end"):
                module.on_train_epoch_end()
            # callbacks' on_train_epoch_end fires AFTER this epoch's
            # validation (lightning's ordering) — see epoch_end below
            return epoch_loss / max(nb, 1)

        def epoch_end(epoch: int) -> dict:
            """Per-epoch tail in lightning's order: validation_step over
            the sharded val set (transform + steps-cap honored, losses
            averaged exactly across workers), THEN the callbacks' epoch
            end — so stopping callbacks see THIS epoch's val_loss."""
            out_hist = {}
            if val_path is not None and \
                    hasattr(module, "validation_step"):
                from .estimator import _iter_val_batches
                module.eval()
                sums = np.zeros((2,), np.float64)
                val_ctx.update(active=True, accum={})
                try:
                    with torch.no_grad():
                        for i, batch in enumerate(_iter_val_batches(
                                val_path, self.batch_size, rank, size,
                                fs=self.store.fs, opts=self.opts)):
                            x, y = _assemble_batch(
                                batch, self.feature_cols,
                                self.label_cols)
                            bt = (torch.from_numpy(
                                      np.ascontiguousarray(x, np.float32)),
                                  torch.from_numpy(
                                      np.ascontiguousarray(y, np.float32)))
                            val_ctx["bs"] = len(x)
                            out = module.validation_step(bt, i)
                            if out is None:
                                continue
                            loss = out["loss"] if isinstance(out, dict) \
                                else out
                            # plain floats / numpy scalars are legal
                            # step outputs too
                            sums[0] += float(
                                loss.detach() if hasattr(loss, "detach")
                                else loss) * len(x)
                            sums[1] += len(x)
                finally:
                    val_ctx["active"] = False
                # epoch means of everything validation_step logged,
                # exact across workers (same weighted-sum combine as
                # the loss), into callback_metrics/logger/history
                if val_ctx["accum"]:
                    names = sorted(val_ctx["accum"])
                    m = np.array([val_ctx["accum"][k] for k in names],
                                 np.float64)
                    if size > 1:
                        m = np.asarray(sync([m])[0], np.float64)
                    for k, (s, n) in zip(names, m):
                        if n > 0:
                            mv = float(s / n)
                            proxy.callback_metrics[k] = mv
                            pending_logs[k] = mv
                            out_hist[k] = mv
                if size > 1:
                    sums = np.asarray(sync([sums])[0], np.float64)
                if sums[1] > 0:
                    # sums[1] == 0 means every batch returned None: a
                    # real pl.LightningModule that never overrode the
                    # base-class hook (hasattr is always true there) —
                    # recording val_loss=0.0 would feed stopping
                    # callbacks a perfect constant.
                    val_loss = float(sums[0] / sums[1])
                    proxy.callback_metrics["val_loss"] = val_loss
                    pending_logs["val_loss"] = val_loss
                    cbs.fire("on_validation_epoch_end")
                    out_hist["val_loss"] = val_loss
            cbs.fire("on_train_epoch_end")
            flush_logs(force=True)
            return out_hist

        history = _epoch_driver(
            self.store, self.run_id, self.epochs, self.metrics,
            self.batch_size, self.feature_cols, self.label_cols,
            rank, size, sync, val_path,
            opts=self.opts,
            restore=restore, serialize=serialize, train_epoch=train_epoch,
            predict=lambda x: _torch_eval_predict(module, x),
            cold_start=(lambda: _torch_sync_params(module, sync))
            if size > 1 else None,
            extra_eval=epoch_end,
            should_stop=synced_should_stop)
        cbs.fire("on_train_end")
        flush_logs(force=True)
        if logger is not None and hasattr(logger, "finalize"):
            logger.finalize("success")
        return history["train_loss"][-1] if history["train_loss"] else 0.0


__all__ = ["LightningEstimator"]
