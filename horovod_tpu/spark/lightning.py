"""Lightning estimator: train a ``LightningModule`` on Spark-managed data.

Reference: horovod/spark/lightning/estimator.py:100+ (TorchEstimator on
pytorch_lightning) + remote.py RemoteTrainer — the estimator ships a
LightningModule to every worker, trains it under horovod with the
module's own ``configure_optimizers``/``training_step`` hooks, and
returns a servable model.

TPU-native reshape: the train task drives the LightningModule *protocol*
directly (``configure_optimizers`` -> wrapped optimizer,
``training_step`` -> loss, ``on_train_epoch_end`` hook) over parquet
shards with per-batch fused gradient averaging on the XLA data plane —
the same flow every estimator in this package uses.  Because only the
protocol is used, any object with those methods trains identically: real
``pytorch_lightning.LightningModule`` subclasses work when lightning is
installed, and lightning is NOT required otherwise (the reference hard-
depends on it; here the Trainer's role is played by the task loop).
"""

from __future__ import annotations

import os
import pickle
from typing import Callable, Optional

import numpy as np

from ..data.loader import ParquetDataLoader
from .estimator import (Estimator, _assemble_batch, _grad_sync_fn,
                        _torch_predict_fn, _torch_sync_grads,
                        _torch_sync_params)
from .store import Store


def _is_optimizer(obj) -> bool:
    return hasattr(obj, "param_groups")


def _first_optimizer(configured):
    """``configure_optimizers`` may return an optimizer, a list/tuple of
    them, or a (optimizers, schedulers) pair (lightning's contract);
    training uses the first optimizer and steps the first scheduler per
    epoch.  A 2-tuple of OPTIMIZERS is the multi-optimizer form, not an
    (optimizer, scheduler) pair — stepping an optimizer as if it were a
    scheduler would apply stale gradients."""
    sched = None
    if isinstance(configured, tuple) and len(configured) == 2 and \
            not _is_optimizer(configured[1]):
        opts, scheds = configured
        opt = opts[0] if isinstance(opts, (list, tuple)) else opts
        if isinstance(scheds, (list, tuple)) and scheds:
            sched = scheds[0]
        elif scheds is not None and not isinstance(scheds, (list, tuple)):
            sched = scheds
        return opt, sched
    if isinstance(configured, (list, tuple)):
        return configured[0], None
    return configured, None


class LightningEstimator(Estimator):
    """Estimator over a LightningModule factory (reference:
    spark/lightning/estimator.py TorchEstimator(model=...)).

    ``model_fn`` builds the module per worker (factories keep the fit
    payload small and make re-instantiation after elastic resets safe —
    the reference serializes the module itself for the same purpose).
    """

    def __init__(self, store: Store, model_fn: Callable, num_proc: int = 1,
                 **kwargs):
        super().__init__(store, num_proc=num_proc, **kwargs)
        self.model_fn = model_fn

    def _make_train_task(self) -> Callable:
        return _LightningTrainTask(self.store, self.run_id, self.model_fn,
                                   self.feature_cols, self.label_cols,
                                   self.batch_size, self.epochs)

    def _load_model(self, payload: bytes) -> Callable:
        return _torch_predict_fn(self.model_fn, payload)


class _LightningTrainTask:
    """Picklable per-worker trainer: the Trainer-role loop over the
    LightningModule protocol (reference: lightning/remote.py
    RemoteTrainer's train function)."""

    def __init__(self, store, run_id, model_fn, feature_cols, label_cols,
                 batch_size, epochs):
        self.store = store
        self.run_id = run_id
        self.model_fn = model_fn
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs

    def __call__(self, train_path: str):
        import io
        import torch
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = ParquetDataLoader(train_path, self.batch_size,
                                   rank=rank, num_workers=size)
        module = self.model_fn()
        if size > 1:  # identical start: one fused parameter sync
            _torch_sync_params(module, sync)
        opt, sched = _first_optimizer(module.configure_optimizers())
        loss = torch.zeros(())
        for epoch in range(self.epochs):
            module.train()
            for i, batch in enumerate(loader):
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                bt = (torch.from_numpy(np.ascontiguousarray(x, np.float32)),
                      torch.from_numpy(np.ascontiguousarray(y, np.float32)))
                opt.zero_grad()
                out = module.training_step(bt, i)
                loss = out["loss"] if isinstance(out, dict) else out
                loss.backward()
                if size > 1:
                    _torch_sync_grads(module, sync)
                opt.step()
            if sched is not None:
                sched.step()
            if hasattr(module, "on_train_epoch_end"):
                module.on_train_epoch_end()
            if rank == 0:  # per-epoch checkpoint (reference: remote.py
                buf = io.BytesIO()  # ModelCheckpoint every epoch)
                torch.save(module.state_dict(), buf)
                self.store.save_checkpoint(self.run_id, buf.getvalue())
        return float(loss)


__all__ = ["LightningEstimator"]
