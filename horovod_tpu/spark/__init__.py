"""Spark integration (reference: horovod/spark/, ~7k LoC).

``horovod_tpu.spark.run(fn, num_proc)`` runs a training function on
cluster tasks; Estimators persist datasets through a Store and return
servable models.  pyspark is required only for real-cluster placement —
the orchestration core and local mode work without it (the reference's
test strategy runs Spark in local mode the same way).
"""

from .runner import (LocalTaskExecutor, SparkTaskExecutor, TaskExecutor,
                     run, run_elastic)
from .store import DBFSLocalStore, FilesystemStore, LocalStore, Store
from .estimator import (Estimator, EstimatorModel, KerasEstimator,
                        LinearEstimator, TorchEstimator)
from .lightning import LightningEstimator

__all__ = ["run", "run_elastic", "TaskExecutor", "LocalTaskExecutor",
           "SparkTaskExecutor", "Store", "FilesystemStore", "LocalStore",
           "DBFSLocalStore", "Estimator", "EstimatorModel",
           "LinearEstimator", "KerasEstimator", "TorchEstimator",
           "LightningEstimator"]
