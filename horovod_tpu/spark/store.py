"""Store: where prepared training data and checkpoints live.

Reference: horovod/spark/common/store.py:36-530 — FilesystemStore keeps
train/val parquet, per-run checkpoints and logs under a base directory;
HDFS/DBFS variants change only path handling and the byte-transport
client.  Here that boundary is explicit: ONE store implementation
(:class:`FilesystemStore`) runs over the seven-method filesystem
protocol (``data/fs.py``), and the remote variants swap the fs object —
:class:`HDFSStore` takes an ``hdfs://`` prefix plus an injected (or
pyarrow-constructed) client, :class:`DBFSLocalStore` rewrites ``dbfs:/``
paths onto the fuse mount.

Datasets are DIRECTORIES of ``part-NNNNN.parquet`` files.  The prepare
step appends parts — from one driver streaming chunks, or from many
Spark partitions writing in parallel (``spark/prepare.py``) — so no
single process ever has to hold the dataset.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..data.fs import BaseFS, LocalFS


class Store:
    """Abstract store surface (reference: store.py:36-100)."""

    fs: BaseFS

    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def is_parquet_dataset(self, path: str) -> bool:
        raise NotImplementedError

    # ---- run logs (per-epoch history) -----------------------------------
    def save_log(self, run_id: str, payload: bytes) -> str:
        raise NotImplementedError

    def read_log(self, run_id: str) -> Optional[bytes]:
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str, **kwargs) -> "Store":
        """Factory dispatching on path scheme (reference: store.py
        Store.create routes hdfs:// to HDFSStore, dbfs:/ to
        DBFSLocalStore, everything else to FilesystemStore)."""
        if prefix_path.startswith("dbfs:/") or \
                prefix_path.startswith("/dbfs"):
            return DBFSLocalStore(prefix_path, **kwargs)
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, **kwargs)
        return FilesystemStore(prefix_path, **kwargs)


def _encode_table(columns: Dict[str, np.ndarray]):
    """Column dict -> (pyarrow table with shape metadata).  Multi-dim
    columns flatten to lists; shapes ride the schema metadata so readers
    restore them (decoder: data/loader.decode_table)."""
    import json

    import pyarrow as pa

    flat = {}
    meta: Dict[str, Any] = {}
    for name, arr in columns.items():
        arr = np.asarray(arr)
        if arr.ndim > 1:  # parquet columns are 1-D; flatten + remember
            meta[name] = list(arr.shape[1:])
            flat[name] = list(arr.reshape(arr.shape[0], -1))
        else:
            flat[name] = arr
    table = pa.table(flat)
    return table.replace_schema_metadata(
        {b"horovod_tpu_shapes": json.dumps(meta).encode()})


class ParquetPartWriter:
    """Append column-dict chunks to a dataset as ``part-NNNNN.parquet``
    files.  ``base_index`` namespaces the part numbers so N writers (one
    per Spark partition) append to the SAME dataset without coordination:
    partition p writes part-(p*stride+i).  Each part lands via
    tmp+rename, so readers never observe half-written files."""

    def __init__(self, store: "FilesystemStore", path: str,
                 base_index: int = 0, stride: int = 1 << 20):
        self.store = store
        self.path = path
        self._next = base_index * stride
        self._wrote = 0

    def write(self, columns: Dict[str, np.ndarray]) -> str:
        import pyarrow.parquet as pq

        fs = self.store.fs
        fs.mkdirs(self.path)
        # 13 digits covers base_index up to ~9.5e6 at the default 2**20
        # stride; a fixed width keeps lexicographic listing == numeric
        # order (9 digits overflowed at partition index 954).
        out = fs.join(self.path, f"part-{self._next:013d}.parquet")
        tmp = out + ".tmp"
        with fs.open(tmp, "wb") as f:
            pq.write_table(_encode_table(columns), f)
        fs.rename(tmp, out)
        self._next += 1
        self._wrote += 1
        return out

    @property
    def parts_written(self) -> int:
        return self._wrote


class FilesystemStore(Store):
    """Storage over a filesystem object (reference: store.py:103-330).
    With the default ``LocalFS`` this is local/NFS/fuse-mounted storage;
    remote stores pass a different fs + posix path joining."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 logs_path: Optional[str] = None,
                 fs: Optional[BaseFS] = None):
        self.fs = fs or LocalFS()
        j = self.fs.join
        self.prefix_path = prefix_path
        self._train = train_path or j(prefix_path, "intermediate_train_data")
        self._val = val_path or j(prefix_path, "intermediate_val_data")
        self._ckpt = checkpoint_path or j(prefix_path, "checkpoints")
        self._logs = logs_path or j(prefix_path, "logs")
        self.fs.mkdirs(prefix_path)

    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        return self._train if idx is None else f"{self._train}.{idx}"

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        return self._val if idx is None else f"{self._val}.{idx}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return self.fs.join(self._ckpt, run_id)

    def get_logs_path(self, run_id: str) -> str:
        return self.fs.join(self._logs, run_id)

    def exists(self, path: str) -> bool:
        return self.fs.exists(path)

    def is_parquet_dataset(self, path: str) -> bool:
        if not self.fs.exists(path):
            return False
        if not self.fs.isdir(path):
            return path.endswith(".parquet")
        return any(f.endswith(".parquet") for f in self.fs.listdir(path))

    # ---- data prep -------------------------------------------------------
    def part_writer(self, path: str, overwrite: bool = True,
                    base_index: int = 0) -> ParquetPartWriter:
        """Chunked/parallel prepare entry (spark/common/util.py
        prepare_data analog): each chunk of rows becomes its own part
        file.  ``overwrite`` clears the dataset first — only ONE caller
        (the driver, before fanning out) should pass it."""
        if overwrite and self.fs.exists(path):
            self.fs.rmtree(path)
        return ParquetPartWriter(self, path, base_index=base_index)

    def write_parquet(self, path: str, columns: Dict[str, np.ndarray],
                      overwrite: bool = True) -> str:
        """One-shot prepare of an in-memory column dict (small data /
        tests); a single part via the same writer."""
        self.part_writer(path, overwrite=overwrite).write(columns)
        return path

    def read_parquet(self, path: str) -> Dict[str, np.ndarray]:
        """Read back a dataset, restoring shapes (decoder shared with
        ParquetDataLoader: data/loader.decode_table)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from ..data.loader import decode_table, list_parquet_files
        tables = []
        for fpath in list_parquet_files(path, fs=self.fs):
            with self.fs.open(fpath, "rb") as f:
                tables.append(pq.read_table(f))
        return decode_table(pa.concat_tables(tables))

    # ---- checkpoints -----------------------------------------------------
    def save_checkpoint(self, run_id: str, payload: bytes,
                        name: str = "checkpoint.bin") -> str:
        d = self.get_checkpoint_path(run_id)
        self.fs.mkdirs(d)
        p = self.fs.join(d, name)
        tmp = p + ".tmp"
        with self.fs.open(tmp, "wb") as f:
            f.write(payload)
        self.fs.rename(tmp, p)
        return p

    def read_checkpoint(self, run_id: str,
                        name: str = "checkpoint.bin") -> Optional[bytes]:
        p = self.fs.join(self.get_checkpoint_path(run_id), name)
        if not self.fs.exists(p):
            return None
        with self.fs.open(p, "rb") as f:
            return f.read()

    # ---- run logs --------------------------------------------------------
    def save_log(self, run_id: str, payload: bytes) -> str:
        d = self.get_logs_path(run_id)
        self.fs.mkdirs(d)
        p = self.fs.join(d, "history.bin")
        tmp = p + ".tmp"
        with self.fs.open(tmp, "wb") as f:
            f.write(payload)
        self.fs.rename(tmp, p)
        return p

    def read_log(self, run_id: str) -> Optional[bytes]:
        p = self.fs.join(self.get_logs_path(run_id), "history.bin")
        if not self.fs.exists(p):
            return None
        with self.fs.open(p, "rb") as f:
            return f.read()


LocalStore = FilesystemStore


class HDFSStore(FilesystemStore):
    """Remote-scheme store (reference: store.py HDFSStore:333-530): paths
    are ``hdfs://namenode/...`` URIs and every byte moves through an
    HDFS client speaking the fs protocol (data/fs.py).

    ``fs`` is the client.  Pass one explicitly (anything implementing the
    seven-method protocol — tests inject a fake namenode; production
    wraps pyarrow's HadoopFileSystem); with ``fs=None`` a pyarrow-backed
    client is attempted, and environments without Hadoop libraries get
    the actionable error instead of a deep pyarrow stack."""

    def __init__(self, prefix_path: str, fs: Optional[BaseFS] = None,
                 **kwargs):
        if not prefix_path.startswith("hdfs://"):
            raise ValueError(f"HDFSStore requires an hdfs:// prefix, got "
                             f"{prefix_path!r}")
        if fs is None:
            fs = _pyarrow_hdfs(prefix_path)
        super().__init__(prefix_path, fs=fs, **kwargs)


class PyArrowFS(BaseFS):
    """fs-protocol adapter over a pyarrow FileSystem.  Module-level and
    holding only the (picklable) pyarrow client, because the Store rides
    inside train tasks shipped to workers with PLAIN pickle
    (runner.py's picklable-class convention)."""

    def __init__(self, pafs_client):
        self._c = pafs_client

    def open(self, path, mode="rb"):
        p = _strip_scheme(path)
        return (self._c.open_input_stream(p) if "r" in mode
                else self._c.open_output_stream(p))

    def exists(self, path):
        from pyarrow import fs as pafs
        info = self._c.get_file_info(_strip_scheme(path))
        return info.type != pafs.FileType.NotFound

    def isdir(self, path):
        from pyarrow import fs as pafs
        info = self._c.get_file_info(_strip_scheme(path))
        return info.type == pafs.FileType.Directory

    def listdir(self, path):
        from pyarrow import fs as pafs
        sel = pafs.FileSelector(_strip_scheme(path))
        return [i.base_name for i in self._c.get_file_info(sel)]

    def mkdirs(self, path):
        self._c.create_dir(_strip_scheme(path), recursive=True)

    def rmtree(self, path):
        p = _strip_scheme(path)
        if self.isdir(p):
            self._c.delete_dir(p)
        elif self.exists(p):
            self._c.delete_file(p)

    def rename(self, src, dst):
        self._c.move(_strip_scheme(src), _strip_scheme(dst))


def _pyarrow_hdfs(uri: str) -> BaseFS:
    """Build a PyArrowFS over pyarrow's HadoopFileSystem, or raise with
    the TPU-image guidance (reference store.py's HDFS client bring-up,
    minus the libhdfs juggling)."""
    try:
        from pyarrow import fs as pafs
        hdfs, _ = pafs.FileSystem.from_uri(uri)
    except Exception as e:
        raise RuntimeError(
            "hdfs:// stores need an HDFS client: pass "
            "HDFSStore(prefix, fs=<client>) with any object speaking the "
            "horovod_tpu.data.fs protocol, or install Hadoop native libs "
            "for pyarrow. TPU-VM images ship neither — mounting the "
            "cluster (fuse/NFS) and using FilesystemStore also works"
        ) from e
    return PyArrowFS(hdfs)


def _strip_scheme(path: str) -> str:
    """hdfs://host[:port]/a/b -> /a/b (pyarrow clients address paths
    relative to the connected namenode)."""
    if path.startswith("hdfs://"):
        rest = path[len("hdfs://"):]
        slash = rest.find("/")
        return rest[slash:] if slash >= 0 else "/"
    return path


class DBFSLocalStore(FilesystemStore):
    """Databricks DBFS store (reference: store.py DBFSLocalStore): paths
    given as ``dbfs:/...`` are accessed through the ``/dbfs/`` fuse mount.
    Everything else is FilesystemStore — the Store abstraction is a
    path-translation boundary, exactly as in the reference."""

    def __init__(self, prefix_path: str, **kwargs):
        super().__init__(self.normalize_path(prefix_path), **kwargs)

    @staticmethod
    def normalize_path(path: str) -> str:
        """``dbfs:/foo`` -> ``/dbfs/foo`` (reference:
        store.py DBFSLocalStore.normalize_path)."""
        if path.startswith("dbfs:/"):
            return "/dbfs/" + path[len("dbfs:/"):].lstrip("/")
        return path
