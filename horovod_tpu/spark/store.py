"""Store: where prepared training data and checkpoints live.

Reference: horovod/spark/common/store.py:36-530 — FilesystemStore keeps
train/val parquet, per-run checkpoints and logs under a base directory;
HDFS/DBFS variants change only path handling.  Here the filesystem store
is the core implementation (TPU VMs mount GCS via fuse or use local SSD;
remote-blob variants slot in by overriding ``fs`` path joins).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional

import numpy as np


class Store:
    """Abstract store surface (reference: store.py:36-100)."""

    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def is_parquet_dataset(self, path: str) -> bool:
        raise NotImplementedError

    # ---- run logs (per-epoch history) -----------------------------------
    def save_log(self, run_id: str, payload: bytes) -> str:
        raise NotImplementedError

    def read_log(self, run_id: str) -> Optional[bytes]:
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str, **kwargs) -> "Store":
        """Factory dispatching on path scheme (reference: store.py
        Store.create routes hdfs:// to HDFSStore and everything else to
        FilesystemStore; DBFSLocalStore handles dbfs:/)."""
        if prefix_path.startswith("dbfs:/") or \
                prefix_path.startswith("/dbfs"):
            return DBFSLocalStore(prefix_path, **kwargs)
        if prefix_path.startswith("hdfs://"):
            raise ValueError(
                "hdfs:// stores need an HDFS client, which TPU-VM images "
                "do not ship; mount the cluster (fuse/NFS) and pass the "
                "mounted path, or use gcsfuse + a local path")
        return FilesystemStore(prefix_path, **kwargs)


class FilesystemStore(Store):
    """Local/NFS/fuse-mounted storage (reference: store.py:103-330)."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 logs_path: Optional[str] = None):
        self.prefix_path = prefix_path
        self._train = train_path or os.path.join(prefix_path,
                                                 "intermediate_train_data")
        self._val = val_path or os.path.join(prefix_path,
                                             "intermediate_val_data")
        self._ckpt = checkpoint_path or os.path.join(prefix_path,
                                                     "checkpoints")
        self._logs = logs_path or os.path.join(prefix_path, "logs")
        os.makedirs(prefix_path, exist_ok=True)

    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        return self._train if idx is None else f"{self._train}.{idx}"

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        return self._val if idx is None else f"{self._val}.{idx}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self._ckpt, run_id)

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self._logs, run_id)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def is_parquet_dataset(self, path: str) -> bool:
        if not os.path.isdir(path):
            return os.path.isfile(path) and path.endswith(".parquet")
        return any(f.endswith(".parquet") for f in os.listdir(path))

    # ---- data prep -------------------------------------------------------
    def write_parquet(self, path: str, columns: Dict[str, np.ndarray],
                      overwrite: bool = True) -> str:
        """Persist a column dict as a parquet dataset (the prepare_data
        step of Estimator.fit, reference: spark/common/util.py)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        if overwrite and os.path.isdir(path):
            shutil.rmtree(path)
        os.makedirs(path, exist_ok=True)
        flat = {}
        meta: Dict[str, Any] = {}
        for name, arr in columns.items():
            arr = np.asarray(arr)
            if arr.ndim > 1:  # parquet columns are 1-D; flatten + remember
                meta[name] = list(arr.shape[1:])
                flat[name] = list(arr.reshape(arr.shape[0], -1))
            else:
                flat[name] = arr
        table = pa.table(flat)
        import json
        table = table.replace_schema_metadata(
            {b"horovod_tpu_shapes": json.dumps(meta).encode()})
        out = os.path.join(path, "part-00000.parquet")
        pq.write_table(table, out)
        return path

    def read_parquet(self, path: str) -> Dict[str, np.ndarray]:
        """Read back a dataset written by write_parquet, restoring shapes
        (decoder shared with ParquetDataLoader: data/loader.decode_table)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        from ..data.loader import decode_table, list_parquet_files
        return decode_table(pa.concat_tables(
            [pq.read_table(f) for f in list_parquet_files(path)]))

    # ---- checkpoints -----------------------------------------------------
    def save_checkpoint(self, run_id: str, payload: bytes,
                        name: str = "checkpoint.bin") -> str:
        d = self.get_checkpoint_path(run_id)
        os.makedirs(d, exist_ok=True)
        p = os.path.join(d, name)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, p)
        return p

    def read_checkpoint(self, run_id: str,
                        name: str = "checkpoint.bin") -> Optional[bytes]:
        p = os.path.join(self.get_checkpoint_path(run_id), name)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    # ---- run logs --------------------------------------------------------
    def save_log(self, run_id: str, payload: bytes) -> str:
        d = self.get_logs_path(run_id)
        os.makedirs(d, exist_ok=True)
        p = os.path.join(d, "history.bin")
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, p)
        return p

    def read_log(self, run_id: str) -> Optional[bytes]:
        p = os.path.join(self.get_logs_path(run_id), "history.bin")
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()


LocalStore = FilesystemStore


class DBFSLocalStore(FilesystemStore):
    """Databricks DBFS store (reference: store.py DBFSLocalStore): paths
    given as ``dbfs:/...`` are accessed through the ``/dbfs/`` fuse mount.
    Everything else is FilesystemStore — proving the Store abstraction is
    a path-translation boundary, exactly as in the reference."""

    def __init__(self, prefix_path: str, **kwargs):
        super().__init__(self.normalize_path(prefix_path), **kwargs)

    @staticmethod
    def normalize_path(path: str) -> str:
        """``dbfs:/foo`` -> ``/dbfs/foo`` (reference:
        store.py DBFSLocalStore.normalize_path)."""
        if path.startswith("dbfs:/"):
            return "/dbfs/" + path[len("dbfs:/"):].lstrip("/")
        return path
