"""Data prepare: DataFrame/chunks -> partitioned parquet in the Store.

Reference: horovod/spark/common/util.py prepare_data — the reference
writes the training DataFrame as a DISTRIBUTED Spark job (each partition
becomes parquet written by its executor) and workers stream it back with
petastorm readers; the driver never materializes the dataset.

Three input shapes, one output contract (a ``part-NNNNN.parquet``
dataset per split, readable by any of the parquet loaders):

* a pyspark DataFrame (anything with ``.rdd``): partition-parallel —
  ``rdd.mapPartitionsWithIndex`` runs :class:`_PartitionWriter` on the
  executors, each writing its own part files straight to the Store
  (namespaced part numbers, no coordination);
* an iterator/generator of column-dict chunks: the driver streams
  chunk-by-chunk through a part writer — bounded memory for datasets
  bigger than driver RAM;
* an in-memory column dict / pandas DataFrame: split + one-shot write
  (small-data path, semantics identical to the pre-partitioned
  estimator).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .store import FilesystemStore, Store


def _as_columns(df, feature_cols=None, label_cols=None, extra_cols=()
                ) -> Dict[str, np.ndarray]:
    """Accept a column dict, or a pyspark/pandas DataFrame.  With no column
    lists, ALL columns convert (transform() must not drop id/label columns
    the caller wants to keep alongside predictions)."""
    if isinstance(df, dict):
        return {k: np.asarray(v) for k, v in df.items()}
    if hasattr(df, "toPandas"):  # pyspark DataFrame (transform-time only)
        df = df.toPandas()
    cols = (list(feature_cols or []) + list(label_cols or []) +
            list(extra_cols)) or list(df.columns)
    return {c: np.stack(df[c].to_numpy()) for c in cols}


def _split_validation(cols: Dict[str, np.ndarray], validation,
                      seed: int = 0):
    """Split a column dict into (train, val) following the reference's
    ``validation`` param (common/params.py): a float in (0, 1) holds out a
    random fraction; a string names a boolean column marking val rows
    (the column itself is dropped from both splits).  Returns val=None
    when no validation was requested or the split came out empty."""
    if not validation:
        return cols, None
    if isinstance(validation, str):
        if validation not in cols:
            raise ValueError(f"validation column {validation!r} not in "
                             f"columns {sorted(cols)}")
        mask = np.asarray(cols[validation]).astype(bool).ravel()
        base = {k: np.asarray(v) for k, v in cols.items()
                if k != validation}
    else:
        frac = float(validation)
        if not 0.0 < frac < 1.0:
            raise ValueError(f"validation fraction must be in (0,1), got "
                             f"{frac}")
        n = len(next(iter(cols.values())))
        mask = np.random.RandomState(seed).rand(n) < frac
        base = {k: np.asarray(v) for k, v in cols.items()}
    train = {k: v[~mask] for k, v in base.items()}
    val = {k: v[mask] for k, v in base.items()}
    return train, (val if mask.any() else None)


def _row_to_dict(row) -> Dict:
    """A pyspark Row (has asDict) or a plain mapping."""
    if hasattr(row, "asDict"):
        return row.asDict()
    return dict(row)


def _write_split_chunk(tw, vw, cols: Dict[str, np.ndarray], columns,
                       validation, seed: int) -> Tuple[int, int]:
    """Select columns, split train/val, append non-empty parts; returns
    (train_rows, val_rows).  The ONE chunk-level write both the
    partition-parallel and chunk-iterator prepare paths share."""
    if columns:
        cols = {c: cols[c] for c in columns}
    tr, va = _split_validation(cols, validation, seed=seed)
    t = len(next(iter(tr.values())))
    v = 0
    if t:
        tw.write(tr)
    if va is not None:
        v = len(next(iter(va.values())))
        vw.write(va)
    return t, v


class _PartitionWriter:
    """Picklable per-partition prepare task: buffer rows, split
    train/val, flush every ``chunk_rows`` as a part file.  Part numbers
    are namespaced by partition index (ParquetPartWriter.base_index), so
    N executors append to the same dataset without coordination —
    the reference's distributed Spark write, minus petastorm."""

    def __init__(self, store: FilesystemStore, train_path: str,
                 val_path: str, columns: List[str], validation, seed: int,
                 chunk_rows: int):
        self.store = store
        self.train_path = train_path
        self.val_path = val_path
        self.columns = columns
        self.validation = validation
        self.seed = seed
        self.chunk_rows = chunk_rows

    def __call__(self, idx: int, it) -> Iterable[Tuple[int, int, int]]:
        tw = self.store.part_writer(self.train_path, overwrite=False,
                                    base_index=idx)
        vw = self.store.part_writer(self.val_path, overwrite=False,
                                    base_index=idx)
        buf: List[Dict] = []
        counts = [0, 0]  # train, val rows
        chunk_i = 0

        def flush():
            nonlocal buf, chunk_i
            if not buf:
                return
            cols = {c: np.stack([np.asarray(r[c]) for r in buf])
                    for c in (self.columns or sorted(buf[0]))}
            # Seeded per (partition, chunk): a re-run of the same layout
            # reproduces the same split.
            t, v = _write_split_chunk(
                tw, vw, cols, None, self.validation,
                # numpy seeds must fit 32 bits; the mix can exceed it on
                # wide DataFrames (idx >= ~4295), so reduce mod 2**32.
                seed=(self.seed + 1000003 * idx + chunk_i) % (1 << 32))
            chunk_i += 1
            counts[0] += t
            counts[1] += v
            buf = []

        for row in it:
            buf.append(_row_to_dict(row))
            if len(buf) >= self.chunk_rows:
                flush()
        flush()
        yield (idx, counts[0], counts[1])


def prepare_data(store: Store, df, feature_cols, label_cols,
                 validation=None, seed: int = 0,
                 chunk_rows: int = 65536,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 run_id: str = "run0",
                 extra_cols: Sequence[str] = ()) -> Tuple[str, Optional[str]]:
    """Materialize ``df`` into the Store as train (+ optional val)
    parquet datasets; returns ``(train_path, val_path_or_None)``.

    Dispatch is by input shape (module docstring); the DataFrame path is
    partition-parallel and the chunk-iterator path is bounded-memory —
    only the plain-dict path assumes the data fits in driver memory
    (because it already does)."""
    train_path = train_path or store.get_train_data_path(run_id)
    val_path = val_path or store.get_val_data_path(run_id)
    extra = (validation,) if isinstance(validation, str) else ()
    columns = (list(feature_cols or []) + list(label_cols or []) +
               list(extra) + [c for c in extra_cols if c])

    if hasattr(df, "rdd"):  # pyspark DataFrame: distributed write
        # Clear both datasets once on the driver; executors append.
        store.part_writer(train_path, overwrite=True)
        store.part_writer(val_path, overwrite=True)
        task = _PartitionWriter(store, train_path, val_path, columns,
                                validation, seed, chunk_rows)
        counts = df.rdd.mapPartitionsWithIndex(task).collect()
        train_rows = sum(t for _, t, _ in counts)
        val_rows = sum(v for _, _, v in counts)
        if train_rows == 0:
            raise ValueError("prepare_data: DataFrame produced 0 train "
                             "rows")
        return train_path, (val_path if val_rows else None)

    if not isinstance(df, dict) and not hasattr(df, "toPandas") and \
            not hasattr(df, "columns") and hasattr(df, "__iter__"):
        # iterator/generator of column-dict chunks: stream through ONE
        # writer — driver memory stays bounded by the chunk size.
        tw = store.part_writer(train_path, overwrite=True)
        vw = store.part_writer(val_path, overwrite=True)
        val_rows = 0
        train_rows = 0
        for i, chunk in enumerate(df):
            cols = {k: np.asarray(v) for k, v in chunk.items()}
            t, v = _write_split_chunk(tw, vw, cols, columns, validation,
                                      seed=seed + i)
            train_rows += t
            val_rows += v
        if train_rows == 0:
            raise ValueError("prepare_data: chunk stream produced 0 train "
                             "rows")
        return train_path, (val_path if val_rows else None)

    # in-memory dict / pandas DataFrame (small-data path)
    cols = _as_columns(df, feature_cols, label_cols,
                       extra_cols=tuple(extra)
                       + tuple(c for c in extra_cols if c))
    train_cols, val_cols = _split_validation(cols, validation, seed)
    store.write_parquet(train_path, train_cols)
    if val_cols is not None:
        store.write_parquet(val_path, val_cols)
        return train_path, val_path
    return train_path, None
