"""horovod_tpu.spark.run: distributed training on a cluster scheduler.

Reference: horovod/spark/runner.py:47-304 — ``run(fn, num_proc)`` starts a
barrier Spark job whose tasks host the training function; the driver
assigns ranks by task, sets up the rendezvous, and collects results.

TPU-native shape: the scheduler's ONLY job is process placement.  The
orchestration core (`_run_on_executor`) is scheduler-agnostic: it brings
up the rendezvous/coordinator env exactly like hvdrun and hands each task
a (rank, env, fn) triple.  ``SparkTaskExecutor`` (gated on pyspark)
supplies placement via a barrier RDD stage; ``LocalTaskExecutor`` places
on local processes — it backs the test tier the same way the reference
tests Spark in local mode (reference: test/utils/spark_common.py:234).
"""

from __future__ import annotations

import multiprocessing
import os
import pickle
import socket
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..runner.hosts import env_for_tasks


class TaskExecutor:
    """Placement backend: run one python callable per task slot.

    ``task_fn(index, hostnames)`` receives the task's index and the full
    per-task hostname list (index-aligned), so ranks — including LOCAL and
    CROSS coordinates on multi-host clusters — are derived from the actual
    placement, not guessed."""

    def num_tasks(self) -> int:
        raise NotImplementedError

    def run_tasks(self, task_fn: Callable[[int, List[str]], Any]
                  ) -> List[Any]:
        raise NotImplementedError

    def with_num_tasks(self, n: int) -> "TaskExecutor":
        """Rebuild this executor at a different task count, preserving its
        configuration — how elastic resets shrink the placement layer.
        Subclasses with extra constructor state must override."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support elastic resizing; "
            "override with_num_tasks(n)")


def _local_task_entry(index: int, payload: bytes, hostnames, q):
    try:
        # Before unpickling: loads() imports the fn's module, which may
        # import keras and initialize a backend — bind the platform the
        # parent asked for first (see utils/platform.py).
        from ..utils.platform import apply_env_platform
        apply_env_platform()
        fn = pickle.loads(payload)
        q.put((index, ("ok", fn(index, hostnames))))
    except BaseException as e:  # surface remote errors with traceback
        q.put((index, ("error", f"{e}\n{traceback.format_exc()}")))


class LocalTaskExecutor(TaskExecutor):
    """Local-process placement (the reference's spark local-mode analog)."""

    def __init__(self, num_tasks: int, start_method: str = "spawn"):
        self._n = num_tasks
        self._start_method = start_method
        self._ctx = multiprocessing.get_context(start_method)

    def num_tasks(self) -> int:
        return self._n

    def with_num_tasks(self, n: int) -> "LocalTaskExecutor":
        return LocalTaskExecutor(n, start_method=self._start_method)

    def run_tasks(self, task_fn: Callable[[int, List[str]], Any]
                  ) -> List[Any]:
        q = self._ctx.Queue()
        payload = pickle.dumps(task_fn)
        hostnames = [socket.gethostname()] * self._n
        procs = [self._ctx.Process(target=_local_task_entry,
                                   args=(i, payload, hostnames, q))
                 for i in range(self._n)]
        for p in procs:
            p.start()
        results: List[Any] = [None] * self._n
        error = None
        got = 0
        while got < self._n:
            try:
                i, (status, val) = q.get(timeout=1.0)
            except Exception:  # queue.Empty: check worker liveness
                dead = [i for i, p in enumerate(procs)
                        if not p.is_alive() and p.exitcode not in (0, None)
                        and results[i] is None]
                if dead:
                    for p in procs:
                        p.terminate()
                    raise RuntimeError(
                        f"task(s) {dead} died without reporting a result "
                        f"(exitcodes "
                        f"{[procs[i].exitcode for i in dead]}) — native "
                        "crash or OOM kill?")
                continue
            got += 1
            if status == "error" and error is None:
                error = (i, val)
            results[i] = val
        for p in procs:
            p.join()
        if error is not None:
            raise RuntimeError(f"task {error[0]} failed: {error[1]}")
        return results


class _spark_partition_entry:
    """Runs inside a barrier task: exchange hostnames, then run.  A
    picklable class (not a closure) so plain pickle suffices — real
    pyspark cloudpickles closures, but nothing here needs that."""

    def __init__(self, task_fn):
        self.task_fn = task_fn

    def __call__(self, it):
        from pyspark import BarrierTaskContext
        ctx = BarrierTaskContext.get()
        hostnames = ctx.allGather(socket.gethostname())
        return [self.task_fn(ctx.partitionId(), list(hostnames))]


class SparkTaskExecutor(TaskExecutor):
    """Barrier-stage placement on a live SparkContext (reference:
    spark/runner.py:47-117 uses a Spark job whose tasks host services);
    hostnames are exchanged with BarrierTaskContext.allGather.  Requires
    pyspark at call time."""

    def __init__(self, num_tasks: Optional[int] = None, spark_context=None):
        try:
            import pyspark  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "horovod_tpu.spark.run on a real cluster requires pyspark; "
                "pass executor=LocalTaskExecutor(n) for local mode"
            ) from e
        from pyspark import SparkContext
        self._sc = spark_context or SparkContext.getOrCreate()
        self._n = num_tasks or int(
            self._sc.getConf().get("spark.executor.instances", "1"))

    def num_tasks(self) -> int:
        return self._n

    def with_num_tasks(self, n: int) -> "SparkTaskExecutor":
        return SparkTaskExecutor(n, spark_context=self._sc)

    def run_tasks(self, task_fn: Callable[[int, List[str]], Any]
                  ) -> List[Any]:
        rdd = self._sc.parallelize(range(self._n), self._n)
        return (rdd.barrier()
                .mapPartitions(_spark_partition_entry(task_fn))
                .collect())


def run(fn: Callable, args: Sequence[Any] = (), kwargs: Dict = None,
        num_proc: Optional[int] = None,
        executor: Optional[TaskExecutor] = None,
        env: Optional[Dict[str, str]] = None,
        coordinator_port: int = 29511,
        use_spark: Optional[bool] = None) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` distributed workers;
    returns the per-rank results as a list (reference: spark/runner.py:195
    returns one result per Spark task).

    With no ``executor``, uses Spark when pyspark is importable (or
    ``use_spark=True``), else local processes."""
    kwargs = kwargs or {}
    if executor is None:
        want_spark = use_spark
        if want_spark is None:
            try:
                import pyspark  # noqa: F401
                want_spark = True
            except ImportError:
                want_spark = False
        executor = (SparkTaskExecutor(num_proc) if want_spark
                    else LocalTaskExecutor(num_proc or 1))
    base_env = {k: v for k, v in (env or {}).items()}
    task = _Task(fn, tuple(args), dict(kwargs), coordinator_port, base_env)
    return executor.run_tasks(task)


def run_elastic(fn: Callable, args: Sequence[Any] = (),
                kwargs: Dict = None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None,
                max_np: Optional[int] = None,
                start_timeout: Optional[float] = None,
                elastic_timeout: Optional[float] = None,
                reset_limit: Optional[int] = 3,
                env: Optional[Dict[str, str]] = None,
                executor_factory: Optional[Callable] = None,
                coordinator_port: int = 29511,
                verbose: int = 1) -> List[Any]:
    """Elastic training on a cluster scheduler (reference:
    spark/runner.py:306-334 run_elastic).

    TPU-native reshape of the reference's gloo-rendezvous elasticity: a
    jax.distributed mesh cannot shrink in place, so each membership
    change is a RESET — the barrier job is relaunched at the surviving
    worker count (bounded below by ``min_np``) and the training function
    resumes from its last durable checkpoint (the estimator tasks'
    per-epoch envelope).  ``reset_limit`` bounds relaunches exactly like
    the reference's param; ``start_timeout``/``elastic_timeout`` are
    accepted for signature parity (process spawn on a barrier stage is
    scheduler-supervised, so there is no separate registration window to
    time out).

    ``executor_factory(n)`` rebuilds the placement backend at size n per
    attempt; with None, pyspark (when importable) or local processes are
    chosen per attempt exactly as :func:`run` does.
    """
    del start_timeout, elastic_timeout  # signature parity; see docstring
    n = num_proc or 1
    lo = max(1, min_np or 1)
    if max_np is not None:
        n = min(n, max_np)
    if n < lo:
        raise ValueError(f"num_proc={n} below min_np={lo}")
    resets = 0
    while True:
        executor = executor_factory(n) if executor_factory else None
        try:
            return run(fn, args=args, kwargs=kwargs, num_proc=n,
                       executor=executor, env=env,
                       coordinator_port=coordinator_port)
        # Broad on purpose: task death surfaces as RuntimeError from
        # LocalTaskExecutor but as Py4J/Spark exception types from a real
        # barrier stage — all of them mean "reset and shrink".
        except Exception as e:
            resets += 1
            if reset_limit is not None and resets > reset_limit:
                raise RuntimeError(
                    f"elastic job failed after {resets - 1} resets "
                    f"(reset_limit={reset_limit})") from e
            n = max(lo, n - 1)
            if verbose:
                import sys as _sys
                print(f"[spark.run_elastic] task failure: {e}; reset "
                      f"#{resets} relaunching with np={n}",
                      file=_sys.stderr)


class _Task:
    """Picklable per-slot entry: derive this task's rank env from the
    exchanged hostname list, set it, run fn (reference: the mpirun/gloo
    exec_fn modules, spark/task/*_exec_fn.py).  The coordinator lands on
    rank 0's host (env_for_tasks), which every task derives identically
    from the same hostname list."""

    def __init__(self, fn, args, kwargs, coordinator_port, base_env):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.coordinator_port = coordinator_port
        self.base_env = base_env

    def __call__(self, index: int, hostnames: List[str]):
        env = dict(self.base_env)
        env.update(env_for_tasks(hostnames, self.coordinator_port)[index])
        os.environ.update(env)
        from ..utils.platform import apply_env_platform
        apply_env_platform()
        return self.fn(*self.args, **self.kwargs)
