"""Estimator API: fit on a dataset, get back a servable model.

Reference: horovod/spark/common/estimator.py:25-103 — ``Estimator.fit(df)``
persists the DataFrame as parquet in the Store, trains inside
horovod-on-spark workers with petastorm readers, checkpoints per epoch,
and returns a Model transformer.

TPU-native reshape: data arrives as a pyspark DataFrame (prepared as a
DISTRIBUTED partition-parallel parquet write — spark/prepare.py), an
iterator of column-dict chunks (streamed through the driver with bounded
memory), or an in-memory column dict; training runs through
``horovod_tpu.spark.run`` on any TaskExecutor, workers STREAM their
shard row-group by row-group (StreamingParquetDataLoader), rank 0
checkpoints to the Store each epoch, and ``fit`` returns a
KerasModel/TorchModel wrapper exposing ``transform``.
"""

from __future__ import annotations

import io
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..data.loader import ParquetDataLoader, StreamingParquetDataLoader
from .prepare import _as_columns, _split_validation, prepare_data
from .runner import TaskExecutor, run as spark_run
from .store import Store


# ---------------------------------------------------------------------------
# metrics + per-epoch checkpoint envelope (shared by every train task)

_NAMED_METRICS = {
    "mse": lambda p, y: float(np.mean((p - y) ** 2)),
    "mae": lambda p, y: float(np.mean(np.abs(p - y))),
    "accuracy": lambda p, y: float(np.mean(
        (np.argmax(p, axis=-1) if p.ndim > 1 and p.shape[-1] > 1
         else (p > 0.5).astype(np.int64)).ravel() ==
        np.asarray(y).ravel().astype(np.int64))),
}


def _resolve_metrics(metrics) -> List:
    """Names or callables -> [(name, fn(pred, y) -> float)] (reference:
    common/params.py metrics param; keras/torch estimators accept both)."""
    out = []
    for m in metrics or ():
        if callable(m):
            out.append((getattr(m, "__name__", "metric"), m))
        elif m in _NAMED_METRICS:
            out.append((m, _NAMED_METRICS[m]))
        else:
            raise ValueError(f"unknown metric {m!r}; named metrics: "
                             f"{sorted(_NAMED_METRICS)}")
    return out


def _save_epoch_checkpoint(store: Store, run_id: str, epoch: int,
                           model_bytes: bytes, history: Dict) -> None:
    """Durable per-epoch envelope: {epoch, model, history} (reference:
    estimator per-epoch ckpt via keras callbacks / remote.py; resume keys
    off the stored epoch)."""
    store.save_checkpoint(run_id, pickle.dumps(
        {"fmt": 1, "epoch": int(epoch), "model": model_bytes,
         "history": history}))


def _load_epoch_checkpoint(store: Store, run_id: str) -> Optional[Dict]:
    """Read the envelope back; legacy raw payloads (pre-envelope) load as
    epoch=-1 so resume starts from scratch but serving still works."""
    payload = store.read_checkpoint(run_id)
    if payload is None:
        return None
    try:
        obj = pickle.loads(payload)
    except Exception:
        return {"fmt": 0, "epoch": -1, "model": payload, "history": {}}
    if isinstance(obj, dict) and "model" in obj and "epoch" in obj:
        return obj
    return {"fmt": 0, "epoch": -1, "model": payload, "history": {}}


def _make_train_loader(store, path: str, batch_size: int, rank: int,
                       size: int, opts: Dict):
    """Worker-side train reader honoring the data params
    (shuffle_buffer_size -> ShuffleBufferLoader wrap; streaming base)."""
    loader = StreamingParquetDataLoader(path, batch_size, rank=rank,
                                        num_workers=size, fs=store.fs)
    if opts.get("shuffle_buffer_size"):
        from ..data.loader import ShuffleBufferLoader
        loader = ShuffleBufferLoader(loader, opts["shuffle_buffer_size"],
                                     seed=opts.get("seed", 0))
    return loader


def _iter_train(loader, epoch: int, opts: Dict):
    """One epoch's batches: per-epoch reshuffle (set_epoch), the
    train_steps_per_epoch cap, and the transformation_fn hook."""
    import itertools
    if hasattr(loader, "set_epoch"):
        loader.set_epoch(epoch)
    cap = opts.get("train_steps_per_epoch")
    transform = opts.get("transformation_fn")
    it = iter(loader) if cap is None else itertools.islice(loader, cap)
    for batch in it:  # islice never pulls the batch past the cap
        yield transform(batch) if transform else batch


def _iter_val_batches(val_path: str, batch_size: int, rank: int,
                      size: int, fs=None, opts: Optional[Dict] = None):
    """This worker's shard of the val set as (x, y) pairs, honoring the
    shared data knobs (val_batch_size, transformation_fn,
    validation_steps_per_epoch) — ONE definition for the predict-metrics
    path and the lightning validation_step path."""
    import itertools
    opts = opts or {}
    loader = ParquetDataLoader(val_path,
                               opts.get("val_batch_size") or batch_size,
                               rank=rank, num_workers=size, fs=fs)
    transform = opts.get("transformation_fn")
    val_cap = opts.get("validation_steps_per_epoch")
    it = iter(loader) if val_cap is None else \
        itertools.islice(loader, val_cap)
    for batch in it:
        if transform:
            batch = transform(batch)
        yield batch


def _eval_metrics(predict: Callable, val_path: Optional[str],
                  feature_cols, label_cols, metrics, batch_size: int,
                  rank: int, size: int, sync, fs=None,
                  opts: Optional[Dict] = None) -> Dict[str, float]:
    """Per-epoch validation metrics over the (sharded) val dataset.  The
    cross-worker combine is exact: Average(weighted sums)/Average(counts)
    equals the global weighted mean regardless of shard imbalance."""
    if val_path is None or not metrics:
        return {}
    sums = np.zeros((len(metrics) + 1,), np.float64)
    for batch in _iter_val_batches(val_path, batch_size, rank, size,
                                   fs=fs, opts=opts):
        x, y = _assemble_batch(batch, feature_cols, label_cols)
        p = np.asarray(predict(x))
        for j, (_, fn) in enumerate(metrics):
            sums[j] += fn(p, y) * len(x)
        sums[-1] += len(x)
    if size > 1:
        sums = np.asarray(sync([sums])[0], np.float64)
    denom = max(sums[-1], 1.0)
    return {f"val_{name}": float(sums[j] / denom)
            for j, (name, _) in enumerate(metrics)}


def _epoch_driver(store: Store, run_id: str, epochs: int, metrics,
                  batch_size: int, feature_cols, label_cols,
                  rank: int, size: int, sync,
                  val_path: Optional[str], *,
                  restore: Callable[[bytes], None],
                  serialize: Callable[[], bytes],
                  train_epoch: Callable[[int], float],
                  predict: Callable[[np.ndarray], np.ndarray],
                  cold_start: Optional[Callable[[], None]] = None,
                  opts: Optional[Dict] = None,
                  should_stop: Optional[Callable[[], bool]] = None,
                  extra_eval: Optional[Callable[[int], Dict]] = None
                  ) -> Dict:
    """The one epoch loop every train task shares: resume from the stored
    envelope (or run ``cold_start`` — typically the initial cross-worker
    parameter sync), then per epoch: train, eval val metrics, rank-0
    checkpoint + history log, failure-injection hook.  Framework
    specifics come in as closures (restore/serialize/train_epoch/predict).
    """
    metrics = _resolve_metrics(metrics)
    start_epoch = 0
    history: Dict[str, List[float]] = {}
    env = _load_epoch_checkpoint(store, run_id)
    if env is not None and env["epoch"] >= 0:
        restore(env["model"])
        start_epoch = env["epoch"] + 1
        history = dict(env.get("history") or {})
    elif cold_start is not None:
        cold_start()
    opts = opts or {}
    for epoch in range(start_epoch, epochs):
        history.setdefault("train_loss", []).append(train_epoch(epoch))
        for k, v in _eval_metrics(predict, val_path, feature_cols,
                                  label_cols, metrics, batch_size, rank,
                                  size, sync, fs=store.fs,
                                  opts=opts).items():
            history.setdefault(k, []).append(v)
        if extra_eval is not None:
            # framework-specific per-epoch eval (e.g. lightning's
            # validation_step protocol) merged into the same history
            for k, v in (extra_eval(epoch) or {}).items():
                history.setdefault(k, []).append(v)
        if rank == 0 and opts.get("verbose"):
            parts = [f"{k}={v[-1]:.4f}" for k, v in history.items()]
            print(f"[estimator] epoch {epoch}: " + " ".join(parts),
                  flush=True)
        if rank == 0:
            _save_epoch_checkpoint(store, run_id, epoch, serialize(),
                                   history)
            store.save_log(run_id, pickle.dumps(history))
        _maybe_inject_fault(rank, epoch)
        if should_stop is not None and should_stop():
            break  # e.g. keras EarlyStopping set model.stop_training
    return history


def _maybe_inject_fault(rank: int, epoch: int) -> None:
    """Failure-injection hook for elastic tests: when
    ``HOROVOD_SPARK_FAULT='<rank>,<epoch>,<marker_path>'`` is set and the
    marker file does not exist yet, the matching worker hard-exits after
    that epoch's checkpoint — once.  The marker makes the relaunched job
    run clean (the integration tier's analog of the reference's
    elastic_common.py host-mutation hooks)."""
    spec = os.environ.get("HOROVOD_SPARK_FAULT")
    if not spec:
        return
    frank, fepoch, marker = spec.split(",", 2)
    if rank == int(frank) and epoch == int(fepoch) and \
            not os.path.exists(marker):
        with open(marker, "w") as f:
            f.write(f"fault injected at rank={rank} epoch={epoch}\n")
        os._exit(17)


class EstimatorModel:
    """Fitted-model transformer (reference: HorovodModel,
    common/estimator.py:97-103).  ``history`` carries the per-epoch
    train/val series recorded by the train task."""

    def __init__(self, predict_fn: Callable[[np.ndarray], np.ndarray],
                 feature_cols: Sequence[str], output_col: str = "predict",
                 history: Optional[Dict[str, List[float]]] = None):
        self._predict = predict_fn
        self.feature_cols = list(feature_cols)
        self.output_col = output_col
        self.history = dict(history or {})

    def transform(self, df):
        cols = _as_columns(df)  # keep every input column in the output
        x = np.concatenate(
            [cols[c].reshape(len(cols[c]), -1) for c in self.feature_cols],
            axis=1)
        out = dict(cols)
        out[self.output_col] = self._predict(x)
        return out


class Estimator:
    """Scheduler-agnostic estimator core (reference: estimator.py:25-96).

    Subclasses supply ``_train_task`` (a picklable callable run per worker)
    and ``_load_model`` (driver-side: bytes -> predict_fn).
    """

    def __init__(self, store: Store, num_proc: int = 1,
                 feature_cols: Sequence[str] = ("features",),
                 label_cols: Sequence[str] = ("label",),
                 batch_size: int = 32, epochs: int = 1,
                 run_id: str = "run0",
                 executor: Optional[TaskExecutor] = None,
                 validation=None,
                 metrics: Sequence = (),
                 loss=None,
                 seed: int = 0,
                 shuffle_buffer_size: int = 0,
                 train_steps_per_epoch: Optional[int] = None,
                 validation_steps_per_epoch: Optional[int] = None,
                 val_batch_size: Optional[int] = None,
                 transformation_fn: Optional[Callable] = None,
                 sample_weight_col: Optional[str] = None,
                 verbose: int = 0):
        """Reference param parity (spark/common/params.py): beyond the
        core fit knobs, ``shuffle_buffer_size`` streams a bounded-memory
        shuffle over each worker's shard (petastorm semantics),
        ``train/validation_steps_per_epoch`` cap batches per epoch,
        ``val_batch_size`` overrides the eval batch,
        ``transformation_fn`` rewrites each batch dict before assembly
        (the reference's per-row transform hook, applied batchwise),
        ``sample_weight_col`` names a per-row weight column applied to
        the training loss (reference: params.py sample_weight_col;
        validation metrics stay unweighted, matching the reference's
        evaluation), and ``verbose`` prints rank-0 per-epoch progress.
        Petastorm
        reader-pool knobs (reader_pool_type, *_reader_num_workers,
        partitions_per_process) have no analog — the streaming loaders
        read row groups directly."""
        self.store = store
        self.num_proc = num_proc
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.run_id = run_id
        self.executor = executor
        self.validation = validation
        self.metrics = list(metrics)
        self.loss = loss
        self.seed = seed
        if int(shuffle_buffer_size) < 0:
            raise ValueError(f"shuffle_buffer_size must be >= 0, got "
                             f"{shuffle_buffer_size}")
        self.shuffle_buffer_size = int(shuffle_buffer_size)
        self.train_steps_per_epoch = train_steps_per_epoch
        self.validation_steps_per_epoch = validation_steps_per_epoch
        self.val_batch_size = val_batch_size
        self.transformation_fn = transformation_fn
        self.sample_weight_col = sample_weight_col
        self.verbose = verbose
        _resolve_metrics(self.metrics)  # fail fast on unknown names

    def _data_opts(self) -> Dict:
        """The per-worker data/reporting params every train task shares
        (reference: spark/common/params.py surface)."""
        return {"shuffle_buffer_size": self.shuffle_buffer_size,
                "train_steps_per_epoch": self.train_steps_per_epoch,
                "validation_steps_per_epoch": self.validation_steps_per_epoch,
                "val_batch_size": self.val_batch_size,
                "transformation_fn": self.transformation_fn,
                "sample_weight_col": self.sample_weight_col,
                "verbose": self.verbose,
                "seed": self.seed}

    # -- subclass surface --------------------------------------------------
    def _make_train_task(self) -> Callable:
        raise NotImplementedError

    def _load_model(self, payload: bytes) -> Callable:
        raise NotImplementedError

    # -- the fit flow ------------------------------------------------------
    def has_checkpoint(self) -> bool:
        """Resume support (reference: estimator.py:91-96 _has_checkpoint,
        made public here — user code legitimately branches on it): when a
        checkpoint exists, the next fit/fit_on_parquet CONTINUES training
        from the stored epoch instead of starting over."""
        return self.store.read_checkpoint(self.run_id) is not None

    # reference-parity spelling
    _has_checkpoint = has_checkpoint

    def fit(self, df, elastic: bool = False, min_np: int = 1,
            reset_limit: Optional[int] = 3) -> EstimatorModel:
        """Persist df (with optional validation split) to the Store, train
        on ``num_proc`` workers, return the fitted transformer (reference:
        common/estimator.py:25-96 _fit -> prepare_data ->
        _fit_on_prepared_data).

        ``elastic=True`` routes the job through :func:`run_elastic` —
        task failures shrink the worker set (down to ``min_np``) and
        training resumes from the last epoch checkpoint.

        ``df`` may be a pyspark DataFrame (prepared partition-parallel on
        the executors — the driver never materializes it), an iterator of
        column-dict chunks (streamed, bounded driver memory), or an
        in-memory column dict / pandas DataFrame (one-shot write)."""
        train_path, val_path = prepare_data(
            self.store, df, self.feature_cols, self.label_cols,
            validation=self.validation, seed=self.seed,
            run_id=self.run_id,
            extra_cols=(self.sample_weight_col,)
            if self.sample_weight_col else ())
        return self._fit_on_paths(train_path, val_path, elastic=elastic,
                                  min_np=min_np, reset_limit=reset_limit)

    def fit_on_parquet(self, elastic: bool = False, min_np: int = 1,
                       reset_limit: Optional[int] = 3) -> EstimatorModel:
        """Train on data already materialized in the Store (reference:
        estimator.fit_on_parquet:37-48) — the re-fit path after a driver
        restart, skipping the prepare step."""
        train_path = self.store.get_train_data_path(self.run_id)
        if not self.store.is_parquet_dataset(train_path):
            raise ValueError(f"no parquet dataset at {train_path}; run "
                             "fit() once (or write the dataset) first")
        val_path = self.store.get_val_data_path(self.run_id)
        if not self.store.is_parquet_dataset(val_path):
            val_path = None
        return self._fit_on_paths(train_path, val_path, elastic=elastic,
                                  min_np=min_np, reset_limit=reset_limit)

    def _fit_on_paths(self, train_path: str, val_path: Optional[str],
                      elastic: bool, min_np: int,
                      reset_limit: Optional[int]) -> EstimatorModel:
        task = self._make_train_task()
        if elastic:
            from .runner import run_elastic
            run_elastic(task, args=(train_path, val_path),
                        num_proc=self.num_proc, min_np=min_np,
                        reset_limit=reset_limit,
                        executor_factory=self._executor_factory())
        else:
            spark_run(task, args=(train_path, val_path),
                      num_proc=self.num_proc, executor=self.executor)

        env = _load_epoch_checkpoint(self.store, self.run_id)
        if env is None:
            raise RuntimeError("training produced no checkpoint")
        return EstimatorModel(self._load_model(env["model"]),
                              self.feature_cols,
                              history=env.get("history"))

    def _executor_factory(self):
        """How run_elastic rebuilds the placement layer at a smaller size
        after a failure: the executor's own ``with_num_tasks`` preserves
        its configuration (start_method, spark context, ...)."""
        if self.executor is None:
            return None
        return self.executor.with_num_tasks


def _grad_sync_fn():
    """Cross-worker average over the REAL data plane when the runner
    exported a coordinator (size > 1): hvd.init() assembles the mesh via
    jax.distributed and gradients ride an eager allreduce.  Single-worker
    runs skip the bring-up."""
    size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
    if size <= 1:
        return lambda gs: gs
    import horovod_tpu as hvd
    from horovod_tpu.ops.collectives import process_local
    hvd.init()

    def sync(gs):
        """Average a LIST of arrays in one fused grouped collective (one
        dispatch per batch, not one per parameter)."""
        outs = hvd.grouped_allreduce(
            [process_local(np.asarray(g)) for g in gs], op=hvd.Average)
        return [np.asarray(o, dtype=np.asarray(g).dtype)
                for o, g in zip(outs, gs)]
    return sync


def _torch_sync_params(model, sync) -> None:
    """All workers start from identical weights (rank-0 convention): ONE
    fused sync of the initial parameters."""
    import torch
    avgs = sync([p.detach().numpy() for p in model.parameters()])
    with torch.no_grad():
        for p, a in zip(model.parameters(), avgs):
            p.copy_(torch.from_numpy(np.ascontiguousarray(a)))


def _torch_sync_grads(model, sync) -> None:
    """ONE fused grouped collective per batch, not one per parameter."""
    import torch
    with_grads = [p for p in model.parameters() if p.grad is not None]
    gs = sync([p.grad.numpy() for p in with_grads])
    for p, g in zip(with_grads, gs):
        p.grad.copy_(torch.from_numpy(np.ascontiguousarray(g)))


def _torch_eval_predict(model, x: np.ndarray) -> np.ndarray:
    """One forward in eval mode, restoring train mode after (the val-
    metrics predict closure shared by the torch and lightning tasks)."""
    import torch
    model.eval()
    with torch.no_grad():
        out = model(torch.from_numpy(
            np.ascontiguousarray(x, np.float32))).numpy()
    model.train()
    return out


def _torch_predict_fn(model_fn: Callable, payload: bytes) -> Callable:
    """state_dict bytes -> eval-mode predict closure (shared by the torch
    and lightning estimators)."""
    import io
    import torch
    model = model_fn()
    model.load_state_dict(torch.load(io.BytesIO(payload),
                                     weights_only=True))
    model.eval()

    def predict(x: np.ndarray) -> np.ndarray:
        with torch.no_grad():
            return model(torch.from_numpy(
                np.ascontiguousarray(x, np.float32))).numpy()
    return predict


def _batch_weights(batch, opts) -> Optional[np.ndarray]:
    """Per-row loss weights as a (n, 1) float array, or None (reference:
    sample_weight_col)."""
    col = (opts or {}).get("sample_weight_col")
    if not col:
        return None
    if col not in batch:
        raise ValueError(
            f"sample_weight_col {col!r} not in the batch (columns: "
            f"{sorted(batch)}); the dataset was prepared without it, or "
            "a transformation_fn dropped it")
    w = np.asarray(batch[col], np.float64).ravel()
    return w[:, None]


def _assemble_batch(batch, feature_cols, label_cols):
    """Stack feature columns into a 2-D x and the (first) label column into
    a 2-D y — the one batch-assembly implementation every train task
    shares."""
    x = np.concatenate([batch[c].reshape(len(batch[c]), -1)
                        for c in feature_cols], axis=1)
    y = batch[label_cols[0]].reshape(len(x), -1)
    return x, y


class _SGDTrainTask:
    """Picklable linear-model trainer used by LinearEstimator: each worker
    reads ITS parquet shard, per-batch gradients are averaged across
    workers through the eager data plane, rank 0 checkpoints an epoch
    envelope (resume + history) to the store."""

    def __init__(self, store, run_id, feature_cols, label_cols, batch_size,
                 epochs, lr, metrics=(), opts=None):
        self.store = store
        self.run_id = run_id
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs
        self.lr = lr
        self.metrics = list(metrics)
        self.opts = dict(opts or {})

    def __call__(self, train_path: str, val_path: Optional[str] = None):
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = _make_train_loader(self.store, train_path,
                                    self.batch_size, rank, size, self.opts)
        # probe through the SAME pipeline the epochs use, so a
        # shape-changing transformation_fn sizes w/b correctly
        first = next(_iter_train(loader, 0, self.opts))
        x, y = _assemble_batch(first, self.feature_cols, self.label_cols)
        state = {"w": np.zeros((x.shape[1], y.shape[1]), np.float64),
                 "b": np.zeros((y.shape[1],), np.float64)}

        def restore(payload: bytes) -> None:
            state.update(pickle.loads(payload))

        def train_epoch(epoch: int) -> float:
            epoch_loss, nb = 0.0, 0
            for batch in _iter_train(loader, epoch, self.opts):
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                sw = _batch_weights(batch, self.opts)
                pred = x @ state["w"] + state["b"]
                err = (pred - y) if sw is None else (pred - y) * sw
                gw, gb = sync([x.T @ err / len(x), err.mean(axis=0)])
                state["w"] -= self.lr * gw
                state["b"] -= self.lr * gb
                sq = (pred - y) ** 2 if sw is None else sw * (pred - y) ** 2
                epoch_loss += float(np.mean(sq))
                nb += 1
            return epoch_loss / max(nb, 1)

        history = _epoch_driver(
            self.store, self.run_id, self.epochs, self.metrics,
            self.batch_size, self.feature_cols, self.label_cols,
            rank, size, sync, val_path,
            opts=self.opts,
            restore=restore,
            serialize=lambda: pickle.dumps(dict(state)),
            train_epoch=train_epoch,
            predict=lambda x: x @ state["w"] + state["b"])
        # w_sum lets callers assert every worker converged to the SAME
        # model (gradient sync actually happened).
        return {"mse": history["train_loss"][-1],
                "w_sum": float(state["w"].sum() + state["b"].sum())}


class LinearEstimator(Estimator):
    """A concrete end-to-end estimator (ridge-free linear regression) that
    exercises the full Store -> parquet -> sharded-read -> train ->
    checkpoint -> Model flow without framework dependencies."""

    def __init__(self, *args, lr: float = 0.1, **kwargs):
        super().__init__(*args, **kwargs)
        self.lr = lr

    def _make_train_task(self) -> Callable:
        return _SGDTrainTask(self.store, self.run_id, self.feature_cols,
                             self.label_cols, self.batch_size, self.epochs,
                             self.lr, metrics=self.metrics,
                             opts=self._data_opts())

    def _load_model(self, payload: bytes) -> Callable:
        state = pickle.loads(payload)

        def predict(x: np.ndarray) -> np.ndarray:
            return x @ state["w"] + state["b"]
        return predict


class KerasEstimator(Estimator):
    """Keras-3 estimator (reference: spark/keras/estimator.py): the model
    is built by a factory and trained per-worker on parquet shards; after
    every epoch the weights are AVERAGED across workers through the eager
    data plane (per-epoch parameter averaging — one collective per epoch
    instead of per batch), then rank 0 checkpoints model bytes."""

    def __init__(self, store: Store, model_fn: Callable, num_proc: int = 1,
                 lr: float = 1e-3, callbacks: Sequence = (), **kwargs):
        """``callbacks``: keras callbacks run inside every worker
        (reference: keras estimator's callbacks param) — epoch-level
        hooks (set_model, on_train_begin/end, on_epoch_begin/end with
        the CROSS-WORKER average loss), which covers LR schedules,
        ReduceLROnPlateau, and EarlyStopping (model.stop_training ends
        the run).  They ship to workers by pickle, so use module-level
        schedule fns.  Callback STATE is rebuilt on elastic/checkpoint
        resume (only weights+history persist) — prefer absolute
        schedules (epoch -> lr) over relative ones across resumes."""
        super().__init__(store, num_proc=num_proc, **kwargs)
        self.model_fn = model_fn
        self.lr = lr
        self.callbacks = list(callbacks)

    def _make_train_task(self) -> Callable:
        return _KerasTrainTask(self.store, self.run_id, self.model_fn,
                               self.feature_cols, self.label_cols,
                               self.batch_size, self.epochs, self.lr,
                               loss=self.loss, metrics=self.metrics,
                               callbacks=self.callbacks,
                               opts=self._data_opts())

    def _load_model(self, payload: bytes) -> Callable:
        weights = pickle.loads(payload)
        model = self.model_fn()
        model.set_weights(weights)  # once, not per predict call

        def predict(x: np.ndarray) -> np.ndarray:
            return np.asarray(model(x))
        return predict


def _torch_loss_fn(loss, weighted: bool = False):
    """Resolve the user ``loss`` param to a callable(pred, y) -> scalar
    tensor (reference: TorchEstimator ``loss`` accepts instances and
    callables; strings are the keras-style convenience).  ``weighted``
    builds NAMED losses with reduction="none" so per-row sample weights
    can apply; custom instances/callables own their reduction, so the
    combination is rejected with guidance."""
    import torch
    table = {"mse": torch.nn.MSELoss, "l1": torch.nn.L1Loss,
             "mae": torch.nn.L1Loss, "bce": torch.nn.BCELoss,
             "bce_logits": torch.nn.BCEWithLogitsLoss,
             "cross_entropy": torch.nn.CrossEntropyLoss}
    if isinstance(loss, str) and loss not in table:
        raise ValueError(f"unknown torch loss {loss!r}; named losses: "
                         f"{sorted(table)}")
    if weighted:
        if loss is not None and not isinstance(loss, str):
            raise ValueError(
                "sample_weight_col requires a NAMED loss (or the mse "
                "default) so it can be built unreduced; weight inside "
                "your custom loss instead")
        return table[loss or "mse"](reduction="none")
    if loss is None:
        return torch.nn.MSELoss()
    if isinstance(loss, str):
        return table[loss]()
    return loss  # instance or plain callable


class TorchEstimator(Estimator):
    """Torch estimator (reference: spark/torch/ TorchEstimator): the model
    is built by a factory, trained per-worker on parquet shards with
    per-batch gradient averaging over the data plane, checkpointed via
    state_dict bytes.

    ``loss`` is a name ('mse', 'l1', 'bce', 'bce_logits', 'cross_entropy'),
    a torch loss instance, or a callable(pred, y); ``optimizer_fn`` builds
    the optimizer from model.parameters() (picklable; default SGD(lr));
    ``metrics``/``validation`` come from the Estimator base (reference
    exposes the same four on spark/torch/estimator.py)."""

    def __init__(self, store: Store, model_fn: Callable, num_proc: int = 1,
                 lr: float = 1e-3, optimizer_fn: Optional[Callable] = None,
                 **kwargs):
        super().__init__(store, num_proc=num_proc, **kwargs)
        self.model_fn = model_fn
        self.lr = lr
        self.optimizer_fn = optimizer_fn

    def _make_train_task(self) -> Callable:
        return _TorchTrainTask(self.store, self.run_id, self.model_fn,
                               self.feature_cols, self.label_cols,
                               self.batch_size, self.epochs, self.lr,
                               loss=self.loss, metrics=self.metrics,
                               optimizer_fn=self.optimizer_fn,
                               opts=self._data_opts())

    def _load_model(self, payload: bytes) -> Callable:
        return _torch_predict_fn(self.model_fn, payload)


class _TorchTrainTask:
    def __init__(self, store, run_id, model_fn, feature_cols, label_cols,
                 batch_size, epochs, lr, loss=None, metrics=(),
                 optimizer_fn=None, opts=None):
        self.opts = dict(opts or {})
        self.store = store
        self.run_id = run_id
        self.model_fn = model_fn
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs
        self.lr = lr
        self.loss = loss
        self.metrics = list(metrics)
        self.optimizer_fn = optimizer_fn

    def __call__(self, train_path: str, val_path: Optional[str] = None):
        import io
        import torch
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = _make_train_loader(self.store, train_path,
                                    self.batch_size, rank, size, self.opts)
        model = self.model_fn()
        opt = (self.optimizer_fn(model.parameters()) if self.optimizer_fn
               else torch.optim.SGD(model.parameters(), lr=self.lr))
        weighted = bool(self.opts.get("sample_weight_col"))
        loss_fn = _torch_loss_fn(self.loss, weighted=weighted)
        # Class-index losses need (n,) int64 targets, not the (n,1) float
        # regression layout _assemble_batch produces.
        index_target = isinstance(loss_fn, torch.nn.CrossEntropyLoss) or \
            self.loss == "cross_entropy"

        def as_target(y: np.ndarray):
            if index_target:
                return torch.from_numpy(y.ravel().astype(np.int64))
            return torch.from_numpy(np.ascontiguousarray(y, np.float32))

        def restore(payload: bytes) -> None:
            model.load_state_dict(torch.load(io.BytesIO(payload),
                                             weights_only=True))

        def serialize() -> bytes:
            buf = io.BytesIO()
            torch.save(model.state_dict(), buf)
            return buf.getvalue()

        def train_epoch(epoch: int) -> float:
            epoch_loss, nb = 0.0, 0
            for batch in _iter_train(loader, epoch, self.opts):
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                sw = _batch_weights(batch, self.opts)
                xt = torch.from_numpy(np.ascontiguousarray(x, np.float32))
                opt.zero_grad()
                loss = loss_fn(model(xt), as_target(y))
                if sw is not None:
                    wt = torch.from_numpy(
                        np.ascontiguousarray(sw, np.float32))
                    while loss.dim() > 1:  # per-element -> per-row
                        loss = loss.mean(dim=-1)
                    loss = (loss * wt.ravel()).mean()
                loss.backward()
                if size > 1:
                    _torch_sync_grads(model, sync)
                opt.step()
                epoch_loss += float(loss.detach())
                nb += 1
            return epoch_loss / max(nb, 1)

        history = _epoch_driver(
            self.store, self.run_id, self.epochs, self.metrics,
            self.batch_size, self.feature_cols, self.label_cols,
            rank, size, sync, val_path,
            opts=self.opts,
            restore=restore, serialize=serialize, train_epoch=train_epoch,
            predict=lambda x: _torch_eval_predict(model, x),
            cold_start=(lambda: _torch_sync_params(model, sync))
            if size > 1 else None)
        return history["train_loss"][-1] if history["train_loss"] else 0.0


class _KerasTrainTask:
    def __init__(self, store, run_id, model_fn, feature_cols, label_cols,
                 batch_size, epochs, lr, loss=None, metrics=(),
                 callbacks=(), opts=None):
        self.callbacks = list(callbacks)
        self.opts = dict(opts or {})
        self.store = store
        self.run_id = run_id
        self.model_fn = model_fn
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs
        self.lr = lr
        self.loss = loss
        self.metrics = list(metrics)

    def __call__(self, train_path: str, val_path: Optional[str] = None):
        import keras
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = _make_train_loader(self.store, train_path,
                                    self.batch_size, rank, size, self.opts)
        model = self.model_fn()
        # ``loss`` passes straight to compile: keras resolves names and
        # callables the same way (reference: keras estimator's loss param).
        model.compile(optimizer=keras.optimizers.SGD(self.lr),
                      loss=self.loss or "mse")
        for cb in self.callbacks:
            cb.set_model(model)
            cb.on_train_begin()

        def train_epoch(epoch: int) -> float:
            for cb in self.callbacks:
                cb.on_epoch_begin(epoch)
            epoch_loss, nb = 0.0, 0
            for batch in _iter_train(loader, epoch, self.opts):
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                sw = _batch_weights(batch, self.opts)
                loss = model.train_on_batch(
                    x, y, sample_weight=None if sw is None
                    else sw.ravel().astype(np.float32))
                epoch_loss += float(np.asarray(loss).ravel()[0])
                # train_on_batch reports the RUNNING mean since the last
                # metric reset, not this batch's loss; reset so the
                # epoch average is an average of per-batch losses
                model.reset_metrics()
                nb += 1
            # per-epoch parameter averaging keeps every worker's model
            # identical at epoch boundaries (one fused collective)
            model.set_weights(sync([np.asarray(w)
                                    for w in model.get_weights()]))
            # callbacks see the CROSS-WORKER average loss, so stateful
            # monitors (ReduceLROnPlateau, EarlyStopping) make the SAME
            # decision on every worker instead of diverging per shard
            avg = float(np.asarray(sync(
                [np.asarray([epoch_loss / max(nb, 1)], np.float64)]
            )[0]).ravel()[0])
            for cb in self.callbacks:
                cb.on_epoch_end(epoch, logs={"loss": avg})
            return avg

        history = _epoch_driver(
            self.store, self.run_id, self.epochs, self.metrics,
            self.batch_size, self.feature_cols, self.label_cols,
            rank, size, sync, val_path,
            opts=self.opts,
            restore=lambda p: model.set_weights(pickle.loads(p)),
            serialize=lambda: pickle.dumps(model.get_weights()),
            train_epoch=train_epoch,
            predict=lambda x: np.asarray(model(x)),
            should_stop=lambda: bool(getattr(model, "stop_training",
                                             False)))
        for cb in self.callbacks:
            cb.on_train_end()
        return history["train_loss"][-1] if history["train_loss"] else 0.0
