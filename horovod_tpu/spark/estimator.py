"""Estimator API: fit on a dataset, get back a servable model.

Reference: horovod/spark/common/estimator.py:25-103 — ``Estimator.fit(df)``
persists the DataFrame as parquet in the Store, trains inside
horovod-on-spark workers with petastorm readers, checkpoints per epoch,
and returns a Model transformer.

TPU-native reshape: data arrives as a column dict (or a pyspark DataFrame
when pyspark is present — converted via toPandas), training runs through
``horovod_tpu.spark.run`` on any TaskExecutor, workers read their shard
with ParquetDataLoader, rank 0 checkpoints to the Store each epoch, and
``fit`` returns a KerasModel/TorchModel wrapper exposing ``transform``.
"""

from __future__ import annotations

import io
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..data.loader import ParquetDataLoader
from .runner import TaskExecutor, run as spark_run
from .store import Store


def _as_columns(df, feature_cols=None, label_cols=None
                ) -> Dict[str, np.ndarray]:
    """Accept a column dict, or a pyspark/pandas DataFrame.  With no column
    lists, ALL columns convert (transform() must not drop id/label columns
    the caller wants to keep alongside predictions)."""
    if isinstance(df, dict):
        return {k: np.asarray(v) for k, v in df.items()}
    if hasattr(df, "toPandas"):  # pyspark DataFrame
        df = df.toPandas()
    cols = (list(feature_cols or []) + list(label_cols or [])) or \
        list(df.columns)
    return {c: np.stack(df[c].to_numpy()) for c in cols}


class EstimatorModel:
    """Fitted-model transformer (reference: HorovodModel,
    common/estimator.py:97-103)."""

    def __init__(self, predict_fn: Callable[[np.ndarray], np.ndarray],
                 feature_cols: Sequence[str], output_col: str = "predict"):
        self._predict = predict_fn
        self.feature_cols = list(feature_cols)
        self.output_col = output_col

    def transform(self, df):
        cols = _as_columns(df)  # keep every input column in the output
        x = np.concatenate(
            [cols[c].reshape(len(cols[c]), -1) for c in self.feature_cols],
            axis=1)
        out = dict(cols)
        out[self.output_col] = self._predict(x)
        return out


class Estimator:
    """Scheduler-agnostic estimator core (reference: estimator.py:25-96).

    Subclasses supply ``_train_task`` (a picklable callable run per worker)
    and ``_load_model`` (driver-side: bytes -> predict_fn).
    """

    def __init__(self, store: Store, num_proc: int = 1,
                 feature_cols: Sequence[str] = ("features",),
                 label_cols: Sequence[str] = ("label",),
                 batch_size: int = 32, epochs: int = 1,
                 run_id: str = "run0",
                 executor: Optional[TaskExecutor] = None):
        self.store = store
        self.num_proc = num_proc
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.run_id = run_id
        self.executor = executor

    # -- subclass surface --------------------------------------------------
    def _make_train_task(self) -> Callable:
        raise NotImplementedError

    def _load_model(self, payload: bytes) -> Callable:
        raise NotImplementedError

    # -- the fit flow ------------------------------------------------------
    def _has_checkpoint(self) -> bool:
        """Resume support (reference: estimator.py:91-96)."""
        return self.store.read_checkpoint(self.run_id) is not None

    def fit(self, df) -> EstimatorModel:
        cols = _as_columns(df, self.feature_cols, self.label_cols)
        train_path = self.store.get_train_data_path(self.run_id)
        self.store.write_parquet(train_path, cols)

        task = self._make_train_task()
        spark_run(task, args=(train_path,), num_proc=self.num_proc,
                  executor=self.executor)

        payload = self.store.read_checkpoint(self.run_id)
        if payload is None:
            raise RuntimeError("training produced no checkpoint")
        return EstimatorModel(self._load_model(payload),
                              self.feature_cols)


def _grad_sync_fn():
    """Cross-worker average over the REAL data plane when the runner
    exported a coordinator (size > 1): hvd.init() assembles the mesh via
    jax.distributed and gradients ride an eager allreduce.  Single-worker
    runs skip the bring-up."""
    size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
    if size <= 1:
        return lambda gs: gs
    import horovod_tpu as hvd
    from horovod_tpu.ops.collectives import process_local
    hvd.init()

    def sync(gs):
        """Average a LIST of arrays in one fused grouped collective (one
        dispatch per batch, not one per parameter)."""
        outs = hvd.grouped_allreduce(
            [process_local(np.asarray(g)) for g in gs], op=hvd.Average)
        return [np.asarray(o, dtype=np.asarray(g).dtype)
                for o, g in zip(outs, gs)]
    return sync


def _torch_sync_params(model, sync) -> None:
    """All workers start from identical weights (rank-0 convention): ONE
    fused sync of the initial parameters."""
    import torch
    avgs = sync([p.detach().numpy() for p in model.parameters()])
    with torch.no_grad():
        for p, a in zip(model.parameters(), avgs):
            p.copy_(torch.from_numpy(np.ascontiguousarray(a)))


def _torch_sync_grads(model, sync) -> None:
    """ONE fused grouped collective per batch, not one per parameter."""
    import torch
    with_grads = [p for p in model.parameters() if p.grad is not None]
    gs = sync([p.grad.numpy() for p in with_grads])
    for p, g in zip(with_grads, gs):
        p.grad.copy_(torch.from_numpy(np.ascontiguousarray(g)))


def _torch_predict_fn(model_fn: Callable, payload: bytes) -> Callable:
    """state_dict bytes -> eval-mode predict closure (shared by the torch
    and lightning estimators)."""
    import io
    import torch
    model = model_fn()
    model.load_state_dict(torch.load(io.BytesIO(payload),
                                     weights_only=True))
    model.eval()

    def predict(x: np.ndarray) -> np.ndarray:
        with torch.no_grad():
            return model(torch.from_numpy(
                np.ascontiguousarray(x, np.float32))).numpy()
    return predict


def _assemble_batch(batch, feature_cols, label_cols):
    """Stack feature columns into a 2-D x and the (first) label column into
    a 2-D y — the one batch-assembly implementation every train task
    shares."""
    x = np.concatenate([batch[c].reshape(len(batch[c]), -1)
                        for c in feature_cols], axis=1)
    y = batch[label_cols[0]].reshape(len(x), -1)
    return x, y


class _SGDTrainTask:
    """Picklable linear-model trainer used by LinearEstimator: each worker
    reads ITS parquet shard, per-batch gradients are averaged across
    workers through the eager data plane, rank 0 checkpoints to the
    store."""

    def __init__(self, store, run_id, feature_cols, label_cols, batch_size,
                 epochs, lr):
        self.store = store
        self.run_id = run_id
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs
        self.lr = lr

    def __call__(self, train_path: str):
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = ParquetDataLoader(train_path, self.batch_size,
                                   rank=rank, num_workers=size)
        first = next(iter(loader))
        x0, y0 = _assemble_batch(first, self.feature_cols, self.label_cols)
        w = np.zeros((x0.shape[1], y0.shape[1]), np.float64)
        b = np.zeros((y0.shape[1],), np.float64)
        for _ in range(self.epochs):
            for batch in loader:
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                pred = x @ w + b
                gw, gb = sync([x.T @ (pred - y) / len(x),
                               (pred - y).mean(axis=0)])
                w -= self.lr * gw
                b -= self.lr * gb
        if rank == 0:
            self.store.save_checkpoint(
                self.run_id, pickle.dumps({"w": w, "b": b}))
        # w_sum lets callers assert every worker converged to the SAME
        # model (gradient sync actually happened).
        return {"mse": float(np.mean((x @ w + b - y) ** 2)),
                "w_sum": float(w.sum() + b.sum())}


class LinearEstimator(Estimator):
    """A concrete end-to-end estimator (ridge-free linear regression) that
    exercises the full Store -> parquet -> sharded-read -> train ->
    checkpoint -> Model flow without framework dependencies."""

    def __init__(self, *args, lr: float = 0.1, **kwargs):
        super().__init__(*args, **kwargs)
        self.lr = lr

    def _make_train_task(self) -> Callable:
        return _SGDTrainTask(self.store, self.run_id, self.feature_cols,
                             self.label_cols, self.batch_size, self.epochs,
                             self.lr)

    def _load_model(self, payload: bytes) -> Callable:
        state = pickle.loads(payload)

        def predict(x: np.ndarray) -> np.ndarray:
            return x @ state["w"] + state["b"]
        return predict


class KerasEstimator(Estimator):
    """Keras-3 estimator (reference: spark/keras/estimator.py): the model
    is built by a factory and trained per-worker on parquet shards; after
    every epoch the weights are AVERAGED across workers through the eager
    data plane (per-epoch parameter averaging — one collective per epoch
    instead of per batch), then rank 0 checkpoints model bytes."""

    def __init__(self, store: Store, model_fn: Callable, num_proc: int = 1,
                 lr: float = 1e-3, **kwargs):
        super().__init__(store, num_proc=num_proc, **kwargs)
        self.model_fn = model_fn
        self.lr = lr

    def _make_train_task(self) -> Callable:
        return _KerasTrainTask(self.store, self.run_id, self.model_fn,
                               self.feature_cols, self.label_cols,
                               self.batch_size, self.epochs, self.lr)

    def _load_model(self, payload: bytes) -> Callable:
        weights = pickle.loads(payload)
        model = self.model_fn()
        model.set_weights(weights)  # once, not per predict call

        def predict(x: np.ndarray) -> np.ndarray:
            return np.asarray(model(x))
        return predict


class TorchEstimator(Estimator):
    """Torch estimator (reference: spark/torch/ TorchEstimator): the model
    is built by a factory, trained per-worker on parquet shards with
    per-batch gradient averaging over the data plane, checkpointed via
    state_dict bytes."""

    def __init__(self, store: Store, model_fn: Callable, num_proc: int = 1,
                 lr: float = 1e-3, **kwargs):
        super().__init__(store, num_proc=num_proc, **kwargs)
        self.model_fn = model_fn
        self.lr = lr

    def _make_train_task(self) -> Callable:
        return _TorchTrainTask(self.store, self.run_id, self.model_fn,
                               self.feature_cols, self.label_cols,
                               self.batch_size, self.epochs, self.lr)

    def _load_model(self, payload: bytes) -> Callable:
        return _torch_predict_fn(self.model_fn, payload)


class _TorchTrainTask:
    def __init__(self, store, run_id, model_fn, feature_cols, label_cols,
                 batch_size, epochs, lr):
        self.store = store
        self.run_id = run_id
        self.model_fn = model_fn
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs
        self.lr = lr

    def __call__(self, train_path: str):
        import io
        import torch
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = ParquetDataLoader(train_path, self.batch_size,
                                   rank=rank, num_workers=size)
        model = self.model_fn()
        if size > 1:
            _torch_sync_params(model, sync)
        opt = torch.optim.SGD(model.parameters(), lr=self.lr)
        loss_fn = torch.nn.MSELoss()
        loss = torch.zeros(())
        for _ in range(self.epochs):
            for batch in loader:
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                xt = torch.from_numpy(np.ascontiguousarray(x, np.float32))
                yt = torch.from_numpy(np.ascontiguousarray(y, np.float32))
                opt.zero_grad()
                loss = loss_fn(model(xt), yt)
                loss.backward()
                if size > 1:
                    _torch_sync_grads(model, sync)
                opt.step()
        if rank == 0:
            buf = io.BytesIO()
            torch.save(model.state_dict(), buf)
            self.store.save_checkpoint(self.run_id, buf.getvalue())
        return float(loss)


class _KerasTrainTask:
    def __init__(self, store, run_id, model_fn, feature_cols, label_cols,
                 batch_size, epochs, lr):
        self.store = store
        self.run_id = run_id
        self.model_fn = model_fn
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.batch_size = batch_size
        self.epochs = epochs
        self.lr = lr

    def __call__(self, train_path: str):
        import keras
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
        sync = _grad_sync_fn()
        loader = ParquetDataLoader(train_path, self.batch_size,
                                   rank=rank, num_workers=size)
        model = self.model_fn()
        model.compile(optimizer=keras.optimizers.SGD(self.lr), loss="mse")
        for _ in range(self.epochs):
            for batch in loader:
                x, y = _assemble_batch(batch, self.feature_cols,
                                       self.label_cols)
                loss = model.train_on_batch(x, y)
            # per-epoch parameter averaging keeps every worker's model
            # identical at epoch boundaries (one fused collective)
            model.set_weights(sync([np.asarray(w)
                                    for w in model.get_weights()]))
        if rank == 0:
            self.store.save_checkpoint(
                self.run_id, pickle.dumps(model.get_weights()))
        return float(np.asarray(loss).ravel()[0])
