"""Request journal + redrive: the durability half of the serving plane
(docs/serving.md#fault-tolerance).

The router journals every ACCEPTED request — prompt, params, dense
sequence key — to the rendezvous KV scope ``serve_journal`` at the same
moment it enqueues the request for the engine fleet.  The journal lives
in the launcher's rendezvous server, which survives worker deaths, so
after a fleet reset (rank death, wedged-engine SIGABRT, preemption) the
new rank 0 can reconstruct exactly what was promised to clients:

  * a journal entry with a ``serve_out`` ``.done`` record finished
    before the reset — nothing to do;
  * an entry without one is UNFINISHED: the tokens already streamed to
    the client are recovered from the published ``serve_out`` parts
    (the router streamed exactly those), the request is re-admitted,
    and — greedy decode being deterministic — the regenerated stream's
    first ``len(emitted)`` tokens are suppressed instead of re-published
    so the client's ndjson stream resumes seamlessly from the last
    token it saw (serve/worker.py applies the suppression).

Everything here is a pure function over a ``get(scope, key) ->
Optional[bytes]`` probe so the redrive computation unit-tests without a
fleet (tests/test_serve_ft.py) and runs identically against the live KV
(serve/worker.py wires ``runner/http_client.get_kv`` in).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

JOURNAL_SCOPE = "serve_journal"

KVGet = Callable[[str, str], Optional[bytes]]


def journal_key(seq: int) -> str:
    """Dense journal numbering — the SAME key the request carries in
    ``serve_req`` (router.req_key), so redrive can probe seq 0,1,2,...
    with no KV listing primitive."""
    return f"req.{seq:06d}"


def emitted_prefix(get: KVGet, rid: str) -> Tuple[List[int], int]:
    """Tokens already published (and therefore already streamed to the
    client) for one request, plus the next part index to publish at.
    A torn part PUT ends the prefix there — the router's stream stopped
    at the same place, so suppression and the client stay aligned."""
    from .router import OUT_SCOPE
    emitted: List[int] = []
    part = 0
    while True:
        raw = get(OUT_SCOPE, f"{rid}.part.{part:06d}")
        if raw is None:
            return emitted, part
        try:
            emitted.extend(int(t) for t in json.loads(raw).get("tokens", []))
        except (ValueError, TypeError):
            return emitted, part
        part += 1


def redrive_plan(get: KVGet) -> Tuple[List[Dict[str, Any]], int]:
    """Scan the journal and build the redrive list: every unfinished
    entry annotated with ``resume_emitted`` (the streamed prefix to
    suppress) and ``resume_part`` (where publishing resumes).  Returns
    ``(entries, next_seq)`` where ``next_seq`` is the first request
    sequence number the journal has NOT claimed — the resumed fleet's
    request-drain cursor (completed requests are skipped but counted)."""
    from .router import OUT_SCOPE
    entries: List[Dict[str, Any]] = []
    seq = 0
    while True:
        raw = get(JOURNAL_SCOPE, journal_key(seq))
        if raw is None:
            return entries, seq
        seq += 1
        try:
            entry = json.loads(raw)
        except (ValueError, TypeError):
            continue  # torn journal PUT: hold the numbering, skip it
        rid = entry.get("id")
        if not rid:
            continue
        if get(OUT_SCOPE, f"{rid}.done") is not None:
            continue  # finished before the reset
        emitted, part = emitted_prefix(get, rid)
        entry["resume_emitted"] = emitted
        entry["resume_part"] = part
        if entry.get("trace"):
            # Redrive hop: derive a child context so the resumed
            # fleet's spans link under the original admission
            # (serve/trace.py — pure, so recomputing the same journal
            # entry re-mints identical span ids).
            from . import trace as trace_mod
            entry["trace"] = trace_mod.child(entry["trace"], "redrive")
        entries.append(entry)
