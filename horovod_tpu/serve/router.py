"""Request router: the serving plane's front door on the rendezvous
HTTP server (docs/serving.md).

``POST /generate`` accepts ``{"tokens": [...], "max_new_tokens": N}``,
enqueues the request onto the rendezvous KV (scope ``serve_req`` —
the SAME transport every other plane rides), and streams the engine's
tokens back as newline-delimited JSON while rank 0 of the engine fleet
publishes them (scope ``serve_out``).  ``GET /serve/stats`` merges the
router's queue counters with the engine's self-published stats (scope
``serve`` key ``stats``).

Backpressure: the router is the admission valve in front of the
engine's own max_batch_tokens budget — beyond ``max_pending``
unfinished requests it answers 429 immediately instead of growing an
unbounded queue (tested in tests/test_serve.py).

The handler side runs inside runner/http_server.py's threaded server
(one thread per in-flight stream — the async queue is the KV scope, the
threads are just the drains), so the router needs no process of its
own: ``hvdrun --serve`` gives the fleet a router for free.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional

REQ_SCOPE = "serve_req"
OUT_SCOPE = "serve_out"
PLAN_SCOPE = "serve_plan"
STATS_SCOPE = "serve"
STATS_KEY = "stats"

DEFAULT_MAX_PENDING = 64
DEFAULT_STREAM_TIMEOUT_S = 120.0
_POLL_S = 0.02


def req_key(seq: int) -> str:
    return f"req.{seq:06d}"


class RouterState:
    """Router-side counters: submitted/completed/rejected + the dense
    sequence numbering the engine fleet consumes in order."""

    def __init__(self, max_pending: int = DEFAULT_MAX_PENDING,
                 stream_timeout_s: float = DEFAULT_STREAM_TIMEOUT_S):
        self.max_pending = int(max_pending)
        self.stream_timeout_s = float(stream_timeout_s)
        self._lock = threading.Lock()
        self.next_seq = 0
        self.completed = 0
        self.rejected = 0

    def try_claim(self) -> Optional[int]:
        """Next sequence number, or None under backpressure."""
        with self._lock:
            if self.next_seq - self.completed >= self.max_pending:
                self.rejected += 1
                return None
            seq = self.next_seq
            self.next_seq += 1
            return seq

    def finish_stream(self) -> None:
        with self._lock:
            self.completed += 1

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {"submitted": self.next_seq,
                    "completed": self.completed,
                    "rejected": self.rejected,
                    "pending": self.next_seq - self.completed,
                    "max_pending": self.max_pending}


def get_router_state(server) -> RouterState:
    """Lazily attach one RouterState to the rendezvous HTTP server."""
    state = getattr(server, "serve_router", None)
    if state is None:
        state = server.serve_router = RouterState()
    return state


def parse_generate_body(raw: bytes) -> Dict[str, Any]:
    """Validate one /generate body; raises ValueError with a
    client-renderable message."""
    try:
        body = json.loads(raw or b"{}")
    except ValueError:
        raise ValueError("body is not valid JSON")
    tokens = body.get("tokens")
    if not isinstance(tokens, list) or not tokens or \
            not all(isinstance(t, int) and t >= 0 for t in tokens):
        raise ValueError("'tokens' must be a non-empty list of token ids "
                         "(no server-side tokenizer; docs/serving.md)")
    max_new = body.get("max_new_tokens", 16)
    if not isinstance(max_new, int) or max_new < 1:
        raise ValueError("'max_new_tokens' must be a positive int")
    out = {"tokens": tokens, "max_new_tokens": max_new}
    if body.get("eos_id") is not None:
        if not isinstance(body["eos_id"], int):
            raise ValueError("'eos_id' must be an int")
        out["eos_id"] = body["eos_id"]
    return out


def handle_generate(handler) -> None:
    """POST /generate on the rendezvous server: enqueue to the KV, then
    stream ndjson lines ({"tokens": [...]} parts, then {"done": ...})
    as the engine publishes them.  Connection close delimits the body
    (HTTP/1.0 semantics of the rendezvous server)."""
    server = handler.server
    state = get_router_state(server)
    length = int(handler.headers.get("Content-Length", 0))
    raw = handler.rfile.read(length)
    try:
        req = parse_generate_body(raw)
    except ValueError as e:
        _json_response(handler, 400, {"error": str(e)})
        return
    seq = state.try_claim()
    if seq is None:
        _json_response(handler, 429, {
            "error": "serving queue full",
            **state.counters()})
        return
    key = req_key(seq)
    req["id"] = key
    req["submitted_t"] = time.time()
    try:
        with server.kv_lock:
            server.kv.setdefault(REQ_SCOPE, {})[key] = \
                json.dumps(req).encode()
            server.kv_times.setdefault(REQ_SCOPE, {})[key] = time.time()
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("X-Serve-Request-Id", key)
        handler.end_headers()
        _stream_results(handler, server, key, state.stream_timeout_s)
    finally:
        state.finish_stream()


def _stream_results(handler, server, key: str, timeout_s: float) -> None:
    """Drain ``serve_out`` parts for one request to the client as they
    arrive; ends with the ``.done`` record (or a timeout record)."""
    deadline = time.time() + timeout_s
    part = 0
    while True:
        with server.kv_lock:
            scope = server.kv.get(OUT_SCOPE, {})
            chunk = scope.get(f"{key}.part.{part:06d}")
            done = scope.get(f"{key}.done")
        if chunk is not None:
            handler.wfile.write(chunk + b"\n")
            handler.wfile.flush()
            part += 1
            continue
        if done is not None:
            handler.wfile.write(done + b"\n")
            handler.wfile.flush()
            return
        if time.time() >= deadline:
            handler.wfile.write(json.dumps(
                {"error": f"timed out after {timeout_s:.0f}s waiting for "
                          f"{key}"}).encode() + b"\n")
            return
        time.sleep(_POLL_S)


def render_stats(server) -> Dict[str, Any]:
    """GET /serve/stats: router counters + the engine fleet's
    self-published stats (KV scope ``serve`` key ``stats``)."""
    state = get_router_state(server)
    out: Dict[str, Any] = {"router": state.counters()}
    with server.kv_lock:
        raw = server.kv.get(STATS_SCOPE, {}).get(STATS_KEY)
    if raw is not None:
        try:
            out["engine"] = json.loads(raw)
        except (ValueError, TypeError):
            pass  # a torn PUT must not 500 the stats view
    return out


def _json_response(handler, code: int, obj: Dict[str, Any]) -> None:
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)
