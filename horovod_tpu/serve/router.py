"""Request router: the serving plane's front door on the rendezvous
HTTP server (docs/serving.md).

``POST /generate`` accepts ``{"tokens": [...], "max_new_tokens": N}``,
enqueues the request onto the rendezvous KV (scope ``serve_req`` —
the SAME transport every other plane rides), and streams the engine's
tokens back as newline-delimited JSON while rank 0 of the engine fleet
publishes them (scope ``serve_out``).  ``GET /serve/stats`` merges the
router's queue counters with the engine's self-published stats (scope
``serve`` key ``stats``).

Fault tolerance (docs/serving.md#fault-tolerance):

  * every ACCEPTED request is also journaled to scope ``serve_journal``
    (serve/journal.py) so a fleet reset can redrive unfinished work —
    the journal write shares the admission's kv_lock critical section,
    so a journaled request and an enqueued request are the same set;
  * admission is watermark-based with hysteresis: beyond the high
    watermark requests are shed with 429 + a ``Retry-After`` header
    derived from the measured per-request service time (TPOT x tokens,
    EWMA) times the queue depth; admission resumes at the low watermark;
  * ``POST /admin/drain`` stops admission (503), signals the engine
    fleet through the KV (scope ``serve`` key ``drain``), and waits for
    rank 0's ``drained`` ack — the fleet finishes every accepted
    request, checkpoints its final stats, and exits 0 (the
    preemption-safe rolling-restart path).

The handler side runs inside runner/http_server.py's threaded server
(one thread per in-flight stream — the async queue is the KV scope, the
threads are just the drains), so the router needs no process of its
own: ``hvdrun --serve`` gives the fleet a router for free.  Stream
reads and journal writes touch the IN-PROCESS kv dict (the router lives
in the rendezvous server's process), so no KV transport error can kill
a stream router-side; the worker-side KV legs carry the bounded
exp-backoff retry (serve/worker.py ``_kv_op``).
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, Optional

from .journal import JOURNAL_SCOPE

REQ_SCOPE = "serve_req"
OUT_SCOPE = "serve_out"
PLAN_SCOPE = "serve_plan"
STATS_SCOPE = "serve"
STATS_KEY = "stats"
DRAIN_KEY = "drain"
DRAINED_KEY = "drained"

DEFAULT_MAX_PENDING = 64
DEFAULT_STREAM_TIMEOUT_S = 120.0
RETRY_AFTER_CAP_S = 60
_POLL_S = 0.02


def req_key(seq: int) -> str:
    return f"req.{seq:06d}"


class RouterState:
    """Router-side admission state: submitted/completed/rejected
    counters, the dense sequence numbering the engine fleet consumes in
    order, watermark shedding with hysteresis, the drain latch, and the
    service-time EWMA behind ``Retry-After``."""

    def __init__(self, max_pending: int = DEFAULT_MAX_PENDING,
                 stream_timeout_s: float = DEFAULT_STREAM_TIMEOUT_S,
                 shed_high: Optional[int] = None,
                 shed_low: Optional[int] = None,
                 journal: bool = True):
        self.max_pending = int(max_pending)
        self.stream_timeout_s = float(stream_timeout_s)
        self.shed_high = int(shed_high) if shed_high else self.max_pending
        if shed_low:
            self.shed_low = int(shed_low)
        else:
            self.shed_low = max(
                0, self.shed_high - max(1, self.shed_high // 4))
        self.journal = bool(journal)
        self._lock = threading.Lock()
        self.next_seq = 0
        self.completed = 0
        self.rejected = 0
        self.shed = 0
        self.draining = False
        self.reject_reason: Optional[str] = None  # set by a None claim
        self._shedding = False
        self._service_ewma: Optional[float] = None  # s of decode/request

    def try_claim(self) -> Optional[int]:
        """Next sequence number, or None under shedding/drain (the
        reason lands in ``reject_reason`` for the status code)."""
        from ..utils import metrics as M
        with self._lock:
            if self.draining:
                self.rejected += 1
                self.reject_reason = "draining"
                return None
            pending = self.next_seq - self.completed
            if self._shedding and pending <= self.shed_low:
                self._shedding = False  # hysteresis: resume admission
            if self._shedding or pending >= self.shed_high:
                self._shedding = True
                self.rejected += 1
                self.shed += 1
                self.reject_reason = "shed"
                M.SERVE_SHEDS.inc()
                return None
            seq = self.next_seq
            self.next_seq += 1
            self.reject_reason = None
            if self.journal:
                M.SERVE_JOURNAL_DEPTH.set(self.next_seq - self.completed)
            return seq

    def finish_stream(self) -> None:
        from ..utils import metrics as M
        with self._lock:
            self.completed += 1
            if self.journal:
                M.SERVE_JOURNAL_DEPTH.set(
                    max(0, self.next_seq - self.completed))

    def observe_done(self, tpot_s: Any, n_tokens: int) -> None:
        """Feed one finished request's measured decode time into the
        service-time EWMA (tpot x generated tokens) — the Retry-After
        basis.  Bad/missing measurements are ignored."""
        try:
            svc = float(tpot_s) * max(1, int(n_tokens))
        except (TypeError, ValueError):
            return
        if svc <= 0:
            return
        with self._lock:
            if self._service_ewma is None:
                self._service_ewma = svc
            else:
                self._service_ewma = 0.7 * self._service_ewma + 0.3 * svc

    def retry_after_s(self) -> int:
        """Client back-off hint for a shed: measured per-request service
        time x queue depth, in whole seconds clamped to [1, 60].  With
        no measurement yet, 1 — the cheapest honest answer."""
        with self._lock:
            pending = self.next_seq - self.completed
            svc = self._service_ewma
        if svc is None:
            return 1
        return int(min(RETRY_AFTER_CAP_S, max(1, math.ceil(pending * svc))))

    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return {"submitted": self.next_seq,
                    "completed": self.completed,
                    "rejected": self.rejected,
                    "shed": self.shed,
                    "pending": self.next_seq - self.completed,
                    "max_pending": self.max_pending,
                    "shed_high": self.shed_high,
                    "shed_low": self.shed_low,
                    "draining": self.draining,
                    "journal": self.journal}


def get_router_state(server) -> RouterState:
    """Lazily attach one RouterState to the rendezvous HTTP server,
    configured from the knob registry (watermarks, journal switch)."""
    state = getattr(server, "serve_router", None)
    if state is None:
        from ..common.knobs import Knobs
        knobs = Knobs()
        state = server.serve_router = RouterState(
            shed_high=int(knobs["HOROVOD_SERVE_SHED_HIGH"]) or None,
            shed_low=int(knobs["HOROVOD_SERVE_SHED_LOW"]) or None,
            journal=bool(knobs["HOROVOD_SERVE_JOURNAL"]))
    return state


def parse_generate_body(raw: bytes) -> Dict[str, Any]:
    """Validate one /generate body; raises ValueError with a
    client-renderable message."""
    try:
        body = json.loads(raw or b"{}")
    except ValueError:
        raise ValueError("body is not valid JSON")
    tokens = body.get("tokens")
    if not isinstance(tokens, list) or not tokens or \
            not all(isinstance(t, int) and t >= 0 for t in tokens):
        raise ValueError("'tokens' must be a non-empty list of token ids "
                         "(no server-side tokenizer; docs/serving.md)")
    max_new = body.get("max_new_tokens", 16)
    if not isinstance(max_new, int) or max_new < 1:
        raise ValueError("'max_new_tokens' must be a positive int")
    out = {"tokens": tokens, "max_new_tokens": max_new}
    if body.get("eos_id") is not None:
        if not isinstance(body["eos_id"], int):
            raise ValueError("'eos_id' must be an int")
        out["eos_id"] = body["eos_id"]
    return out


def handle_generate(handler) -> None:
    """POST /generate on the rendezvous server: journal + enqueue to the
    KV, then stream ndjson lines ({"tokens": [...]} parts, then
    {"done": ...}) as the engine publishes them.  Connection close
    delimits the body (HTTP/1.0 semantics of the rendezvous server)."""
    server = handler.server
    state = get_router_state(server)
    length = int(handler.headers.get("Content-Length", 0))
    raw = handler.rfile.read(length)
    try:
        req = parse_generate_body(raw)
    except ValueError as e:
        _json_response(handler, 400, {"error": str(e)})
        return
    seq = state.try_claim()
    if seq is None:
        if state.reject_reason == "draining":
            _json_response(handler, 503, {
                "error": "serving fleet is draining; retry against the "
                         "next fleet",
                **state.counters()})
        else:
            _json_response(handler, 429, {
                "error": "serving queue full (load shed)",
                **state.counters()},
                extra_headers={"Retry-After":
                               str(state.retry_after_s())})
        return
    key = req_key(seq)
    req["id"] = key
    req["submitted_t"] = time.time()
    try:
        encoded = json.dumps(req).encode()
        with server.kv_lock:
            now = time.time()
            server.kv.setdefault(REQ_SCOPE, {})[key] = encoded
            server.kv_times.setdefault(REQ_SCOPE, {})[key] = now
            if state.journal:
                # Same critical section as the enqueue: the journaled
                # set and the promised set cannot diverge.
                server.kv.setdefault(JOURNAL_SCOPE, {})[key] = encoded
                server.kv_times.setdefault(JOURNAL_SCOPE, {})[key] = now
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("X-Serve-Request-Id", key)
        handler.end_headers()
        _stream_results(handler, server, key, state)
    finally:
        state.finish_stream()


def _stream_results(handler, server, key: str, state: RouterState) -> None:
    """Drain ``serve_out`` parts for one request to the client as they
    arrive; ends with the ``.done`` record (or a timeout record).  Reads
    are in-process dict lookups — a fleet reset stalls the stream (no
    new parts) without breaking it, and the redriven fleet's resumed
    parts continue it seamlessly."""
    deadline = time.time() + state.stream_timeout_s
    part = 0
    while True:
        with server.kv_lock:
            scope = server.kv.get(OUT_SCOPE, {})
            chunk = scope.get(f"{key}.part.{part:06d}")
            done = scope.get(f"{key}.done")
        if chunk is not None:
            handler.wfile.write(chunk + b"\n")
            handler.wfile.flush()
            part += 1
            continue
        if done is not None:
            handler.wfile.write(done + b"\n")
            handler.wfile.flush()
            try:
                rec = json.loads(done)
                state.observe_done(rec.get("tpot_s"),
                                   len(rec.get("tokens") or ()))
            except (ValueError, TypeError):
                pass  # a torn done record still ends the stream
            return
        if time.time() >= deadline:
            handler.wfile.write(json.dumps(
                {"error": f"timed out after {state.stream_timeout_s:.0f}s "
                          f"waiting for {key}"}).encode() + b"\n")
            return
        time.sleep(_POLL_S)


def handle_drain(handler) -> None:
    """POST /admin/drain (docs/serving.md#fault-tolerance): stop
    admission, signal the engine fleet (KV scope ``serve`` key
    ``drain``), wait up to HOROVOD_SERVE_DRAIN_TIMEOUT for rank 0's
    ``drained`` ack — the fleet finishes every accepted request first —
    and report the outcome.  200 = drained clean (the workers exit 0);
    504 = the fleet did not acknowledge within the budget."""
    from ..common.knobs import Knobs
    from ..utils import metrics as M
    server = handler.server
    state = get_router_state(server)
    first = not state.draining
    state.draining = True
    if first:
        M.SERVE_DRAINS.inc()
    with server.kv_lock:
        now = time.time()
        server.kv.setdefault(STATS_SCOPE, {})[DRAIN_KEY] = \
            json.dumps({"t": now}).encode()
        server.kv_times.setdefault(STATS_SCOPE, {})[DRAIN_KEY] = now
    deadline = time.time() + float(Knobs()["HOROVOD_SERVE_DRAIN_TIMEOUT"])
    ack = None
    while time.time() < deadline:
        with server.kv_lock:
            ack = server.kv.get(STATS_SCOPE, {}).get(DRAINED_KEY)
        if ack is not None:
            break
        time.sleep(_POLL_S)
    out: Dict[str, Any] = {"drained": ack is not None,
                           "router": state.counters()}
    if ack is not None:
        try:
            out["engine_final"] = json.loads(ack)
        except (ValueError, TypeError):
            pass  # a torn ack still proves the drain completed
    _json_response(handler, 200 if ack is not None else 504, out)


def render_stats(server) -> Dict[str, Any]:
    """GET /serve/stats: router counters + the engine fleet's
    self-published stats (KV scope ``serve`` key ``stats``)."""
    state = get_router_state(server)
    out: Dict[str, Any] = {"router": state.counters()}
    with server.kv_lock:
        raw = server.kv.get(STATS_SCOPE, {}).get(STATS_KEY)
        journal = len(server.kv.get(JOURNAL_SCOPE, {}))
    out["journal"] = {"enabled": state.journal, "entries": journal}
    if raw is not None:
        try:
            out["engine"] = json.loads(raw)
        except (ValueError, TypeError):
            pass  # a torn PUT must not 500 the stats view
    return out


def _json_response(handler, code: int, obj: Dict[str, Any],
                   extra_headers: Optional[Dict[str, str]] = None) -> None:
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for k, v in (extra_headers or {}).items():
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(body)
