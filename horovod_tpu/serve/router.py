"""Request router: the serving plane's front door on the rendezvous
HTTP server (docs/serving.md).

``POST /generate`` accepts ``{"tokens": [...], "max_new_tokens": N}``,
enqueues the request onto the rendezvous KV (scope ``serve_req`` —
the SAME transport every other plane rides), and streams the engine's
tokens back as newline-delimited JSON while rank 0 of the engine fleet
publishes them (scope ``serve_out``).  ``GET /serve/stats`` merges the
router's queue counters with the engine's self-published stats (scope
``serve`` key ``stats``).

Fault tolerance (docs/serving.md#fault-tolerance):

  * every ACCEPTED request is also journaled to scope ``serve_journal``
    (serve/journal.py) so a fleet reset can redrive unfinished work —
    the journal write shares the admission's kv_lock critical section,
    so a journaled request and an enqueued request are the same set;
  * admission is watermark-based with hysteresis: beyond the high
    watermark requests are shed with 429 + a ``Retry-After`` header
    derived from the measured per-request service time (TPOT x tokens,
    EWMA) times the queue depth; admission resumes at the low watermark;
  * ``POST /admin/drain`` stops admission (503), signals the engine
    fleet through the KV (scope ``serve`` key ``drain``), and waits for
    rank 0's ``drained`` ack — the fleet finishes every accepted
    request, checkpoints its final stats, and exits 0 (the
    preemption-safe rolling-restart path).

The handler side runs inside runner/http_server.py's threaded server
(one thread per in-flight stream — the async queue is the KV scope, the
threads are just the drains), so the router needs no process of its
own: ``hvdrun --serve`` gives the fleet a router for free.  Stream
reads and journal writes touch the IN-PROCESS kv dict (the router lives
in the rendezvous server's process — with ``--kv-shards`` the owning
shard's store, still in-process; docs/control-plane.md), so no KV
transport error can kill a stream router-side; the worker-side KV legs
carry the bounded exp-backoff retry (serve/worker.py ``_kv_op``).

Token delivery is event-driven: rank 0's direct stream
(serve/stream.py) and the shard servers' ``serve_out`` PUT path both
notify the server's ``kv_wakeup`` condition, so ``_stream_results``
wakes on arrival instead of busy-polling; the poll interval that
remains (the fallback cadence, HOROVOD_SERVE_POLL_INTERVAL) backs off
under an EWMA-informed cap (:class:`AdaptivePoll`).  Consumed streams
are garbage-collected: once a client has drained ``.done``, the
per-request ``serve_out`` parts are deleted and the done record slims
to a tombstone, so a long-lived fleet's KV stops growing per token
(journal entries are retained — the tombstone is what redrive skips).
"""

from __future__ import annotations

import contextlib
import json
import math
import threading
import time
from typing import Any, Dict, List, Optional

from . import trace as trace_mod
from .journal import JOURNAL_SCOPE
from .replica import REPLICA_SCOPE, ReplicaRouter, scoped

REQ_SCOPE = "serve_req"
OUT_SCOPE = "serve_out"
PLAN_SCOPE = "serve_plan"
STATS_SCOPE = "serve"
STATS_KEY = "stats"
DRAIN_KEY = "drain"
DRAINED_KEY = "drained"

DEFAULT_MAX_PENDING = 64
DEFAULT_STREAM_TIMEOUT_S = 120.0
RETRY_AFTER_CAP_S = 60
_POLL_S = 0.02  # default base cadence; knob HOROVOD_SERVE_POLL_INTERVAL
_DARK_CHECK_S = 0.25  # per-stream dark-replica probe cadence


def req_key(seq: int) -> str:
    return f"req.{seq:06d}"


def _store(server, scope: str):
    """The in-process store owning ``scope``: the shard's httpd under
    --kv-shards, the server itself otherwise (runner/http_server
    store_for; every store lives in the router's process either way)."""
    from ..runner.http_server import store_for
    return store_for(server, scope)


@contextlib.contextmanager
def _locked_stores(server, *scopes):
    """Acquire the owning stores' locks for several scopes at once (in
    shard order, deduplicated — deadlock-free by canonical ordering)
    and yield scope -> store.  The enqueue+journal critical section
    spans two scopes that may live on different shards; the invariant
    'journaled set == promised set' must hold across both."""
    stores = {scope: _store(server, scope) for scope in scopes}
    ordered = sorted({id(s): s for s in stores.values()}.values(),
                     key=lambda s: getattr(s, "shard_index", 0))
    with contextlib.ExitStack() as stack:
        for s in ordered:
            stack.enter_context(s.kv_lock)
        yield stores


class AdaptivePoll:
    """EWMA-informed poll backoff for the stream drain: every empty
    wait grows the next interval 1.5x from the knob base, capped by the
    observed inter-part arrival gap's EWMA (never sleep far past when
    the next token is due) and a hard ceiling; any arrival resets to
    the base.  Pure arithmetic over an injectable clock — unit-tested
    without sleeping (tests/test_kv_shard.py)."""

    HARD_CAP_S = 0.25
    GROWTH = 1.5
    ALPHA = 0.3  # EWMA weight of the newest observed gap

    def __init__(self, base_s: float):
        self.base = max(1e-4, float(base_s))
        self._cur = self.base
        self._ewma_gap: Optional[float] = None
        self._last_data: Optional[float] = None

    def observe_data(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self._last_data is not None:
            gap = max(0.0, now - self._last_data)
            self._ewma_gap = gap if self._ewma_gap is None else (
                (1 - self.ALPHA) * self._ewma_gap + self.ALPHA * gap)
        self._last_data = now
        self._cur = self.base

    def cap(self) -> float:
        if self._ewma_gap is None:
            return self.HARD_CAP_S
        return min(self.HARD_CAP_S, max(self.base, self._ewma_gap))

    def idle(self) -> float:
        """Interval to wait now; grows the next one."""
        wait = min(self._cur, self.cap())
        self._cur = min(self.cap(), self._cur * self.GROWTH)
        return wait


class RouterState:
    """Router-side admission state: submitted/completed/rejected
    counters, the dense sequence numbering the engine fleet consumes in
    order, watermark shedding with hysteresis, the drain latch, and the
    service-time EWMA behind ``Retry-After``."""

    def __init__(self, max_pending: int = DEFAULT_MAX_PENDING,
                 stream_timeout_s: float = DEFAULT_STREAM_TIMEOUT_S,
                 shed_high: Optional[int] = None,
                 shed_low: Optional[int] = None,
                 journal: bool = True,
                 poll_interval: float = _POLL_S):
        self.max_pending = int(max_pending)
        self.stream_timeout_s = float(stream_timeout_s)
        self.poll_interval = float(poll_interval)
        self.shed_high = int(shed_high) if shed_high else self.max_pending
        if shed_low:
            self.shed_low = int(shed_low)
        else:
            self.shed_low = max(
                0, self.shed_high - max(1, self.shed_high // 4))
        self.journal = bool(journal)
        self._lock = threading.Lock()
        self.next_seq = 0
        self.completed = 0
        self.rejected = 0
        self.shed = 0
        self.draining = False
        self.reject_reason: Optional[str] = None  # set by a None claim
        self._shedding = False
        self._service_ewma: Optional[float] = None  # s of decode/request

    def try_claim(self) -> Optional[int]:
        """Next sequence number, or None under shedding/drain (the
        reason lands in ``reject_reason`` for the status code)."""
        from ..utils import metrics as M
        with self._lock:
            if self.draining:
                self.rejected += 1
                self.reject_reason = "draining"
                return None
            pending = self.next_seq - self.completed
            if self._shedding and pending <= self.shed_low:
                self._shedding = False  # hysteresis: resume admission
            if self._shedding or pending >= self.shed_high:
                self._shedding = True
                self.rejected += 1
                self.shed += 1
                self.reject_reason = "shed"
                M.SERVE_SHEDS.inc()
                return None
            seq = self.next_seq
            self.next_seq += 1
            self.reject_reason = None
            if self.journal:
                M.SERVE_JOURNAL_DEPTH.set(self.next_seq - self.completed)
            return seq

    def finish_stream(self) -> None:
        from ..utils import metrics as M
        with self._lock:
            self.completed += 1
            if self.journal:
                M.SERVE_JOURNAL_DEPTH.set(
                    max(0, self.next_seq - self.completed))

    def observe_done(self, tpot_s: Any, n_tokens: int) -> None:
        """Feed one finished request's measured decode time into the
        service-time EWMA (tpot x generated tokens) — the Retry-After
        basis.  Bad/missing measurements are ignored."""
        try:
            svc = float(tpot_s) * max(1, int(n_tokens))
        except (TypeError, ValueError):
            return
        if svc <= 0:
            return
        with self._lock:
            if self._service_ewma is None:
                self._service_ewma = svc
            else:
                self._service_ewma = 0.7 * self._service_ewma + 0.3 * svc

    def retry_after_s(self) -> int:
        """Client back-off hint for a shed: measured per-request service
        time x queue depth, in whole seconds clamped to [1, 60].  With
        no measurement yet, 1 — the cheapest honest answer."""
        with self._lock:
            pending = self.next_seq - self.completed
            svc = self._service_ewma
        if svc is None:
            return 1
        return int(min(RETRY_AFTER_CAP_S, max(1, math.ceil(pending * svc))))

    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return {"submitted": self.next_seq,
                    "completed": self.completed,
                    "rejected": self.rejected,
                    "shed": self.shed,
                    "pending": self.next_seq - self.completed,
                    "max_pending": self.max_pending,
                    "shed_high": self.shed_high,
                    "shed_low": self.shed_low,
                    "draining": self.draining,
                    "journal": self.journal}


def get_router_state(server, replica_id: int = 0) -> RouterState:
    """Lazily attach one RouterState per replica fleet to the
    rendezvous HTTP server, configured from the knob registry
    (watermarks, journal switch).  Replica 0's state is also aliased at
    ``server.serve_router`` — the pre-replica attachment point every
    existing test/tool reads (docs/serving.md#replicated-tier)."""
    rid = int(replica_id)
    states = getattr(server, "serve_routers", None)
    if states is None:
        states = server.serve_routers = {}
    if rid == 0 and getattr(server, "serve_router", None) is not None:
        states.setdefault(0, server.serve_router)
    state = states.get(rid)
    if state is None:
        from ..common.knobs import Knobs
        knobs = Knobs()
        state = states[rid] = RouterState(
            shed_high=int(knobs["HOROVOD_SERVE_SHED_HIGH"]) or None,
            shed_low=int(knobs["HOROVOD_SERVE_SHED_LOW"]) or None,
            journal=bool(knobs["HOROVOD_SERVE_JOURNAL"]),
            poll_interval=float(knobs["HOROVOD_SERVE_POLL_INTERVAL"]))
        if rid == 0:
            server.serve_router = state
    return state


def get_replica_router(server) -> ReplicaRouter:
    """Lazily attach the replica registry/affinity router
    (serve/replica.py) to the rendezvous HTTP server.  Empty until a
    replica fleet registers — a single unregistered fleet keeps the
    pre-replica fast path byte-for-byte."""
    rr = getattr(server, "serve_replicas", None)
    if rr is None:
        from ..common.knobs import Knobs
        knobs = Knobs()
        rr = server.serve_replicas = ReplicaRouter(
            affinity=bool(knobs["HOROVOD_SERVE_AFFINITY"]),
            dead_after_s=float(knobs["HOROVOD_SERVE_REPLICA_DEAD_S"]))
    return rr


def refresh_replicas(server, rr: ReplicaRouter) -> int:
    """Fold the replica registry scope and every registered replica's
    latest stats publish (fingerprints, queue depth, shed) into the
    ReplicaRouter; returns how many replicas are registered.  All reads
    are in-process store lookups; heartbeat freshness is judged from
    the server's own KV receipt stamps — a replica with a broken clock
    still ages honestly."""
    store = _store(server, REPLICA_SCOPE)
    with store.kv_lock:
        regs = dict(store.kv.get(REPLICA_SCOPE, {}))
    for key in sorted(regs):
        try:
            info = json.loads(regs[key])
            rid = int(info["replica_id"])
        except (ValueError, TypeError, KeyError):
            continue  # a torn registration must not 500 the front door
        st_scope = scoped(STATS_SCOPE, rid)
        st = _store(server, st_scope)
        with st.kv_lock:
            sraw = st.kv.get(st_scope, {}).get(STATS_KEY)
            stamp = st.kv_times.get(st_scope, {}).get(STATS_KEY)
        rr.register(rid, info, now=float(stamp or 0.0))
        if sraw is not None and stamp is not None:
            try:
                rr.update(rid, json.loads(sraw), now=float(stamp))
            except (ValueError, TypeError):
                pass  # a torn stats PUT keeps the previous advertisement
        # Least-loaded needs a signal fresher than the <= 1 Hz stats
        # heartbeat: overlay this process's OWN in-flight count for the
        # replica (requests routed here and not yet completed), so a
        # burst arriving between two heartbeats spreads instead of
        # piling onto the lowest replica id.
        state = (getattr(server, "serve_routers", None) or {}).get(rid)
        if state is not None:
            rr.note_load(rid, state.next_seq - state.completed)
    return len(rr.replicas)


def parse_generate_body(raw: bytes) -> Dict[str, Any]:
    """Validate one /generate body; raises ValueError with a
    client-renderable message."""
    try:
        body = json.loads(raw or b"{}")
    except ValueError:
        raise ValueError("body is not valid JSON")
    tokens = body.get("tokens")
    if not isinstance(tokens, list) or not tokens or \
            not all(isinstance(t, int) and t >= 0 for t in tokens):
        raise ValueError("'tokens' must be a non-empty list of token ids "
                         "(no server-side tokenizer; docs/serving.md)")
    max_new = body.get("max_new_tokens", 16)
    if not isinstance(max_new, int) or max_new < 1:
        raise ValueError("'max_new_tokens' must be a positive int")
    out = {"tokens": tokens, "max_new_tokens": max_new}
    if body.get("eos_id") is not None:
        if not isinstance(body["eos_id"], int):
            raise ValueError("'eos_id' must be an int")
        out["eos_id"] = body["eos_id"]
    return out


def _enqueue_request(server, state: RouterState, rid: int,
                     req: Dict[str, Any], key: str) -> None:
    """Journal + enqueue one request under replica ``rid``'s scopes in
    ONE critical section (both owning stores' locks held): the
    journaled set and the promised set cannot diverge."""
    rq_scope = scoped(REQ_SCOPE, rid)
    jn_scope = scoped(JOURNAL_SCOPE, rid)
    encoded = json.dumps(req).encode()
    with _locked_stores(server, rq_scope, jn_scope) as stores:
        now = time.time()
        rq = stores[rq_scope]
        rq.kv.setdefault(rq_scope, {})[key] = encoded
        rq.kv_times.setdefault(rq_scope, {})[key] = now
        if state.journal:
            jn = stores[jn_scope]
            jn.kv.setdefault(jn_scope, {})[key] = encoded
            jn.kv_times.setdefault(jn_scope, {})[key] = now


# -------------------------------------------------------- trace records
def _trace_key(replica_id: int, rid: str) -> str:
    """serve_trace store key: replica-prefixed so two replicas' dense
    rid spaces (both mint req.000000) cannot collide; within a replica
    sorted order stays admission order (trace.prune_keys)."""
    return f"r{int(replica_id):02d}.{rid}"


def _trace_put(server, tkey: str, rec: Dict[str, Any]) -> None:
    """Write one request's serve_trace record (in-process store) and
    enforce the bounded retention (serve/trace.py TRACE_RETAIN)."""
    from ..utils import metrics as M
    store = _store(server, trace_mod.TRACE_SCOPE)
    with store.kv_lock:
        scope = store.kv.setdefault(trace_mod.TRACE_SCOPE, {})
        times = store.kv_times.setdefault(trace_mod.TRACE_SCOPE, {})
        fresh = tkey not in scope
        scope[tkey] = json.dumps(rec).encode()
        times[tkey] = time.time()
        pruned = trace_mod.prune_keys(list(scope))
        for k in pruned:
            scope.pop(k, None)
            times.pop(k, None)
    try:
        if fresh:
            M.SERVE_TRACE_RECORDS.inc()
        if pruned:
            M.SERVE_TRACE_PRUNED.inc(len(pruned))
    except Exception:
        pass  # telemetry must never take the front door down


def _finalize_trace(server, trace_rec: Dict[str, Any], tkey: str,
                    done_rec: Optional[Dict[str, Any]],
                    status: str) -> None:
    """Close one request's trace record at stream end: decompose the
    measured wall time into lifecycle components that sum EXACTLY to it
    (serve/trace.py ``attribute`` — over-attribution rescaled with the
    ratio kept observable), persist, export the component histograms,
    and emit the router-side STREAM span.  A timed-out request keeps
    its record (status ``timeout``, no components) — forensics must
    cover requests that died mid-flight."""
    from ..utils import metrics as M
    now = time.time()
    wall = max(0.0, now - float(trace_rec.get("submitted_t") or now))
    trace_rec["status"] = status
    trace_rec["wall_s"] = wall
    if done_rec is not None:
        measured = dict(done_rec.get("timing") or {})
        measured["placement"] = trace_rec.get("placement_s")
        comps, ratio = trace_mod.attribute(wall, measured)
        trace_rec["components"] = comps
        trace_rec["overattribution"] = ratio
        trace_rec["finish_reason"] = done_rec.get("finish_reason")
        trace_rec["n_tokens"] = len(done_rec.get("tokens") or ())
        trace_rec["ttft_s"] = done_rec.get("ttft_s")
        trace_rec["tpot_s"] = done_rec.get("tpot_s")
        try:
            for c, v in comps.items():
                M.SERVE_COMPONENT_SECONDS.observe(v, component=c)
            M.SERVE_TRACE_OVERATTRIBUTION.set(ratio)
        except Exception:
            pass  # telemetry must never take the front door down
        from ..runner.http_server import trace_span
        ctx = trace_rec.get("trace") or {}
        trace_span(server, "stream", "STREAM",
                   start_t=now - comps["stream"], dur_s=comps["stream"],
                   args=trace_mod.span_args(ctx, "STREAM"))
    _trace_put(server, tkey, trace_rec)


def render_trace(server) -> Dict[str, Any]:
    """GET /serve/trace (docs/serving.md#request-lifecycle): tail
    analytics over the bounded per-request trace records — per-component
    p50/p99 fleet rollup plus the slowest-requests table."""
    store = _store(server, trace_mod.TRACE_SCOPE)
    with store.kv_lock:
        raw = dict(store.kv.get(trace_mod.TRACE_SCOPE, {}))
    records = []
    for k in sorted(raw):
        try:
            records.append(json.loads(raw[k]))
        except (ValueError, TypeError):
            continue  # a torn record must not 500 the analytics view
    out = trace_mod.rollup(records)
    # The raw records ride the payload (bounded by TRACE_RETAIN) so
    # `hvdrun doctor --request RID` reconstructs a lifecycle from the
    # same fetch the rollup came from.
    out["records"] = records
    return out


def handle_generate(handler) -> None:
    """POST /generate on the rendezvous server: place the request on a
    replica fleet (prefix affinity when replicas are registered —
    serve/replica.py; the single unregistered fleet otherwise), journal
    + enqueue to that replica's KV scopes, then stream ndjson lines
    ({"tokens": [...]} parts, then {"done": ...}) as the engine
    publishes them.  Connection close delimits the body (HTTP/1.0
    semantics of the rendezvous server)."""
    from ..utils import metrics as M
    server = handler.server
    length = int(handler.headers.get("Content-Length", 0))
    raw = handler.rfile.read(length)
    try:
        req = parse_generate_body(raw)
    except ValueError as e:
        _json_response(handler, 400, {"error": str(e)})
        return
    rr = get_replica_router(server)
    place_t0 = time.perf_counter()
    replicated = refresh_replicas(server, rr) > 0
    rid_replica, hit_blocks = 0, 0
    verdict = None
    if replicated:
        placed = rr.route(req["tokens"], time.time())
        verdict = rr.last_verdict
        if placed is None:
            _json_response(handler, 503, {
                "error": "no live serving replica (all heartbeats "
                         "stale); retry",
                "replicas": rr.counters(time.time())})
            return
        rid_replica, hit_blocks = placed
        try:
            M.ROUTER_ROUTED.inc(replica=str(rid_replica))
            (M.ROUTER_AFFINITY_HITS if hit_blocks
             else M.ROUTER_AFFINITY_MISSES).inc()
            M.ROUTER_REPLICAS_UP.set(len(rr.live(time.time())))
        except Exception:
            pass  # telemetry must never take the front door down
    placement_s = time.perf_counter() - place_t0
    state = get_router_state(server, rid_replica)
    seq = state.try_claim()
    if seq is None:
        if state.reject_reason == "draining":
            _json_response(handler, 503, {
                "error": "serving fleet is draining; retry against the "
                         "next fleet",
                **state.counters()})
        else:
            # Shed forensics: no sequence number is claimed, so mint a
            # shed-marker rid — the 429 response and its trace record
            # name the request they acted on.
            shed_rid = f"shed.{rid_replica}.{state.shed}"
            _json_response(handler, 429, {
                "error": "serving queue full (load shed)",
                "rid": shed_rid,
                **state.counters()},
                extra_headers={
                    "Retry-After": str(state.retry_after_s()),
                    "X-Serve-Request-Id": shed_rid})
            _trace_put(server, _trace_key(rid_replica, shed_rid), {
                "rid": shed_rid, "status": "shed",
                "submitted_t": time.time(),
                "placement_s": placement_s,
                "attempts": [{"replica": rid_replica,
                              "verdict": verdict}]})
        return
    key = req_key(seq)
    req["id"] = key
    req["submitted_t"] = time.time()
    # Causal trace context (serve/trace.py): minted ONCE here, then
    # propagated through the journal entry, the plan stream, the engine,
    # the prefill->decode handoff, and back on the done record.
    ctx = trace_mod.mint(key)
    req["trace"] = ctx
    tkey = _trace_key(rid_replica, key)
    trec: Dict[str, Any] = {
        "rid": key, "status": "running",
        "submitted_t": req["submitted_t"],
        "trace": ctx,
        "prompt_tokens": len(req["tokens"]),
        "max_new_tokens": req["max_new_tokens"],
        "placement_s": placement_s,
        "attempts": [{"replica": rid_replica, "rid": key,
                      "affinity_blocks": hit_blocks,
                      "verdict": verdict}],
    }
    try:
        _enqueue_request(server, state, rid_replica, req, key)
        _trace_put(server, tkey, trec)
        from ..runner.http_server import trace_span
        trace_span(server, "router", "ROUTE",
                   start_t=req["submitted_t"] - placement_s,
                   dur_s=placement_s,
                   args=trace_mod.span_args(ctx, "ROUTE",
                                            replica=rid_replica))
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("X-Serve-Request-Id", key)
        if replicated:
            handler.send_header("X-Serve-Replica", str(rid_replica))
            handler.send_header("X-Serve-Affinity-Blocks",
                                str(hit_blocks))
        handler.end_headers()
        _stream_results(handler, server, key, state,
                        replica_id=rid_replica,
                        rr=rr if replicated else None, req=req,
                        trace_rec=trec, trace_key=tkey)
    finally:
        state.finish_stream()


def _redispatch(server, rr: ReplicaRouter, req: Dict[str, Any],
                dead_rid: int, streamed: List[int], part: int):
    """Move one accepted stream off a dark replica: re-journal +
    re-enqueue the request on the best surviving replica with
    ``resume_emitted``/``resume_part`` set to what the client already
    received — the survivor's rank 0 applies the standard redrive
    suppression (serve/worker.py ``_apply_resume``), so the client's
    ndjson stream resumes byte-identically from the last token it saw.
    Returns ``(new_rid, new_key, new_state)`` or None (no survivor, or
    the survivor is shedding — the caller keeps waiting until the
    original replica returns or the stream times out)."""
    from ..utils import metrics as M
    now = time.time()
    placed = rr.route(req["tokens"], now, exclude=[dead_rid])
    if placed is None:
        return None
    new_rid, _ = placed
    new_state = get_router_state(server, new_rid)
    seq = new_state.try_claim()
    if seq is None:
        return None
    new_key = req_key(seq)
    rec = dict(req)
    rec["id"] = new_key
    rec["submitted_t"] = now
    rec["resume_emitted"] = [int(t) for t in streamed]
    rec["resume_part"] = int(part)
    rec["redispatched_from"] = dead_rid
    _enqueue_request(server, new_state, new_rid, rec, new_key)
    rr.note_redispatch()
    try:
        M.ROUTER_REDISPATCHES.inc()
        M.ROUTER_ROUTED.inc(replica=str(new_rid))
    except Exception:
        pass
    return new_rid, new_key, new_state


def _stream_results(handler, server, key: str, state: RouterState,
                    replica_id: int = 0,
                    rr: Optional[ReplicaRouter] = None,
                    req: Optional[Dict[str, Any]] = None,
                    trace_rec: Optional[Dict[str, Any]] = None,
                    trace_key: Optional[str] = None) -> None:
    """Drain ``serve_out`` parts for one request to the client as they
    arrive; ends with the ``.done`` record (or a timeout record).  Reads
    are in-process dict lookups — a fleet reset stalls the stream (no
    new parts) without breaking it, and the redriven fleet's resumed
    parts continue it seamlessly.  Arrival is event-driven: the direct
    stream's ingest and the shard PUT path both notify ``kv_wakeup``;
    the timed wait is only the fallback cadence, backed off by
    :class:`AdaptivePoll`.  After the client consumes ``.done`` the
    request's parts are deleted and the done record slims to a
    tombstone (the marker redrive skips) so serve_out stays bounded.

    With a replica tier (``rr`` set), a stream whose replica goes DARK
    mid-request is re-dispatched to a surviving replica
    (:func:`_redispatch`): the wait loop switches to the survivor's
    ``serve_out`` scope at the same part index and the client never
    sees the failover."""
    from ..runner.http_server import add_stream_waiter, drop_stream_waiter
    out_scope = scoped(OUT_SCOPE, replica_id)
    store = _store(server, out_scope)
    # Keyed waiter (docs/serving.md#replicated-tier): this stream wakes
    # only on ITS records, not on every record any stream ingests — the
    # broadcast condition is the fallback for bare test servers.  The
    # lost-wakeup window (record lands between the registry probe and
    # the wait) is bounded by AdaptivePoll's hard cap, same as before.
    keyed = add_stream_waiter(server, out_scope, key)
    wakeup = keyed if keyed is not None \
        else getattr(server, "kv_wakeup", None)
    poll = AdaptivePoll(state.poll_interval)
    deadline = time.time() + state.stream_timeout_s
    next_dark_check = 0.0
    part = 0
    streamed: List[int] = []  # tokens on the client's wire (redispatch)
    extra_states: List[RouterState] = []
    try:
        while True:
            with store.kv_lock:
                scope = store.kv.get(out_scope, {})
                chunk = scope.get(f"{key}.part.{part:06d}")
                done = scope.get(f"{key}.done")
            if chunk is not None:
                handler.wfile.write(chunk + b"\n")
                handler.wfile.flush()
                part += 1
                poll.observe_data()
                if rr is not None:
                    try:
                        streamed.extend(
                            int(t) for t in
                            json.loads(chunk).get("tokens", []))
                    except (ValueError, TypeError):
                        pass  # a torn part still reached the client
                continue
            if done is not None:
                handler.wfile.write(done + b"\n")
                handler.wfile.flush()
                rec: Optional[Dict[str, Any]] = None
                try:
                    rec = json.loads(done)
                    state.observe_done(rec.get("tpot_s"),
                                       len(rec.get("tokens") or ()))
                except (ValueError, TypeError):
                    rec = None  # a torn done record still ends the stream
                if trace_rec is not None and trace_key is not None:
                    _finalize_trace(server, trace_rec, trace_key,
                                    rec if isinstance(rec, dict) else None,
                                    status="done")
                _collect_consumed(store, key, part, out_scope)
                return
            if time.time() >= deadline:
                handler.wfile.write(json.dumps(
                    {"error": "timed out after "
                              f"{state.stream_timeout_s:.0f}s "
                              f"waiting for {key}"}).encode() + b"\n")
                if trace_rec is not None and trace_key is not None:
                    # Died mid-flight: the record survives for doctor
                    # --request, status says where the lifecycle ended.
                    _finalize_trace(server, trace_rec, trace_key, None,
                                    status="timeout")
                return
            if rr is not None and req is not None and \
                    time.time() >= next_dark_check:
                # Bound the dark-replica probe's cadence per stream:
                # kv_wakeup is a per-record broadcast, so checking on
                # every idle wake would fold the whole registry
                # O(streams x tokens/s) times — the heartbeat the probe
                # reads only moves at ~1 Hz anyway, and dead_after_s
                # dwarfs a quarter-second detection lag.
                next_dark_check = time.time() + _DARK_CHECK_S
                refresh_replicas(server, rr)
                if rr.is_dark(replica_id, time.time()):
                    moved = _redispatch(server, rr, req, replica_id,
                                        streamed, part)
                    if moved is not None:
                        if keyed is not None:
                            drop_stream_waiter(server, out_scope, key)
                        prev_replica = replica_id
                        replica_id, key, new_state = moved
                        if trace_rec is not None and trace_key is not None:
                            # Forensics: both replica attempts, with the
                            # delivered-prefix suppression boundary.
                            trace_rec["attempts"].append({
                                "replica": replica_id, "rid": key,
                                "redispatched_from": prev_replica,
                                "resume_part": part,
                                "suppressed_tokens": len(streamed),
                                "verdict": rr.last_verdict})
                            _trace_put(server, trace_key, trace_rec)
                        extra_states.append(new_state)
                        out_scope = scoped(OUT_SCOPE, replica_id)
                        store = _store(server, out_scope)
                        keyed = add_stream_waiter(server, out_scope, key)
                        wakeup = keyed if keyed is not None \
                            else getattr(server, "kv_wakeup", None)
                        poll.observe_data()  # survivor restarts cadence
                        continue
            wait = poll.idle()
            if wakeup is not None:
                with wakeup:
                    wakeup.wait(wait)
            else:
                time.sleep(wait)
    finally:
        if keyed is not None:
            drop_stream_waiter(server, out_scope, key)
        for st in extra_states:
            st.finish_stream()


def _collect_consumed(store, key: str, nparts: int,
                      out_scope: str = OUT_SCOPE) -> None:
    """Garbage-collect one fully-consumed stream: delete its serve_out
    parts and slim ``.done`` to a token-free tombstone.  The tombstone
    must survive — it is what redrive_plan (serve/journal.py) skips; a
    deleted done with a retained journal entry would re-admit a request
    whose client is gone."""
    done_key = f"{key}.done"
    with store.kv_lock:
        scope = store.kv.get(out_scope, {})
        times = store.kv_times.get(out_scope, {})
        for p in range(nparts):
            pk = f"{key}.part.{p:06d}"
            scope.pop(pk, None)
            times.pop(pk, None)
        done = scope.get(done_key)
        if done is None:
            return
        try:
            rec = json.loads(done)
        except (ValueError, TypeError):
            rec = {}
        scope[done_key] = json.dumps({
            "done": True, "consumed": True,
            "finish_reason": rec.get("finish_reason"),
            "n_tokens": len(rec.get("tokens") or ()),
        }).encode()


def handle_drain(handler) -> None:
    """POST /admin/drain (docs/serving.md#fault-tolerance): stop
    admission, signal the engine fleet (KV scope ``serve`` key
    ``drain``), wait up to HOROVOD_SERVE_DRAIN_TIMEOUT for rank 0's
    ``drained`` ack — the fleet finishes every accepted request first —
    and report the outcome.  200 = drained clean (the workers exit 0);
    504 = the fleet did not acknowledge within the budget."""
    from ..common.knobs import Knobs
    from ..utils import metrics as M
    server = handler.server
    rr = get_replica_router(server)
    rids = (sorted(rr.replicas)
            if refresh_replicas(server, rr) else [0])
    first = False
    for rid in rids:
        state = get_router_state(server, rid)
        first = first or not state.draining
        state.draining = True
    if first:
        M.SERVE_DRAINS.inc()
    stores = {}
    for rid in rids:
        st_scope = scoped(STATS_SCOPE, rid)
        store = stores[rid] = (st_scope, _store(server, st_scope))
        with store[1].kv_lock:
            now = time.time()
            store[1].kv.setdefault(st_scope, {})[DRAIN_KEY] = \
                json.dumps({"t": now}).encode()
            store[1].kv_times.setdefault(st_scope, {})[DRAIN_KEY] = now
    deadline = time.time() + float(Knobs()["HOROVOD_SERVE_DRAIN_TIMEOUT"])
    acks: Dict[int, Any] = {}
    while time.time() < deadline and len(acks) < len(rids):
        for rid in rids:
            if rid in acks:
                continue
            st_scope, store = stores[rid]
            with store.kv_lock:
                ack = store.kv.get(st_scope, {}).get(DRAINED_KEY)
            if ack is not None:
                acks[rid] = ack
        if len(acks) < len(rids):
            time.sleep(_POLL_S)
    drained = len(acks) == len(rids)
    out: Dict[str, Any] = {
        "drained": drained,
        "router": get_router_state(server, rids[0]).counters()}
    if len(rids) > 1:
        out["replicas_drained"] = sorted(acks)
        out["replicas"] = rids
    if acks:
        try:
            out["engine_final"] = json.loads(acks[min(acks)])
        except (ValueError, TypeError):
            pass  # a torn ack still proves the drain completed
    _json_response(handler, 200 if drained else 504, out)


def render_stats(server) -> Dict[str, Any]:
    """GET /serve/stats: router counters + the engine fleet's
    self-published stats (KV scope ``serve`` key ``stats``), plus the
    control-plane shard health when the KV is sharded (the operational
    view `hvdrun doctor --serve` renders; docs/control-plane.md)."""
    state = get_router_state(server)
    out: Dict[str, Any] = {"router": state.counters()}
    st = _store(server, STATS_SCOPE)
    with st.kv_lock:
        raw = st.kv.get(STATS_SCOPE, {}).get(STATS_KEY)
    jn = _store(server, JOURNAL_SCOPE)
    with jn.kv_lock:
        journal = len(jn.kv.get(JOURNAL_SCOPE, {}))
    out["journal"] = {"enabled": state.journal, "entries": journal}
    if raw is not None:
        try:
            out["engine"] = json.loads(raw)
        except (ValueError, TypeError):
            pass  # a torn PUT must not 500 the stats view
    rr = get_replica_router(server)
    if refresh_replicas(server, rr):
        # Replicated tier (docs/serving.md#replicated-tier): placement
        # counters + per-replica registry/load/digest rows, each
        # replica's admission state, and its full self-published engine
        # stats (kv_pool + spill occupancy included) — the payload
        # `hvdrun doctor --serve` renders as the per-replica table.
        now = time.time()
        view = rr.counters(now)
        view["admission"] = {
            str(rid): get_router_state(server, rid).counters()
            for rid in sorted(rr.replicas)}
        view["engines"] = {
            str(rid): rr.replicas[rid].get("stats", {})
            for rid in sorted(rr.replicas)}
        out["replicas"] = view
    from ..runner.http_server import kv_shard_health, watch_state_for
    shards = kv_shard_health(server)
    if shards is not None:
        out["kv_shards"] = shards
    ws = watch_state_for(server)
    if ws is not None:
        # Watch plane (docs/watch.md): the on-call reader checking the
        # front door should see firing alerts next to admission state.
        firing = ws.engine.evaluate()
        out["alerts"] = {
            "firing": len(firing),
            "critical": sum(1 for f in firing
                            if f.get("severity") == "critical"),
            "rules": sorted({f["rule"] for f in firing}),
        }
    return out


def _json_response(handler, code: int, obj: Dict[str, Any],
                   extra_headers: Optional[Dict[str, str]] = None) -> None:
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for k, v in (extra_headers or {}).items():
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(body)
