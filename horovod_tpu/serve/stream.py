"""Direct token streaming: the serving plane's hottest path off the KV
(docs/control-plane.md#direct-streaming).

Before this module, every generated token rode the rendezvous KV twice:
rank 0 PUT a ``serve_out`` part, then the router busy-polled the scope
to stream it to the client — one HTTP round trip plus a poll loop per
part, all through the single rendezvous accept loop.  Now rank 0 holds
ONE persistent chunked ``POST /serve/stream`` connection to the router
and writes newline-delimited JSON records as the engine emits them:

    {"rid": "req.000007", "part": 0, "tokens": [1, 2, 3]}
    {"rid": "req.000007", "done": {"done": true, "tokens": [...], ...}}

The router-side handler (:func:`handle_stream`, running inside the
rendezvous server process) ingests each record by MIRRORING it into the
``serve_out`` store — the exact keys/values the KV PUT path would have
written — and waking the stream drains via the server's ``kv_wakeup``
condition.  Two properties follow by construction:

  * the journal keeps its KV source of truth: redrive's emitted-prefix
    recovery (serve/journal.py) reads the same ``serve_out`` keys
    whether parts arrived directly or via KV PUTs, so a fleet reset —
    or a streaming-connection loss mid-request — resumes client streams
    byte-identically (fall back to KV recovery of the published
    prefix);
  * the consumer is source-agnostic: the router's ``_stream_results``
    waits on one condition that both this handler and the shard
    servers' ``serve_out`` PUT path notify, so a worker that fell back
    to KV publishing (HOROVOD_SERVE_DIRECT=0, or the connection broke)
    feeds the same stream seamlessly.

Worker side, :class:`DirectTokenStream` wraps the persistent connection:
``send`` returns False on any transport error (the caller falls back to
``_kv_put`` for that record and a reconnect is attempted on the next
send), so a router restart degrades to the KV path instead of dropping
tokens.  Everything here is stdlib-only and jax-free.
"""

from __future__ import annotations

import http.client
import json
import time
from typing import Any, Dict, Iterator, Optional

STREAM_ROUTE = "/serve/stream"


# -------------------------------------------------------- router (ingest)
def _iter_chunked(rfile) -> Iterator[bytes]:
    """Decode a chunked transfer-encoded request body incrementally —
    BaseHTTPRequestHandler does not, and the whole point is reading
    records as the worker writes them, not at connection close."""
    while True:
        line = rfile.readline(1026).strip()
        if not line:
            return
        try:
            size = int(line.split(b";")[0], 16)
        except ValueError:
            return  # torn framing: end the stream, worker will fall back
        if size == 0:
            rfile.readline()  # trailing CRLF after the last-chunk marker
            return
        data = rfile.read(size)
        rfile.readline()  # chunk-terminating CRLF
        if not data:
            return
        yield data


def _iter_records(handler) -> Iterator[Dict[str, Any]]:
    """ndjson records from the request body: chunked (the persistent
    stream) or Content-Length'd (a one-shot batch) both work."""
    if handler.headers.get("Transfer-Encoding", "").lower() == "chunked":
        chunks = _iter_chunked(handler.rfile)
    else:
        length = int(handler.headers.get("Content-Length", 0))
        chunks = iter((handler.rfile.read(length),)) if length else iter(())
    buf = b""
    for chunk in chunks:
        buf += chunk
        while b"\n" in buf:
            line, _, buf = buf.partition(b"\n")
            if not line.strip():
                continue
            try:
                yield json.loads(line)
            except (ValueError, TypeError):
                continue  # a torn record must not kill the stream
    if buf.strip():
        try:
            yield json.loads(buf)
        except (ValueError, TypeError):
            pass


def ingest_record(server, rec: Dict[str, Any]) -> bool:
    """Mirror one direct-stream record into the ``serve_out`` store —
    byte-compatible with the KV PUT path, so redrive prefix recovery
    and late-attaching client streams see one truth — and wake the
    stream drains.  Returns False for records without a usable shape."""
    from ..runner.http_server import store_for, wake_stream
    from .router import OUT_SCOPE
    if rec.get("kind") == "kvblock":
        # Prefill->decode KV handoff riding the same persistent stream
        # (docs/serving.md#replicated-tier): mirrored into its serve_kv
        # scope exactly as the KV PUT fallback would have written it.
        kv_scope = rec.get("scope")
        key = rec.get("key")
        payload = rec.get("payload")
        if not isinstance(kv_scope, str) or \
                not kv_scope.startswith("serve_kv") or \
                not isinstance(key, str) or payload is None:
            return False
        store = store_for(server, kv_scope)
        now = time.time()
        with store.kv_lock:
            store.kv.setdefault(kv_scope, {})[key] = \
                json.dumps(payload).encode()
            store.kv_times.setdefault(kv_scope, {})[key] = now
        wake_stream(server, kv_scope, key)
        if isinstance(payload, dict) and payload.get("trace"):
            # The handoff's router transit, on the merged timeline: one
            # instant-like span linking the prefill fleet's export to
            # the decode fleet's import (docs/serving.md
            # #request-lifecycle).
            from ..runner.http_server import trace_span
            from . import trace as trace_mod
            trace_span(server, "handoff", "KV_HANDOFF",
                       start_t=now, dur_s=0.0,
                       args=trace_mod.span_args(
                           payload["trace"], "KV_HANDOFF",
                           rid=str(payload.get("req_id") or "")))
        return True
    rid = rec.get("rid")
    if not rid or not isinstance(rid, str):
        return False
    # Replica scoping (serve/replica.py): replica K's worker labels its
    # records with the scoped output scope; unlabeled records are the
    # single-fleet/replica-0 path, byte-identical to before.
    out_scope = rec.get("scope", OUT_SCOPE)
    if not isinstance(out_scope, str) or \
            out_scope.split(".r", 1)[0] != OUT_SCOPE:
        return False
    if "tokens" in rec and rec.get("part") is not None:
        key = f"{rid}.part.{int(rec['part']):06d}"
        value = json.dumps({"tokens": rec["tokens"]}).encode()
        ntokens = len(rec["tokens"] or ())
    elif isinstance(rec.get("done"), dict):
        key = f"{rid}.done"
        value = json.dumps(rec["done"]).encode()
        ntokens = 0
    else:
        return False
    store = store_for(server, out_scope)
    now = time.time()
    with store.kv_lock:
        store.kv.setdefault(out_scope, {})[key] = value
        store.kv_times.setdefault(out_scope, {})[key] = now
    if ntokens:
        try:
            from ..utils import metrics as M
            M.SERVE_STREAM_DIRECT_TOKENS.inc(ntokens)
        except Exception:
            pass  # telemetry must never break token delivery
    wake_stream(server, out_scope, key)
    return True


def handle_stream(handler) -> None:
    """POST /serve/stream: drain rank 0's persistent record stream into
    the serve_out store until the worker closes it (or dies — a torn
    connection just ends the loop; the worker's next publish falls back
    to KV PUTs and the streams continue from the same store)."""
    server = handler.server
    ingested = 0
    try:
        for rec in _iter_records(handler):
            if ingest_record(server, rec):
                ingested += 1
    except (OSError, ValueError):
        pass  # connection loss mid-record: the KV fallback takes over
    try:
        body = json.dumps({"ok": True, "records": ingested}).encode()
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
    except OSError:
        pass  # peer already gone; nothing to acknowledge


# -------------------------------------------------------- worker (emit)
class DirectTokenStream:
    """Rank 0's persistent direct connection to the router.  ``send``
    never raises: False means the record was NOT delivered (connection
    down and one reconnect attempt failed) and the caller must publish
    it via the KV instead.  The connection re-establishes lazily on a
    later send, so a router restart costs a KV-published window, not
    the stream."""

    def __init__(self, addr: str, port: int, timeout: float = 10.0):
        self.addr = addr
        self.port = int(port)
        self.timeout = float(timeout)
        self._conn: Optional[http.client.HTTPConnection] = None
        self.sent = 0
        self.fallbacks = 0  # caller-visible: records that missed direct

    def _connect(self) -> bool:
        try:
            conn = http.client.HTTPConnection(self.addr, self.port,
                                              timeout=self.timeout)
            conn.putrequest("POST", STREAM_ROUTE)
            conn.putheader("Transfer-Encoding", "chunked")
            conn.putheader("Content-Type", "application/x-ndjson")
            conn.endheaders()
            self._conn = conn
            return True
        except OSError:
            self._conn = None
            return False

    def _write(self, data: bytes) -> bool:
        assert self._conn is not None
        try:
            self._conn.send(b"%x\r\n" % len(data) + data + b"\r\n")
            return True
        except OSError:
            try:
                self._conn.close()
            finally:
                self._conn = None
            return False

    def send(self, record: Dict[str, Any]) -> bool:
        data = json.dumps(record).encode() + b"\n"
        if self._conn is not None and self._write(data):
            self.sent += 1
            return True
        # one reconnect attempt per send: a dead router degrades this
        # record to the KV path without stalling the engine tick
        if self._connect() and self._write(data):
            self.sent += 1
            return True
        self.fallbacks += 1
        return False

    def close(self) -> None:
        conn, self._conn = self._conn, None
        if conn is None:
            return
        try:
            conn.send(b"0\r\n\r\n")
            conn.getresponse().read()
        except OSError:
            pass  # a torn close loses no data: everything sent is stored
        finally:
            conn.close()
