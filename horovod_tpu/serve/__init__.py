"""Serving plane: continuous-batching multi-host inference over the
trained ``models/`` checkpoints (docs/serving.md).

Four legs, mirroring how every training plane is built:

  * **decode path** — paged KV cache prefill/decode added to the models
    themselves (models/llama.py, models/moe_llama.py ``init_cache`` /
    ``apply_cached``), proven bit-near the full-sequence forward;
  * **engine** (:mod:`.engine`) — in-flight batching scheduler + one
    jit'd mixed prefill/decode step per tick over a static slot table;
  * **router** (:mod:`.router`) — ``POST /generate`` + ``GET
    /serve/stats`` on the rendezvous HTTP server, feeding the engine
    fleet over the existing KV transport (``hvdrun --serve`` launches
    everything);
  * **SLO observability for free** — hvd_serve_* metrics at /metrics,
    per-request NEGOTIATE/PREFILL/DECODE spans in the merged timeline,
    engine liveness on /health.

Heavy modules load lazily: importing :mod:`horovod_tpu` must not pay
for jax-model machinery a training job never uses.
"""

from __future__ import annotations

from .config import ServeConfig, from_knobs, validate_serve_knobs

_LAZY = {
    "ServeEngine": ("engine", "ServeEngine"),
    "Scheduler": ("engine", "Scheduler"),
    "BlockAllocator": ("engine", "BlockAllocator"),
    "Request": ("engine", "Request"),
    "cache_shardings": ("engine", "cache_shardings"),
    "save_servable": ("engine", "save_servable"),
    "load_servable": ("engine", "load_servable"),
    "FleetFrontend": ("worker", "FleetFrontend"),
    "JOURNAL_SCOPE": ("journal", "JOURNAL_SCOPE"),
    "redrive_plan": ("journal", "redrive_plan"),
    "emitted_prefix": ("journal", "emitted_prefix"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(f".{mod}", __name__), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["ServeConfig", "from_knobs", "validate_serve_knobs",
           *_LAZY.keys()]
