"""Request-lifecycle tracing plane (docs/serving.md#request-lifecycle).

The replicated serving tier broke the single-fleet assumption the PR-5
timeline was built on: one request now crosses the router, a
prefix-affinity-placed replica, a prefill-role engine, a KV handoff to
a decode-role engine, possibly a host-RAM spill reload, a dark-replica
re-dispatch, and the direct stream.  This module is the causal glue —
a compact trace context minted at router admission and propagated
through every hop, plus the per-request SLO attribution that decomposes
measured TTFT/decode wall time into lifecycle components that sum
EXACTLY to the measurement (the perf/ledger.py sums-exactly
discipline).

Determinism contract (the hvdlint ``trace-context`` rule): span ids are
a pure function of (request id, hop name) — FNV-1a, never RNG or
clock — so a journal redrive, a re-dispatched stream, or a scenario
replay re-mints the IDENTICAL ids, and the merged Perfetto view links
parents to children across replica fleets without coordination.  This
module deliberately imports neither ``time`` nor ``random``: callers
pass timestamps in (the scenario harness passes virtual-clock ticks).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

# KV scope holding one bounded-retention record per traced request.
TRACE_SCOPE = "serve_trace"

# Per-request records retained in the serve_trace scope (oldest keys
# pruned on write; rids are req.{seq:06d}, so sorted order = admission
# order).
TRACE_RETAIN = 256

# Lifecycle components, in causal order.  ``attribute`` guarantees they
# sum exactly to the measured wall time; ``stream`` is the residual leg
# (router observe -> client delivery plus anything unmodeled).
COMPONENTS = ("queue", "placement", "prefill", "handoff", "decode",
              "stream")

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_FNV_MASK = 0xFFFFFFFFFFFFFFFF


def _fnv64(data: str) -> int:
    h = _FNV_OFFSET
    for b in data.encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _FNV_MASK
    return h


def span_id(rid: str, hop: str) -> str:
    """Deterministic span id: a pure function of (request id, hop name).
    Two processes that never talked emit the same id for the same hop of
    the same request — that is what links the merged trace."""
    return f"{_fnv64(f'{rid}/{hop}'):016x}"


def mint(rid: str) -> Dict[str, Any]:
    """Trace context minted once, at router admission: the root span id
    plus a hop counter every downstream leg increments."""
    return {"rid": rid, "span": span_id(rid, "admit"), "hop": 0}


def child(ctx: Dict[str, Any], hop: str) -> Dict[str, Any]:
    """Derive the next hop's context: new span id, parent = the previous
    hop's span, hop counter bumped.  Pure — re-deriving the same hop of
    the same request yields the same ids."""
    rid = str(ctx.get("rid", ""))
    n = int(ctx.get("hop", 0)) + 1
    return {"rid": rid, "span": span_id(rid, f"{n}.{hop}"),
            "parent": ctx.get("span"), "hop": n}


def span_args(ctx: Optional[Dict[str, Any]], hop: str,
              **extra: Any) -> Dict[str, Any]:
    """Timeline ``record_span`` args carrying the causal context — the
    shape the hvdlint trace-context rule recognizes (a ``rid`` key,
    span ids minted via :func:`span_id`).  Tolerates a missing context
    (pre-trace submitters): the rid-only args still tag the lane."""
    ctx = ctx or {}
    rid = str(ctx.get("rid", extra.pop("rid", "")))
    args: Dict[str, Any] = {"rid": rid, "hop": hop,
                            "span": span_id(rid, hop)}
    if ctx.get("span"):
        args["parent"] = ctx["span"]
    args.update(extra)
    return args


# ------------------------------------------------------- SLO attribution
def attribute(wall_s: float, measured: Dict[str, Any]
              ) -> Tuple[Dict[str, float], float]:
    """Decompose a request's measured wall time into the lifecycle
    components, ledger-style: the named components come from measured
    hop durations, ``stream`` absorbs the unattributed residual, and
    when measurement skew makes the parts overshoot the wall they are
    rescaled to fit with the overshoot kept OBSERVABLE as the returned
    over-attribution ratio (modeled/measured; 1.0 = parts fit).

    Invariant: ``math.fsum(components.values()) == wall_s`` exactly
    (float-exact — the residual leg is computed as a difference, and
    rescale dust is folded back into the largest modeled part)."""
    wall = max(0.0, float(wall_s))
    parts = {c: max(0.0, float(measured.get(c) or 0.0))
             for c in COMPONENTS if c != "stream"}
    modeled = math.fsum(parts.values())
    ratio = 1.0
    scale = 1.0
    if modeled > wall:
        # wall == 0 with modeled parts is unbounded overshoot; clamp to
        # a finite, JSON-safe ratio that still reads as "over".
        ratio = (modeled / wall) if wall > 0.0 else max(1.0, modeled)
        scale = (wall / modeled) if modeled > 0.0 else 0.0
    comps = {c: parts[c] * scale for c in parts}
    resid = wall - math.fsum(comps.values())
    if resid < 0.0 and comps:
        big = max(comps, key=lambda c: (comps[c], c))
        comps[big] = max(0.0, comps[big] + resid)
        resid = wall - math.fsum(comps[c] for c in comps)
    comps["stream"] = max(0.0, resid)
    ordered = {c: comps.get(c, 0.0) for c in COMPONENTS}
    return ordered, ratio


# --------------------------------------------------------- fleet rollup
def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile — deterministic, no numpy (the
    scenario-harness convention)."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(math.ceil(q / 100.0 * len(vs))) - 1))
    return vs[idx]


def rollup(records: List[Dict[str, Any]], slowest: int = 10
           ) -> Dict[str, Any]:
    """Tail analytics over per-request trace records (``GET
    /serve/trace``): per-component p50/p99 across completed requests
    plus the slowest-requests table, wall-time descending."""
    comp_vals: Dict[str, List[float]] = {c: [] for c in COMPONENTS}
    walls: List[Tuple[float, Dict[str, Any]]] = []
    completed = 0
    for rec in records:
        comps = rec.get("components")
        if comps:
            completed += 1
            for c in COMPONENTS:
                comp_vals[c].append(float(comps.get(c, 0.0) or 0.0))
        walls.append((float(rec.get("wall_s", 0.0) or 0.0), rec))
    walls.sort(key=lambda t: (-t[0], str(t[1].get("rid", ""))))
    table = []
    for wall, rec in walls[:max(0, int(slowest))]:
        comps = rec.get("components") or {}
        worst = max(((c, float(comps.get(c, 0.0) or 0.0))
                     for c in COMPONENTS), key=lambda t: t[1],
                    default=(None, 0.0))
        table.append({
            "rid": rec.get("rid"), "status": rec.get("status"),
            "wall_s": round(wall, 6),
            "replica": (rec.get("attempts") or [{}])[-1].get("replica"),
            "attempts": len(rec.get("attempts") or []),
            "worst_component": worst[0] if worst[1] > 0.0 else None,
            "worst_s": round(worst[1], 6),
        })
    return {
        "requests": len(records),
        "completed": completed,
        "components": {
            c: {"count": len(comp_vals[c]),
                "p50_s": round(percentile(comp_vals[c], 50), 6),
                "p99_s": round(percentile(comp_vals[c], 99), 6)}
            for c in COMPONENTS},
        "slowest": table,
    }


def prune_keys(keys: List[str], retain: int = TRACE_RETAIN) -> List[str]:
    """Keys to delete so the serve_trace scope keeps at most ``retain``
    records: the oldest (lowest-sorting — rids embed the admission
    sequence number) beyond the retention bound."""
    if retain <= 0:
        return sorted(keys)
    extra = len(keys) - retain
    if extra <= 0:
        return []
    return sorted(keys)[:extra]
