"""Serving-plane configuration: the HOROVOD_SERVE_* knob surface.

Deliberately free of jax/model imports so ``hvd.init()`` can validate
the knobs (runtime.py) without paying the serving plane's import cost,
mirroring how the wire/overlap planes validate at init
(docs/serving.md; docs/knobs.md).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static shape/budget contract of one continuous-batching engine.

    ``max_slots`` and ``prefill_chunk`` fix the compiled step's shapes
    (slot table height and chunk width); the knobs bound admission.
    """

    port: int = 0
    max_batch_tokens: int = 2048
    max_seq_len: int = 2048
    cache_blocks: int = 4096
    block_size: int = 16
    max_slots: int = 8
    prefill_chunk: int = 64
    eos_id: Optional[int] = None
    # Raw-speed legs (docs/serving.md#raw-speed).  All three preserve
    # greedy output exactly (prefix sharing reuses identical KV, chunking
    # is a scheduling change, speculative tokens are verified before
    # emission), so they default to the fast path; the knobs exist for
    # the degraded/off modes and for A/B measurement.
    prefix_cache: bool = True
    spec_decode: bool = True
    spec_k: int = 4
    # Replicated tier (docs/serving.md#replicated-tier): this fleet's
    # identity among N independent replica fleets behind one router,
    # the prefill/decode role split within a replica, and the host-RAM
    # spill capacity behind the device pool.  replica_id 0 keeps the
    # unscoped KV names, so a single fleet is byte-for-byte the
    # pre-replica deployment.
    replica_id: int = 0
    replicas: int = 1
    prefill_ranks: int = 0
    spill_blocks: int = 0

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.block_size)  # ceil

    def validate(self, model_max_seq: Optional[int] = None) -> None:
        if not (0 <= self.port <= 65535):
            raise ValueError(
                f"HOROVOD_SERVE_PORT={self.port} invalid; must be in "
                "[0, 65535] (0 = ephemeral; docs/serving.md)")
        for name, v in (("HOROVOD_SERVE_MAX_BATCH_TOKENS",
                         self.max_batch_tokens),
                        ("HOROVOD_SERVE_MAX_SEQ_LEN", self.max_seq_len),
                        ("HOROVOD_SERVE_CACHE_BLOCKS", self.cache_blocks)):
            if v <= 0:
                raise ValueError(
                    f"{name}={v} invalid; must be positive "
                    "(docs/serving.md)")
        if self.block_size <= 0 or self.max_slots <= 0:
            raise ValueError(
                f"serve block_size={self.block_size} / "
                f"max_slots={self.max_slots} invalid; must be positive")
        if self.prefill_chunk <= 0 or \
                self.prefill_chunk > self.max_batch_tokens:
            raise ValueError(
                f"HOROVOD_SERVE_PREFILL_CHUNK={self.prefill_chunk} "
                "invalid; must be in [1, max_batch_tokens="
                f"{self.max_batch_tokens}] (docs/serving.md)")
        if self.spec_k < 1:
            raise ValueError(
                f"HOROVOD_SERVE_SPEC_K={self.spec_k} invalid; the draft "
                "length must be >= 1 (docs/serving.md#raw-speed)")
        if self.spec_decode and self.spec_k + 1 > self.prefill_chunk:
            raise ValueError(
                f"HOROVOD_SERVE_SPEC_K={self.spec_k} exceeds the verify "
                f"row width: need spec_k + 1 <= prefill_chunk="
                f"{self.prefill_chunk} (the compiled step verifies the "
                "bonus token + K drafts in one row; docs/serving.md)")
        if self.replicas < 1:
            raise ValueError(
                f"HOROVOD_SERVE_REPLICAS={self.replicas} invalid; the "
                "replica tier needs >= 1 fleet "
                "(docs/serving.md#replicated-tier)")
        if not (0 <= self.replica_id < self.replicas):
            raise ValueError(
                f"HOROVOD_SERVE_REPLICA_ID={self.replica_id} invalid; "
                f"must be in [0, HOROVOD_SERVE_REPLICAS={self.replicas})"
                " (docs/serving.md#replicated-tier)")
        if self.prefill_ranks < 0:
            raise ValueError(
                f"HOROVOD_SERVE_PREFILL_RANKS={self.prefill_ranks} "
                "invalid; must be >= 0 (0 = colocated prefill+decode; "
                "docs/serving.md#replicated-tier)")
        if self.spill_blocks < 0:
            raise ValueError(
                f"HOROVOD_SERVE_SPILL_BLOCKS={self.spill_blocks} "
                "invalid; must be >= 0 (0 = spill off; "
                "docs/serving.md#replicated-tier)")
        if self.spill_blocks and not self.prefix_cache:
            raise ValueError(
                f"HOROVOD_SERVE_SPILL_BLOCKS={self.spill_blocks} needs "
                "the radix prefix cache on (HOROVOD_SERVE_PREFIX_CACHE); "
                "only tree-held cold blocks spill "
                "(docs/serving.md#replicated-tier)")
        if model_max_seq is not None and self.max_seq_len > model_max_seq:
            raise ValueError(
                f"HOROVOD_SERVE_MAX_SEQ_LEN={self.max_seq_len} exceeds "
                f"the served model's max_seq={model_max_seq}; RoPE "
                "tables end there (docs/serving.md)")


def _opt(knobs: Any, name: str, default: Any) -> Any:
    """Knob lookup tolerant of partial mappings (tests validate with
    plain dicts that predate the fault-tolerance/raw-speed knobs)."""
    try:
        return knobs[name]
    except (KeyError, TypeError):
        return default


def from_knobs(knobs: Any, **overrides: Any) -> ServeConfig:
    """Build a validated ServeConfig from a knob snapshot
    (common/knobs.Knobs or any mapping with __getitem__)."""
    kw = dict(
        port=int(knobs["HOROVOD_SERVE_PORT"]),
        max_batch_tokens=int(knobs["HOROVOD_SERVE_MAX_BATCH_TOKENS"]),
        max_seq_len=int(knobs["HOROVOD_SERVE_MAX_SEQ_LEN"]),
        cache_blocks=int(knobs["HOROVOD_SERVE_CACHE_BLOCKS"]),
        prefill_chunk=int(_opt(knobs, "HOROVOD_SERVE_PREFILL_CHUNK", 64)),
        prefix_cache=bool(_opt(knobs, "HOROVOD_SERVE_PREFIX_CACHE", True)),
        spec_decode=bool(_opt(knobs, "HOROVOD_SERVE_SPEC", True)),
        spec_k=int(_opt(knobs, "HOROVOD_SERVE_SPEC_K", 4)),
        replica_id=int(_opt(knobs, "HOROVOD_SERVE_REPLICA_ID", 0)),
        replicas=int(_opt(knobs, "HOROVOD_SERVE_REPLICAS", 1)),
        prefill_ranks=int(_opt(knobs, "HOROVOD_SERVE_PREFILL_RANKS", 0)),
        spill_blocks=int(_opt(knobs, "HOROVOD_SERVE_SPILL_BLOCKS", 0)),
    )
    kw.update(overrides)
    cfg = ServeConfig(**kw)
    cfg.validate()
    return cfg


def validate_serve_knobs(knobs: Any) -> None:
    """Init-time validation contract (runtime.py): a bad HOROVOD_SERVE_*
    value must fail hvd.init(), not a serving tick hours later."""
    from_knobs(knobs)
    drain = float(_opt(knobs, "HOROVOD_SERVE_DRAIN_TIMEOUT", 30.0))
    if drain <= 0:
        raise ValueError(
            f"HOROVOD_SERVE_DRAIN_TIMEOUT={drain} invalid; the drain "
            "budget must be positive seconds (docs/serving.md)")
    high = int(_opt(knobs, "HOROVOD_SERVE_SHED_HIGH", 0))
    low = int(_opt(knobs, "HOROVOD_SERVE_SHED_LOW", 0))
    if high < 0 or low < 0:
        raise ValueError(
            f"HOROVOD_SERVE_SHED_HIGH={high} / HOROVOD_SERVE_SHED_LOW="
            f"{low} invalid; shed watermarks must be >= 0 "
            "(docs/serving.md)")
    if high and low and low > high:
        raise ValueError(
            f"HOROVOD_SERVE_SHED_LOW={low} exceeds "
            f"HOROVOD_SERVE_SHED_HIGH={high}; hysteresis needs "
            "low <= high (docs/serving.md)")
    poll = float(_opt(knobs, "HOROVOD_SERVE_POLL_INTERVAL", 0.02))
    if poll <= 0:
        raise ValueError(
            f"HOROVOD_SERVE_POLL_INTERVAL={poll} invalid; the router's "
            "stream-probe interval must be positive seconds "
            "(docs/control-plane.md)")
    dead = float(_opt(knobs, "HOROVOD_SERVE_REPLICA_DEAD_S", 3.0))
    if dead <= 0:
        raise ValueError(
            f"HOROVOD_SERVE_REPLICA_DEAD_S={dead} invalid; the router's "
            "dark-replica threshold must be positive seconds "
            "(docs/serving.md#replicated-tier)")
