"""Replicated serving tier (docs/serving.md#replicated-tier).

One lockstep fleet is a serving ceiling: rank 0 plans every tick and
all ranks run one engine.  This module scales the front door OUT the
way Horovod scaled training out (data-parallel replication, arxiv
1802.05799): N independent serving replica fleets register behind one
router/rendezvous process under the ``replicas`` KV scope, and the
router places each ``POST /generate`` with **prefix affinity** — the
replica whose radix prefix cache (serve/engine.py PrefixCache) already
holds the longest prefix of the prompt wins, so replication multiplies
the cache instead of fragmenting it.

The affinity protocol is fingerprint-based and deliberately compact:

  * each replica's rank 0 piggybacks ``prefix_fingerprints`` — rolling
    sha1 fingerprints of the top of its radix tree, one per full token
    block along each cached path — on the stats publish it already
    makes every second (serve/worker.py ``_publish_stats``);
  * the router computes the SAME rolling fingerprints over the
    prompt's full blocks (``prompt_fingerprints``) and routes to the
    replica matching the deepest one, falling back to least-loaded
    (queue-depth from the same stats stream, then lowest replica id);
  * a replica whose stats heartbeat goes stale is DARK: it receives no
    traffic, and streams it was serving are re-dispatched to a
    surviving replica with their already-streamed prefix suppressed —
    the per-replica journal redrive semantics (serve/journal.py),
    driven router-side.

Everything here is lockstep-grade deterministic (the hvdlint
``serve-determinism`` rule covers this module): no RNG, no clock reads
— callers pass ``now`` explicitly — and no unordered-set iteration, so
the affinity map and the replica digest fold replay identically.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

# KV scope the replica registry lives under on the shared rendezvous
# server: one ``replica.KK`` key per fleet, written by each replica's
# rank 0 at startup (docs/serving.md#replicated-tier).
REPLICA_SCOPE = "replicas"


def replica_key(replica_id: int) -> str:
    return f"replica.{replica_id:02d}"


def scoped(base: str, replica_id: int) -> str:
    """Per-replica KV scope name: replica 0 keeps the unscoped names
    (a single fleet is byte-for-byte the pre-replica deployment, and
    every existing test/tool keeps working); replica K > 0 suffixes
    ``.rKK`` so N fleets share one rendezvous KV without collisions.
    The suffix rides into kvshard.shard_for_scope unchanged, so each
    replica's scopes spread over the shards like any other scope."""
    if replica_id == 0:
        return base
    return f"{base}.r{replica_id:02d}"


# ------------------------------------------------------- fingerprints
def _fold_block(h, tokens) -> None:
    h.update((",".join(str(int(t)) for t in tokens) + ";").encode())


def prompt_fingerprints(tokens: List[int], block_size: int,
                        max_blocks: int = 32) -> List[str]:
    """Rolling fingerprints of a prompt's full token blocks:
    ``fps[i]`` identifies the prompt's first ``i + 1`` blocks as a
    unit, so matching a replica's advertisement at depth i means that
    replica's radix tree holds that exact (i + 1)-block prefix.  Pure
    function of (tokens, block_size) — identical on router and every
    replica."""
    fps: List[str] = []
    h = hashlib.sha1()
    n_full = min(len(tokens) // block_size, max_blocks)
    for i in range(n_full):
        _fold_block(h, tokens[i * block_size:(i + 1) * block_size])
        fps.append(h.copy().hexdigest()[:12])
    return fps


def prefix_fingerprints(cache: Any, max_nodes: int = 64) -> List[str]:
    """Compact top-of-tree advertisement of a PrefixCache: breadth-
    first over the radix tree (sorted child keys — deterministic),
    full-block nodes only, each node contributing the rolling sha1 of
    its token path.  Spilled nodes (block migrated to host RAM) still
    advertise — their KV reloads on hit, which is the point of the
    spill tier.  Bounded at ``max_nodes`` entries so the stats publish
    stays small no matter how big the tree grows; the top of the tree
    is exactly where shared system prompts / few-shot templates live,
    so truncation costs the least-shared tails first."""
    out: List[str] = []
    queue: List[Tuple[Any, Any]] = [(cache.root, hashlib.sha1())]
    bs = cache.block_size
    while queue and len(out) < max_nodes:
        node, h = queue.pop(0)
        for key in sorted(node.children):
            child = node.children[key]
            if len(child.tokens) != bs:
                continue  # partial tails are CoW territory, not affinity
            h2 = h.copy()
            _fold_block(h2, child.tokens)
            out.append(h2.hexdigest()[:12])
            if len(out) >= max_nodes:
                break
            queue.append((child, h2))
    return out


def fold_digest(fps: List[str]) -> str:
    """One replica's prefix-tree digest: the rolling sha1 fold of its
    advertised fingerprints in publish order.  Rides the stats payload
    and ``doctor --serve`` so 'do these replicas hold different trees'
    is a two-string comparison."""
    h = hashlib.sha1()
    for fp in fps:
        h.update((fp + "|").encode())
    return h.hexdigest()[:16]


# ------------------------------------------------------------ registry
class ReplicaRouter:
    """Router-side replica registry + prefix-affinity placement.

    Lives on the rendezvous/router process (one instance per server,
    attached by serve/router.py).  State per replica: the registration
    record, the latest advertised fingerprint list (kept as a sorted
    list — membership probes bisect it, iteration stays ordered), the
    queue-depth/shed load signals from the same stats publish, and the
    heartbeat stamp that decides dark.  All methods take ``now``
    explicitly — this class never reads a clock (hvdlint
    serve-determinism)."""

    def __init__(self, block_size: int = 16, affinity: bool = True,
                 dead_after_s: float = 3.0):
        self.block_size = int(block_size)
        self.affinity = bool(affinity)
        self.dead_after_s = float(dead_after_s)
        self.replicas: Dict[int, Dict[str, Any]] = {}
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.redispatches = 0
        # The last route() call's full placement verdict — candidate
        # scores plus the affinity-vs-least-loaded decision — for the
        # request-trace record (docs/serving.md#request-lifecycle).
        # Pure derived state: replaying the same calls rebuilds it.
        self.last_verdict: Optional[Dict[str, Any]] = None

    # ---------------------------------------------------------- intake
    def register(self, replica_id: int,
                 info: Optional[Dict[str, Any]] = None,
                 now: float = 0.0) -> None:
        rid = int(replica_id)
        rec = self.replicas.setdefault(rid, {
            "info": {}, "fps": [], "digest": fold_digest([]),
            "queue_depth": 0, "shed": False, "last_seen": now,
            "routed": 0, "hits": 0, "stats": {},
        })
        if info:
            rec["info"] = dict(info)
            if info.get("block_size"):
                # Fingerprint with the fleet's real block size — the
                # router's default only holds until a replica registers.
                self.block_size = int(info["block_size"])
        rec["last_seen"] = max(rec["last_seen"], now)

    def update(self, replica_id: int, stats: Dict[str, Any],
               now: float = 0.0) -> None:
        """Fold one stats publish into the registry: fingerprints,
        digest, and load signals.  Called by the router when it reads a
        replica's stats key (in-process, no extra transport)."""
        rid = int(replica_id)
        if rid not in self.replicas:
            self.register(rid, now=now)
        rec = self.replicas[rid]
        fps = stats.get("prefix_fps")
        if fps is not None:
            rec["fps"] = sorted(str(f) for f in fps)
            rec["digest"] = stats.get("replica_digest") or \
                fold_digest(list(fps))
        rec["queue_depth"] = int(stats.get("queue_depth",
                                           stats.get("waiting", 0)) or 0)
        rec["shed"] = bool(stats.get("shed", False))
        rec["stats"] = stats
        rec["last_seen"] = max(rec["last_seen"], now)

    # ----------------------------------------------------------- state
    def is_dark(self, replica_id: int, now: float) -> bool:
        rec = self.replicas.get(int(replica_id))
        if rec is None:
            return True
        return (now - rec["last_seen"]) > self.dead_after_s

    def live(self, now: float) -> List[int]:
        return [rid for rid in sorted(self.replicas)
                if not self.is_dark(rid, now)]

    # ----------------------------------------------------------- route
    def _least_loaded(self, rids: List[int]) -> int:
        """Deterministic fallback: lowest (shedding, queue_depth, rid)
        — a shedding replica loses to any accepting one."""
        best = rids[0]
        brec = self.replicas[best]
        for rid in rids[1:]:
            rec = self.replicas[rid]
            if (rec["shed"], rec["queue_depth"], rid) < \
                    (brec["shed"], brec["queue_depth"], best):
                best, brec = rid, rec
        return best

    def route(self, tokens: List[int], now: float,
              exclude: Optional[List[int]] = None
              ) -> Optional[Tuple[int, int]]:
        """Place one request: ``(replica_id, hit_blocks)`` —
        ``hit_blocks`` is the affinity depth in full blocks (0 = pure
        least-loaded placement).  ``exclude`` removes replicas (the
        dead fleet a re-dispatch is fleeing).  None when no live
        replica exists."""
        dropped = sorted(set(int(r) for r in (exclude or [])))
        rids = [r for r in self.live(now) if r not in dropped]
        if not rids:
            self.last_verdict = {"kind": "no_live_replica",
                                 "winner": None, "hit_blocks": 0,
                                 "excluded": dropped, "candidates": []}
            return None
        best_rid, best_depth = None, 0
        depths = {rid: 0 for rid in rids}
        fps: List[str] = []
        if self.affinity:
            fps = prompt_fingerprints(tokens, self.block_size)
            for rid in rids:
                adv = self.replicas[rid]["fps"]
                if not adv:
                    continue
                depth = 0
                for i, fp in enumerate(fps):
                    if _bisect_contains(adv, fp):
                        depth = i + 1
                    else:
                        break
                depths[rid] = depth
                if depth > best_depth:
                    best_rid, best_depth = rid, depth
                elif depth == best_depth and best_rid is not None \
                        and depth > 0:
                    # tie: lighter queue wins, then lower id
                    cand, cur = self.replicas[rid], self.replicas[best_rid]
                    if (cand["queue_depth"], rid) < \
                            (cur["queue_depth"], best_rid):
                        best_rid = rid
        if best_rid is None or best_depth == 0:
            best_rid = self._least_loaded(rids)
            best_depth = 0
            self.affinity_misses += 1
            kind = "least_loaded"
        else:
            self.affinity_hits += 1
            self.replicas[best_rid]["hits"] += 1
            kind = "affinity"
        self.replicas[best_rid]["routed"] += 1
        self.last_verdict = {
            "kind": kind, "winner": best_rid, "hit_blocks": best_depth,
            "prompt_blocks": len(fps), "excluded": dropped,
            "candidates": [
                {"replica": rid, "depth": depths[rid],
                 "queue_depth": self.replicas[rid]["queue_depth"],
                 "shed": self.replicas[rid]["shed"]}
                for rid in rids],
        }
        return best_rid, best_depth

    def note_redispatch(self) -> None:
        self.redispatches += 1

    def note_load(self, replica_id: int, pending: int) -> None:
        """Overlay a FRESHER load signal over the advertised queue
        depth: the stats heartbeat is <= 1 Hz, but the router knows
        exactly how many requests it has placed on a replica that are
        still in flight (RouterState ``next_seq - completed``).  Taking
        the max keeps the least-loaded fallback honest in the window
        between two heartbeats — without it, a burst lands entirely on
        the lowest replica id before any depth is re-advertised.  The
        next ``update`` resets the depth to the replica's own view."""
        rec = self.replicas.get(int(replica_id))
        if rec is not None:
            rec["queue_depth"] = max(rec["queue_depth"], int(pending))

    # ------------------------------------------------------------ view
    def counters(self, now: Optional[float] = None) -> Dict[str, Any]:
        routed = self.affinity_hits + self.affinity_misses
        out: Dict[str, Any] = {
            "replicas": len(self.replicas),
            "affinity": self.affinity,
            "affinity_hits": self.affinity_hits,
            "affinity_misses": self.affinity_misses,
            "affinity_hit_rate": (round(self.affinity_hits / routed, 4)
                                  if routed else None),
            "redispatches": self.redispatches,
        }
        if now is not None:
            out["live"] = self.live(now)
        per = {}
        for rid in sorted(self.replicas):
            rec = self.replicas[rid]
            per[str(rid)] = {
                "routed": rec["routed"],
                "affinity_hits": rec["hits"],
                "queue_depth": rec["queue_depth"],
                "shed": rec["shed"],
                "digest": rec["digest"],
                "fps": len(rec["fps"]),
                "dark": (self.is_dark(rid, now)
                         if now is not None else None),
            }
        out["per_replica"] = per
        return out


def _bisect_contains(sorted_list: List[str], item: str) -> bool:
    import bisect
    i = bisect.bisect_left(sorted_list, item)
    return i < len(sorted_list) and sorted_list[i] == item
