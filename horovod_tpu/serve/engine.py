"""Continuous-batching inference engine over the trained models.

The serving plane's core (docs/serving.md): one preallocated,
mesh-sharded paged KV cache (models/llama.py ``init_cache``), a
static-shape slot table, and ONE jit'd mixed prefill/decode step per
tick.  Horovod's product was "wrap your optimizer, training scales"
(arxiv 1802.05799); the serving analog here is "hand the engine your
trained checkpoint, it serves" — no model rewrite, the same mesh,
launcher and observability stack as training.

Scheduling (in-flight/continuous batching, the Orca/vLLM discipline):

  * **admit-on-slot-free**: the waiting queue is FCFS; a request is
    admitted the tick a slot AND its worst-case cache blocks are free,
    never at epoch/batch boundaries;
  * **max_batch_tokens admission**: each tick processes at most that
    many tokens across the table — decode slots cost 1 each (served
    first: latency-critical), prefill slots consume chunks of
    ``prefill_chunk``, new admissions eat leftover budget;
  * **evict-on-EOS/max-len**: a finished request frees its slot and
    blocks the same tick, so the next waiting request replaces it
    mid-flight.

The tick is pipelined one deep (the ``data/loader.py prefetch`` deque
pattern on the host<->device legs): ``step()`` first harvests the
PREVIOUS tick's device results, then plans/assembles/dispatches the next
tick asynchronously — host scheduling overlaps device compute instead of
serializing after it.

Determinism: greedy (argmax) sampling on device, FCFS admission, LIFO
block reuse — given the same request sequence every rank computes the
same plans and tokens, which is what lets a multi-host fleet run the
engine in lockstep from a rank-0-published plan stream (serve/worker.py)
with no new transport.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .config import ServeConfig


# ------------------------------------------------------------ block pool
class BlockAllocator:
    """Free-list over the paged cache pool.  LIFO reuse: the blocks a
    finished request frees are the first ones the next request gets —
    deterministic across ranks and trivially observable in tests
    (paged-cache block reuse)."""

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """All-or-nothing: a request that cannot get its worst-case
        block count is not admitted (no mid-flight OOM-evict)."""
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, blocks: List[int]) -> None:
        for b in reversed(blocks):
            self._free.append(b)


# --------------------------------------------------------------- request
class Request:
    """One generation request moving waiting -> prefill -> decode ->
    done.  ``ctx_len`` counts tokens written into the cache; ``pos``
    counts prompt tokens consumed."""

    def __init__(self, tokens, max_new_tokens: int,
                 req_id: Optional[str] = None,
                 eos_id: Optional[int] = None):
        self.tokens = [int(t) for t in tokens]
        if not self.tokens:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens} invalid")
        self.max_new_tokens = int(max_new_tokens)
        self.req_id = req_id or f"req-{id(self):x}"
        self.eos_id = eos_id
        self.state = "waiting"
        self.out_tokens: List[int] = []
        self.pos = 0        # prompt tokens consumed
        self.ctx_len = 0    # tokens written into the cache
        self.slot: Optional[int] = None
        self.blocks: List[int] = []
        self.submitted_t = time.perf_counter()
        self.admitted_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.finish_reason: Optional[str] = None

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    def ttft(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t

    def tpot(self) -> Optional[float]:
        if self.done_t is None or self.first_token_t is None or \
                len(self.out_tokens) < 2:
            return None
        return (self.done_t - self.first_token_t) / \
            (len(self.out_tokens) - 1)


# ------------------------------------------------------------- scheduler
class Scheduler:
    """Deterministic slot-table scheduler (pure host state, no jax) —
    unit-testable without a model.  ``plan()`` returns this tick's
    (slot, request, n_tokens) work list and performs admissions;
    ``finish()`` evicts."""

    def __init__(self, cfg: ServeConfig):
        self.cfg = cfg
        self.slots: List[Optional[Request]] = [None] * cfg.max_slots
        self.waiting: "collections.deque[Request]" = collections.deque()
        self.allocator = BlockAllocator(cfg.cache_blocks)
        self.block_tables = -np.ones(
            (cfg.max_slots, cfg.max_blocks_per_seq), np.int32)
        self.completed = 0

    # ------------------------------------------------------------ intake
    def submit(self, req: Request) -> Request:
        if req.prompt_len + req.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"request {req.req_id}: prompt {req.prompt_len} + "
                f"max_new {req.max_new_tokens} exceeds "
                f"HOROVOD_SERVE_MAX_SEQ_LEN={self.cfg.max_seq_len}")
        self.waiting.append(req)
        return req

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def has_work(self) -> bool:
        return self.active > 0 or bool(self.waiting)

    # -------------------------------------------------------------- plan
    def plan(self) -> List[Tuple[int, Request, int]]:
        """One tick's work under the token budget: decode slots first
        (1 token each, latency-critical), prefill continuations next,
        FCFS admissions into the remainder.  Deterministic given state."""
        budget = self.cfg.max_batch_tokens
        chunk = self.cfg.prefill_chunk
        work: List[Tuple[int, Request, int]] = []
        for i, req in enumerate(self.slots):
            if req is not None and req.state == "decode" and budget >= 1:
                work.append((i, req, 1))
                budget -= 1
        for i, req in enumerate(self.slots):
            if req is not None and req.state == "prefill" and budget >= 1:
                n = min(chunk, req.prompt_len - req.pos, budget)
                if n >= 1:
                    work.append((i, req, n))
                    budget -= n
        while self.waiting and budget >= 1:
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            req = self.waiting[0]
            need = -(-(req.prompt_len + req.max_new_tokens)
                     // self.cfg.block_size)
            blocks = self.allocator.alloc(need)
            if blocks is None:
                break  # FCFS head-of-line: no skip-ahead, deterministic
            self.waiting.popleft()
            slot = free_slots[0]
            req.slot, req.blocks = slot, blocks
            req.state = "prefill"
            req.admitted_t = time.perf_counter()
            self.slots[slot] = req
            self.block_tables[slot, :] = -1
            self.block_tables[slot, :need] = blocks
            n = min(chunk, req.prompt_len, budget)
            work.append((slot, req, n))
            budget -= n
        return work

    # ------------------------------------------------------------- evict
    def finish(self, req: Request, reason: str) -> None:
        req.state = "done"
        req.finish_reason = reason
        req.done_t = time.perf_counter()
        if req.slot is not None:
            self.block_tables[req.slot, :] = -1
            self.slots[req.slot] = None
        self.allocator.free(req.blocks)
        req.blocks = []
        req.slot = None
        self.completed += 1


# ------------------------------------------------------------ shardings
def cache_shardings(mesh, num_blocks: int, n_kv_heads: int):
    """NamedSharding for the paged pool [L, blocks, bs, kv_heads, hd]:
    kv heads over a model/tp axis when one exists and divides, blocks
    over the first remaining (data) axis that divides — the cache rides
    the training mesh's existing axes (docs/serving.md)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    head_axis = None
    for a in mesh.axis_names:
        if str(a).split(".")[-1] in ("model", "tp") and \
                n_kv_heads % mesh.shape[a] == 0:
            head_axis = a
            break
    block_axis = None
    for a in mesh.axis_names:
        if a != head_axis and num_blocks % mesh.shape[a] == 0:
            block_axis = a
            break
    return NamedSharding(mesh, P(None, block_axis, None, head_axis, None))


def _make_global(arr: np.ndarray, sharding):
    """Host array -> global jax.Array under ``sharding``.  Works in
    multi-controller runs (every process holds the full host value and
    contributes its addressable shards) — jax.device_put alone cannot
    target non-addressable devices."""
    import jax
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def _global_zeros(shape, dtype, sharding):
    import jax

    def cb(idx):
        slice_shape = tuple(
            len(range(*s.indices(d))) for s, d in zip(idx, shape))
        return np.zeros(slice_shape, dtype)  # ml_dtypes covers bf16
    return jax.make_array_from_callback(tuple(shape), sharding, cb)


def replicate_global(tree, mesh):
    """Replicate a host pytree over the whole (possibly multi-process)
    mesh — the serving twin of parallel/data_parallel.replicate, built
    on make_array_from_callback so it also works multi-controller."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: _make_global(np.asarray(x), sharding), tree)


# ---------------------------------------------------------------- engine
class ServeEngine:
    """The continuous-batching engine: host scheduler + one jit'd mixed
    prefill/decode step over the paged cache.

    ``model`` is a model module exposing ``init_cache`` / ``apply_cached``
    (models/llama.py, models/moe_llama.py); ``model_cfg`` its config
    dataclass; ``params`` the trained pytree (host or global arrays).
    """

    def __init__(self, model, model_cfg, params, cfg: ServeConfig,
                 mesh=None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        cfg.validate(model_max_seq=model_cfg.max_seq)
        self.model = model
        self.model_cfg = model_cfg
        self.cfg = cfg
        if mesh is None:
            from .. import runtime as _rt
            mesh = _rt.get().mesh
        self.mesh = mesh
        self.scheduler = Scheduler(cfg)
        self._repl = NamedSharding(mesh, P())
        self._cache_shd = cache_shardings(mesh, cfg.cache_blocks,
                                          model_cfg.n_kv_heads)
        leaves = jax.tree_util.tree_leaves(params)
        if leaves and isinstance(leaves[0], jax.Array):
            self.params = params
        else:
            self.params = replicate_global(params, mesh)
        cache_struct = jax.eval_shape(
            lambda: model.init_cache(model_cfg, cfg.cache_blocks,
                                     cfg.block_size))
        self.cache = jax.tree_util.tree_map(
            lambda x: _global_zeros(x.shape, x.dtype, self._cache_shd),
            cache_struct)
        self._step_fn = self._build_step()
        # One-deep tick pipeline (the loader.prefetch deque pattern):
        # holds (plan, device next-token array) until the next step()
        # harvests it, so host scheduling overlaps device compute.
        self._inflight: "collections.deque" = collections.deque()
        self.tick = 0
        self._tokens_prefill = 0
        self._tokens_decode = 0
        self._last_fill = 0.0

    # ----------------------------------------------------------- compile
    def _build_step(self):
        import jax
        import jax.numpy as jnp

        model, mcfg = self.model, self.model_cfg

        def step_fn(params, cache, block_tables, lengths, n_new, tokens):
            out = model.apply_cached(params, tokens, mcfg, cache,
                                     block_tables, lengths, n_new)
            logits, cache = out[0], out[1]  # moe also returns aux
            last = jnp.maximum(n_new - 1, 0)
            logits_last = jnp.take_along_axis(
                logits, last[:, None, None], axis=1)[:, 0]
            # Greedy sampling ON DEVICE: the token feeds the next tick
            # without a host round trip in the value chain, and argmax
            # ties break identically on every rank (SPMD determinism).
            next_tokens = jnp.argmax(
                logits_last.astype(jnp.float32), axis=-1).astype(jnp.int32)
            return cache, next_tokens

        return jax.jit(
            step_fn,
            donate_argnums=(1,),
            out_shardings=(
                jax.tree_util.tree_map(lambda _: self._cache_shd,
                                       self.cache),
                self._repl))

    # ------------------------------------------------------------ intake
    def submit(self, tokens, max_new_tokens: int,
               req_id: Optional[str] = None,
               eos_id: Optional[int] = None) -> Request:
        req = Request(tokens, max_new_tokens, req_id=req_id,
                      eos_id=eos_id if eos_id is not None
                      else self.cfg.eos_id)
        return self.scheduler.submit(req)

    def has_work(self) -> bool:
        return self.scheduler.has_work() or bool(self._inflight)

    # -------------------------------------------------------------- tick
    def step(self) -> Dict[str, Any]:
        """Run one engine tick.  Returns the COMPLETED tick's report
        (one tick of pipeline lag): {"tick", "processed", "emitted":
        {req_id: [new tokens]}, "finished": [Request]} — an idle report
        when nothing completed."""
        report = self._harvest()
        self._dispatch()
        self._update_gauges()
        return report

    def flush(self) -> List[Dict[str, Any]]:
        """Drain until idle (no planned work, nothing in flight)."""
        out = []
        while self.has_work():
            out.append(self.step())
        return out

    def _dispatch(self) -> None:
        work = self.scheduler.plan()
        for slot, req, n in work:
            if req.admitted_t is not None and not req.pos and \
                    req.state == "prefill" and req.ctx_len == 0:
                # queue-wait span, emitted once at admission
                self._span("NEGOTIATE", req,
                           req.admitted_t - req.submitted_t,
                           end_t=req.admitted_t)
        if not work:
            return
        cfg = self.cfg
        S, C = cfg.max_slots, cfg.prefill_chunk
        tokens = np.zeros((S, C), np.int32)
        lengths = np.zeros(S, np.int32)
        n_new = np.zeros(S, np.int32)
        for slot, req, n in work:
            if req.state == "prefill":
                tokens[slot, :n] = req.tokens[req.pos:req.pos + n]
            else:
                tokens[slot, 0] = req.out_tokens[-1]
            lengths[slot] = req.ctx_len
            n_new[slot] = n
        # Async dispatch: device_put + jit return immediately; the next
        # step() harvests, so this tick's H2D staging and compute run
        # behind the caller's host work (the double-buffer pattern).
        dev = [_make_global(a, self._repl)
               for a in (np.asarray(self.scheduler.block_tables),
                         lengths, n_new, tokens)]
        self.cache, next_tokens = self._step_fn(
            self.params, self.cache, *dev)
        used = int(n_new.sum())
        self._last_fill = used / cfg.max_batch_tokens
        self._inflight.append((self.tick, work, next_tokens, used))
        self.tick += 1

    def _harvest(self) -> Dict[str, Any]:
        if not self._inflight:
            return {"tick": None, "processed": 0, "emitted": {},
                    "finished": []}
        from ..utils import metrics as M
        tick, work, next_tokens, used = self._inflight.popleft()
        tokens_host = np.asarray(next_tokens)  # D2H fence for this tick
        now = time.perf_counter()
        emitted: Dict[str, List[int]] = {}
        finished: List[Request] = []
        for slot, req, n in work:
            if req.state == "prefill":
                req.pos += n
                req.ctx_len += n
                self._tokens_prefill += n
                M.SERVE_TOKENS.inc(n, phase="prefill")
                if req.pos < req.prompt_len:
                    continue  # still prefilling next tick
                req.state = "decode"
            else:
                req.ctx_len += 1
                self._tokens_decode += 1
                M.SERVE_TOKENS.inc(phase="decode")
            tok = int(tokens_host[slot])
            req.out_tokens.append(tok)
            emitted.setdefault(req.req_id, []).append(tok)
            if req.first_token_t is None:
                req.first_token_t = now
                M.SERVE_TTFT.observe(req.ttft())
                self._span("PREFILL", req, now - req.admitted_t,
                           end_t=now, extra={"prompt": req.prompt_len})
            if (req.eos_id is not None and tok == req.eos_id) or \
                    len(req.out_tokens) >= req.max_new_tokens:
                reason = ("eos" if req.eos_id is not None
                          and tok == req.eos_id else "completed")
                self.scheduler.finish(req, reason)
                finished.append(req)
                tpot = req.tpot()
                if tpot is not None:
                    M.SERVE_TPOT.observe(tpot)
                M.SERVE_REQUESTS.inc(outcome=reason)
                self._span("DECODE", req, req.done_t - req.first_token_t,
                           end_t=req.done_t,
                           extra={"generated": len(req.out_tokens)})
        from .. import postmortem as PM
        PM.record_step(tick)  # engine liveness on the /health plane
        return {"tick": tick, "processed": used, "emitted": emitted,
                "finished": finished}

    def _update_gauges(self) -> None:
        from ..utils import metrics as M
        M.SERVE_QUEUE_DEPTH.set(self.scheduler.queue_depth)
        M.SERVE_BATCH_FILL.set(self._last_fill)

    # ------------------------------------------------------------- spans
    def _span(self, phase: str, req: Request, duration_s: float,
              end_t: float, extra: Optional[dict] = None) -> None:
        """Per-request phase span on the merged timeline's 'serve' lane
        (utils/timeline.record_span); no-op without an active timeline."""
        try:
            from .. import runtime as _rt
            if not _rt.is_initialized():
                return
            tl = getattr(_rt.get(), "timeline", None)
            if tl is None:
                return
            args = {"req": req.req_id}
            if extra:
                args.update(extra)
            lag_us = (time.perf_counter() - end_t) * 1e6
            tl.record_span("serve", phase, max(duration_s, 0.0) * 1e6,
                           args=args, ts_us=tl.now_us() - lag_us
                           - max(duration_s, 0.0) * 1e6)
        except Exception:
            pass  # tracing must never take serving down

    # -------------------------------------------------------------- view
    def stats(self) -> Dict[str, Any]:
        s = self.scheduler
        return {
            "tick": self.tick,
            "active": s.active,
            "waiting": s.queue_depth,
            "completed": s.completed,
            "free_blocks": s.allocator.free_count,
            "batch_fill": round(self._last_fill, 4),
            "tokens_prefill": self._tokens_prefill,
            "tokens_decode": self._tokens_decode,
        }


# ----------------------------------------------------- servable loading
SERVE_MANIFEST = "serve.json"

_MODEL_MODULES = {"llama": "horovod_tpu.models.llama",
                  "moe_llama": "horovod_tpu.models.moe_llama"}


def save_servable(directory: str, model_name: str, config, params,
                  step: int = 0) -> None:
    """Write a servable directory: ``serve.json`` (model family +
    config) beside a sharded checkpoint (checkpoint.py) — what
    ``hvdrun --serve DIR`` consumes."""
    import dataclasses
    from .. import checkpoint as ckpt
    os.makedirs(directory, exist_ok=True)
    cfg_dict = {k: v for k, v in dataclasses.asdict(config).items()
                if not hasattr(v, "dtype")}
    cfg_dict.pop("dtype", None)
    with open(os.path.join(directory, SERVE_MANIFEST), "w") as f:
        json.dump({"model": model_name, "config": cfg_dict}, f)
    ckpt.save_checkpoint(directory, step, params=params)


def load_servable(directory: str, mesh) -> Tuple[Any, Any, Any]:
    """Read a servable directory -> (model module, model config, global
    replicated params).  ``serve.json``: {"model": "llama"|"moe_llama",
    "config": <name in CONFIGS or kwarg dict>, "seed": int?}.  Params
    come from the latest checkpoint under the directory (restored
    through checkpoint.py into replicated shardings); with no
    checkpoint present, a seeded random init serves — the CPU-virtual
    smoke path, loudly labeled."""
    import importlib
    import sys

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    with open(os.path.join(directory, SERVE_MANIFEST)) as f:
        manifest = json.load(f)
    name = manifest.get("model", "llama")
    if name not in _MODEL_MODULES:
        raise ValueError(f"serve.json model {name!r} unknown; expected "
                         f"one of {sorted(_MODEL_MODULES)}")
    model = importlib.import_module(_MODEL_MODULES[name])
    spec = manifest.get("config", "tiny")
    if isinstance(spec, str):
        model_cfg = model.CONFIGS[spec]
    else:
        model_cfg = type(model.CONFIGS["tiny"])(**spec)

    seed = int(manifest.get("seed", 0))
    host = model.init(jax.random.PRNGKey(seed), model_cfg)
    repl = NamedSharding(mesh, P())
    from .. import checkpoint as ckpt
    try:
        mgr = ckpt.CheckpointManager(directory, max_to_keep=10_000)
        try:
            latest = mgr.latest_step()
            if latest is not None:
                template = jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                   sharding=repl), host)
                params = mgr.restore(latest, params=template)["params"]
                return model, model_cfg, params
        finally:
            mgr.close()
    except FileNotFoundError:
        pass
    print(f"[hvd.serve] no checkpoint under {directory}; serving "
          f"seed={seed} random-init params (smoke mode)",
          file=sys.stderr, flush=True)
    return model, model_cfg, replicate_global(host, mesh)
