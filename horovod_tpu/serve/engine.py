"""Continuous-batching inference engine over the trained models.

The serving plane's core (docs/serving.md): one preallocated,
mesh-sharded paged KV cache (models/llama.py ``init_cache``), a
static-shape slot table, and ONE jit'd mixed prefill/decode step per
tick.  Horovod's product was "wrap your optimizer, training scales"
(arxiv 1802.05799); the serving analog here is "hand the engine your
trained checkpoint, it serves" — no model rewrite, the same mesh,
launcher and observability stack as training.

Scheduling (in-flight/continuous batching, the Orca/vLLM discipline):

  * **admit-on-slot-free**: the waiting queue is FCFS; a request is
    admitted the tick a slot AND its worst-case cache blocks are free,
    never at epoch/batch boundaries;
  * **max_batch_tokens admission**: each tick processes at most that
    many tokens across the table — decode slots cost 1 each (served
    first: latency-critical), prefill slots consume chunks of
    ``prefill_chunk``, new admissions eat leftover budget;
  * **evict-on-EOS/max-len**: a finished request frees its slot and
    blocks the same tick, so the next waiting request replaces it
    mid-flight.

The tick is pipelined one deep (the ``data/loader.py prefetch`` deque
pattern on the host<->device legs): ``step()`` first harvests the
PREVIOUS tick's device results, then plans/assembles/dispatches the next
tick asynchronously — host scheduling overlaps device compute instead of
serializing after it.

Determinism: greedy (argmax) sampling on device, FCFS admission, LIFO
block reuse — given the same request sequence every rank computes the
same plans and tokens, which is what lets a multi-host fleet run the
engine in lockstep from a rank-0-published plan stream (serve/worker.py)
with no new transport.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .config import ServeConfig


# ------------------------------------------------------------ block pool
class BlockAllocator:
    """Refcounted free-list over the paged cache pool.  LIFO reuse: the
    blocks a finished request frees are the first ones the next request
    gets — deterministic across ranks and trivially observable in tests
    (paged-cache block reuse).

    With prefix sharing (PrefixCache) one block can back several
    sequences plus the cache itself: ``alloc`` hands blocks out at
    refcount 1, ``incref`` adds an owner, ``free`` releases one owner —
    a block returns to the free list only when its LAST owner lets go."""

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._refs: Dict[int, int] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    def ref(self, block: int) -> int:
        return self._refs.get(block, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """All-or-nothing: a request that cannot get its worst-case
        block count is not admitted (no mid-flight OOM-evict)."""
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._refs[b] = 1
        return blocks

    def incref(self, blocks: List[int]) -> None:
        for b in blocks:
            self._refs[b] += 1

    def free(self, blocks: List[int]) -> None:
        for b in reversed(blocks):
            left = self._refs[b] = self._refs[b] - 1
            if left == 0:
                del self._refs[b]
                self._free.append(b)

    def occupancy(self) -> Dict[str, int]:
        """Pool occupancy for the memory plane (perf/memstats.py;
        docs/memory.md#kv-pool): used/free split plus the blocks more
        than one owner maps (prefix-cache / CoW sharing) — the bytes the
        used count would double-book if summed per sequence."""
        return {
            "num_blocks": self.num_blocks,
            "used_blocks": self.num_blocks - len(self._free),
            "free_blocks": len(self._free),
            "shared_blocks": sum(1 for c in self._refs.values() if c > 1),
        }


# ----------------------------------------------------- radix prefix cache
class _PrefixNode:
    """One radix-tree node = one pool block's worth of cached prompt KV:
    ``tokens`` are the token ids whose KV the block holds (a full block,
    or a partial tail shorter than block_size), children keyed by the
    NEXT block's token tuple.  ``block`` is None while the node's KV
    lives in the host spill tier (HostSpillPool) — the node stays in
    the tree so the prefix stays matchable and reloads on hit."""

    __slots__ = ("tokens", "block", "children", "parent", "stamp")

    def __init__(self, tokens: Tuple[int, ...], block: Optional[int],
                 parent):
        self.tokens = tokens
        self.block = block
        self.children: Dict[Tuple[int, ...], "_PrefixNode"] = {}
        self.parent = parent
        self.stamp = 0


# ------------------------------------------------------- host spill tier
class HostSpillPool:
    """Host-RAM tier behind the device paged pool
    (docs/serving.md#replicated-tier): cold radix-tree blocks —
    allocator refcount exactly 1, i.e. held by nobody but the tree —
    migrate here instead of being dropped at eviction, and reload into
    a fresh device block on the next prefix hit.  Capacity-bounded in
    blocks; when full, the least-recently-touched held block (the
    prefix cache's own deterministic ``stamp`` clock) is dropped for
    good.  Pure host state driven by the request stream (no clock, no
    RNG — the hvdlint serve-determinism scope covers this class), so a
    lockstep fleet spills and reloads identically on every rank.

    ``read_block(block) -> payload`` and ``write_block(block, payload)``
    are engine-provided device accessors (numpy copies of one pool
    block across layers); the pool itself never touches jax."""

    def __init__(self, capacity_blocks: int, read_block, write_block):
        self.capacity = int(capacity_blocks)
        self._read = read_block
        self._write = write_block
        self._held: Dict[int, Any] = {}     # id(node) -> payload
        self._nodes: Dict[int, Any] = {}    # id(node) -> node (for LRU)
        self.spilled_total = 0
        self.reloaded_total = 0
        self.dropped_total = 0
        self.bytes_held = 0

    @property
    def blocks_held(self) -> int:
        return len(self._held)

    def _payload_bytes(self, payload) -> int:
        return sum(int(a.nbytes) for a in payload.values())

    def _drop_coldest(self) -> None:
        victim_key, victim = None, None
        for key in sorted(self._nodes):
            node = self._nodes[key]
            if victim is None or node.stamp < victim.stamp:
                victim_key, victim = key, node
        if victim_key is None:
            return
        payload = self._held.pop(victim_key)
        del self._nodes[victim_key]
        self.bytes_held -= self._payload_bytes(payload)
        self.dropped_total += 1
        # the node's KV is gone for good: unlink it from the tree so
        # match() never offers a prefix nobody can reload
        if victim.parent is not None and not victim.children:
            victim.parent.children.pop(victim.tokens, None)

    def spill(self, node: _PrefixNode) -> bool:
        """Migrate one tree-held block to host RAM.  Returns False when
        capacity is 0 (spill off) — the caller evicts normally."""
        if self.capacity <= 0:
            return False
        while len(self._held) >= self.capacity:
            self._drop_coldest()
        payload = self._read(node.block)
        self._held[id(node)] = payload
        self._nodes[id(node)] = node
        self.bytes_held += self._payload_bytes(payload)
        self.spilled_total += 1
        return True

    def reload(self, node: _PrefixNode, block: int) -> None:
        """Write a held node's KV back into device ``block`` (the
        caller allocated it; the tree takes the ref)."""
        payload = self._held.pop(id(node))
        del self._nodes[id(node)]
        self.bytes_held -= self._payload_bytes(payload)
        self._write(block, payload)
        self.reloaded_total += 1

    def holds(self, node: _PrefixNode) -> bool:
        return id(node) in self._held

    def counters(self) -> Dict[str, Any]:
        return {
            "capacity_blocks": self.capacity,
            "held_blocks": len(self._held),
            "held_bytes": self.bytes_held,
            "spilled_total": self.spilled_total,
            "reloaded_total": self.reloaded_total,
            "dropped_total": self.dropped_total,
        }


class PrefixCache:
    """Radix tree over token-block keys (the automatic-prefix-caching
    discipline on this repo's paged pool): sequences with a common
    prefix map the SAME KV blocks, so repeated prefills of shared system
    prompts / few-shot templates become cache hits.

      * full blocks are shared in place (allocator refcount, zero copy);
      * divergence INSIDE a cached block — including a partial tail —
        is shared copy-on-write: the matcher gets a device-side clone of
        the block (models/llama.py ``copy_blocks``) holding the common
        positions and overwrites its own suffix;
      * when the pool runs dry, admission evicts LRU leaves nobody
        references but the cache (refcount exactly 1).

    Pure host state driven only by the request stream, never by timing —
    every rank replaying the same plan stream computes the identical
    tree, which is what keeps the fleet lockstep (docs/serving.md)."""

    def __init__(self, block_size: int, allocator: BlockAllocator,
                 spill: Optional[HostSpillPool] = None):
        self.block_size = int(block_size)
        self.allocator = allocator
        self.spill = spill
        self.root = _PrefixNode((), -1, None)
        self._clock = 0          # deterministic LRU clock (touch order)
        self.hits = 0            # admissions with a nonzero prefix hit
        self.hit_tokens = 0      # prompt tokens served from cache
        self.blocks_shared = 0   # full blocks mapped instead of computed
        self.cow_copies = 0
        self.evictions = 0

    def _touch(self, node: _PrefixNode) -> None:
        self._clock += 1
        node.stamp = self._clock

    def _reload(self, node: _PrefixNode) -> bool:
        """Bring a spilled node's KV back into a fresh device block (the
        tree takes the ref, exactly like insert()).  The alloc may
        itself evict — eviction never selects spilled nodes, so this
        cannot recurse into the node being reloaded."""
        if self.spill is None or not self.spill.holds(node):
            return False
        blocks = self.allocator.alloc(1)
        if blocks is None:
            if self.evict(1) < 1:
                return False
            blocks = self.allocator.alloc(1)
            if blocks is None:
                return False
        self.spill.reload(node, blocks[0])
        node.block = blocks[0]
        from ..utils import metrics as M
        M.SERVE_SPILL_RELOADS.inc()
        return True

    def match(self, prompt: List[int]
              ) -> Tuple[List[int], Optional[Tuple[int, int]], int]:
        """Longest cached prefix of ``prompt``, capped at
        ``len(prompt) - 1``: at least one prompt token is always
        recomputed so the admitting tick has logits to sample the first
        output from (a zero-token prefill chunk would wedge).  Returns
        ``(full_blocks, cow, hit_tokens)`` — ``full_blocks`` are shared
        as-is (caller increfs); ``cow`` is ``(src_block, n_valid)`` when
        the tail diverges inside a cached block, and the caller owns a
        device-side copy."""
        bs = self.block_size
        limit = len(prompt) - 1
        node, full, pos = self.root, [], 0
        while limit - pos >= bs:
            child = node.children.get(tuple(prompt[pos:pos + bs]))
            if child is None:
                break
            if child.block is None and not self._reload(child):
                break  # spilled and unreloadable: the match ends here
            self._touch(child)
            full.append(child.block)
            node, pos = child, pos + bs
        # Divergence within a block: best partial overlap among this
        # node's children (sorted scan = deterministic tie-break),
        # shared by copy-on-write.
        want = tuple(prompt[pos:limit])
        best, best_n = None, 0
        for key in sorted(node.children):
            child = node.children[key]
            n = 0
            for a, b in zip(want, child.tokens):
                if a != b:
                    break
                n += 1
            if n > best_n:
                best, best_n = child, n
        cow = None
        if best is not None and best_n >= 1:
            if best.block is None and not self._reload(best):
                return full, None, pos
            self._touch(best)
            cow = (best.block, best_n)
        return full, cow, pos + best_n

    def insert(self, prompt: List[int], blocks: List[int]) -> None:
        """Register a finished prefill: ``blocks`` is the slot's table
        row, whose i-th entry holds the prompt's i-th block of KV.
        Existing nodes win (dedup: a prefix computed twice concurrently
        stays owned by its second request and is freed normally); new
        nodes take one cache ref on their block so eviction — not a
        request finishing — decides their lifetime."""
        bs = self.block_size
        node, pos = self.root, 0
        for i in range(len(prompt) // bs):
            key = tuple(prompt[pos:pos + bs])
            child = node.children.get(key)
            if child is None:
                child = _PrefixNode(key, blocks[i], node)
                node.children[key] = child
                self.allocator.incref([child.block])
                self._touch(child)
            node, pos = child, pos + bs
        tail = tuple(prompt[pos:])
        if tail and tail not in node.children:
            child = _PrefixNode(tail, blocks[len(prompt) // bs], node)
            node.children[tail] = child
            self.allocator.incref([child.block])
            self._touch(child)

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` by dropping least-recently-touched
        leaves only the cache references (allocator refcount exactly 1);
        returns how many were freed.  Interior nodes are never dropped —
        that would orphan reachable children.  With a spill tier
        attached, a victim's KV migrates to host RAM first (the node
        stays in the tree, block None, reloadable on the next hit);
        spilled nodes themselves are never victims — they hold no
        device block."""
        freed = 0
        while freed < n_blocks:
            victim = None
            for node in self._walk(self.root):
                if node is self.root or node.children:
                    continue
                if node.block is None:
                    continue  # already spilled: nothing on device
                if self.allocator.ref(node.block) != 1:
                    continue
                if victim is None or node.stamp < victim.stamp:
                    victim = node
            if victim is None:
                break
            if self.spill is not None and self.spill.spill(victim):
                self.allocator.free([victim.block])
                victim.block = None
                from ..utils import metrics as M
                M.SERVE_SPILLS.inc()
            else:
                del victim.parent.children[victim.tokens]
                self.allocator.free([victim.block])
            self.evictions += 1
            freed += 1
        return freed

    def _walk(self, node: _PrefixNode):
        yield node
        for key in sorted(node.children):
            yield from self._walk(node.children[key])

    @property
    def size(self) -> int:
        """Cached blocks currently held by the tree."""
        return sum(1 for _ in self._walk(self.root)) - 1


# --------------------------------------------------------------- request
class Request:
    """One generation request moving waiting -> prefill -> decode ->
    done.  ``ctx_len`` counts tokens written into the cache; ``pos``
    counts prompt tokens consumed."""

    def __init__(self, tokens, max_new_tokens: int,
                 req_id: Optional[str] = None,
                 eos_id: Optional[int] = None):
        self.tokens = [int(t) for t in tokens]
        if not self.tokens:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens} invalid")
        self.max_new_tokens = int(max_new_tokens)
        self.req_id = req_id or f"req-{id(self):x}"
        self.eos_id = eos_id
        self.state = "waiting"
        self.out_tokens: List[int] = []
        self.pos = 0        # prompt tokens consumed
        self.ctx_len = 0    # tokens written into the cache
        self.slot: Optional[int] = None
        self.blocks: List[int] = []
        self.draft: List[int] = []      # this tick's speculative tokens
        self._bigram: Dict[Tuple[int, int], int] = {}
        self._indexed = 0   # context positions already in the index
        self.submitted_t = time.perf_counter()
        self.admitted_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.finish_reason: Optional[str] = None
        # Causal trace context (serve/trace.py): minted by the router at
        # admission, attached at submit, rides the handoff record to the
        # decode side so both fleets' spans link on the same rid.
        self.trace: Optional[Dict[str, Any]] = None
        self.handoff_s: float = 0.0  # decode-side measured export->import
        # Prefill-side component durations that rode the handoff record
        # (queue_s/prefill_s): the decode fleet cannot recompute them —
        # perf_counter stamps are process-local.
        self.upstream: Optional[Dict[str, float]] = None

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    def ttft(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t

    def tpot(self) -> Optional[float]:
        if self.done_t is None or self.first_token_t is None or \
                len(self.out_tokens) < 2:
            return None
        return (self.done_t - self.first_token_t) / \
            (len(self.out_tokens) - 1)

    # ----------------------------------------------------- spec drafting
    def _ctx_tok(self, i: int) -> int:
        n = len(self.tokens)
        return self.tokens[i] if i < n else self.out_tokens[i - n]

    def draft_lookup(self, k: int) -> List[int]:
        """N-gram / prompt-lookup drafting (the draft-model-free leg of
        speculative decoding): find the most recent PRIOR occurrence of
        the context's final bigram and propose up to ``k`` tokens that
        followed it.  The bigram index grows incrementally (O(1) per
        generated token) and deliberately excludes the final bigram
        itself, so a repeating tail still finds its earlier occurrence.
        A pure function of prompt + emitted tokens — deterministic on
        every rank (the lockstep contract)."""
        L = len(self.tokens) + len(self.out_tokens)
        if k < 1 or L < 3:
            return []
        for i in range(max(self._indexed, 1), L - 1):
            self._bigram[(self._ctx_tok(i - 1), self._ctx_tok(i))] = i + 1
        self._indexed = max(self._indexed, L - 1)
        p = self._bigram.get((self._ctx_tok(L - 2), self._ctx_tok(L - 1)))
        if p is None:
            return []
        return [self._ctx_tok(p + j) for j in range(min(k, L - p))]


# ------------------------------------------------------------- scheduler
class Scheduler:
    """Deterministic slot-table scheduler (pure host state, no jax) —
    unit-testable without a model.  ``plan()`` returns this tick's
    (slot, request, n_tokens) work list and performs admissions;
    ``finish()`` evicts.

    ``role`` is the prefill/decode disaggregation split
    (docs/serving.md#replicated-tier): a ``mixed`` scheduler (the
    default, byte-for-byte the pre-split engine) runs both phases; a
    ``prefill`` scheduler admits from the waiting queue but its engine
    hands finished prefills off instead of decoding them; a ``decode``
    scheduler admits ONLY imported handoffs (``queue_import``) — its
    waiting queue is never drained, so a stray submit cannot double-run
    a prompt both sides of the split."""

    ROLES = ("mixed", "prefill", "decode")

    def __init__(self, cfg: ServeConfig, role: str = "mixed"):
        if role not in self.ROLES:
            raise ValueError(f"scheduler role {role!r} invalid; expected "
                             f"one of {self.ROLES}")
        self.cfg = cfg
        self.role = role
        self.slots: List[Optional[Request]] = [None] * cfg.max_slots
        self.waiting: "collections.deque[Request]" = collections.deque()
        self.allocator = BlockAllocator(cfg.cache_blocks)
        self.prefix = (PrefixCache(cfg.block_size, self.allocator)
                       if cfg.prefix_cache else None)
        self.block_tables = -np.ones(
            (cfg.max_slots, cfg.max_blocks_per_seq), np.int32)
        self.completed = 0
        self.admissions = 0
        self.imports = 0
        # CoW copies the NEXT dispatch must run before its writes:
        # (src_block, dst_block) pairs, at most one per admission.
        self.pending_copies: List[Tuple[int, int]] = []
        # Disaggregation intake: handoffs waiting for a slot, the
        # device-block writes the next dispatch must apply before its
        # step reads the cache, and the emissions (the prefill rank's
        # first token) the next report must carry.
        self.import_queue: "collections.deque" = collections.deque()
        self.pending_writes: List[Tuple[int, Any]] = []
        self.import_emits: List[Tuple[Request, List[int]]] = []

    # ------------------------------------------------------------ intake
    def submit(self, req: Request) -> Request:
        if req.prompt_len + req.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"request {req.req_id}: prompt {req.prompt_len} + "
                f"max_new {req.max_new_tokens} exceeds "
                f"HOROVOD_SERVE_MAX_SEQ_LEN={self.cfg.max_seq_len}")
        self.waiting.append(req)
        return req

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def has_work(self) -> bool:
        return self.active > 0 or bool(self.waiting) or \
            bool(self.import_queue)

    # -------------------------------------------------------------- plan
    def plan(self) -> List[Tuple[int, Request, int]]:
        """One tick's work under the token budget: decode slots first
        (1 token + up to spec_k verified drafts each, latency-critical),
        prefill continuations next, FCFS admissions into the remainder.
        Deterministic given state."""
        budget = self.cfg.max_batch_tokens
        chunk = self.cfg.prefill_chunk
        work: List[Tuple[int, Request, int]] = []
        self._drain_imports()
        for i, req in enumerate(self.slots):
            if req is not None and req.state == "decode" and budget >= 1:
                req.draft = []
                if self.cfg.spec_decode:
                    # Draft length caps: the tick budget (each draft
                    # token costs 1), the verify row width (bonus token
                    # + K drafts per row), and the remaining generation
                    # budget (a draft past max_new could be verified at
                    # RoPE positions the reservation never covered).
                    cap = min(self.cfg.spec_k, budget - 1,
                              self.cfg.prefill_chunk - 1,
                              req.max_new_tokens - len(req.out_tokens) - 1)
                    if cap >= 1:
                        req.draft = req.draft_lookup(cap)
                n = 1 + len(req.draft)
                work.append((i, req, n))
                budget -= n
        for i, req in enumerate(self.slots):
            if req is not None and req.state == "prefill" and budget >= 1:
                n = min(chunk, req.prompt_len - req.pos, budget)
                if n >= 1:
                    work.append((i, req, n))
                    budget -= n
        while self.waiting and budget >= 1 and self.role != "decode":
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            req = self.waiting[0]
            row = self._admit_blocks(req)
            if row is None:
                break  # FCFS head-of-line: no skip-ahead, deterministic
            self.waiting.popleft()
            self.admissions += 1
            slot = free_slots[0]
            req.slot, req.blocks = slot, row
            req.state = "prefill"
            req.admitted_t = time.perf_counter()
            self.slots[slot] = req
            self.block_tables[slot, :] = -1
            self.block_tables[slot, :len(row)] = row
            # prefix-hit tokens are already resident: prefill resumes at
            # req.pos (match() keeps >= 1 token to compute, so n >= 1)
            n = min(chunk, req.prompt_len - req.pos, budget)
            work.append((slot, req, n))
            budget -= n
        return work

    def _admit_blocks(self, req: Request) -> Optional[List[int]]:
        """One admission's block-table row.  With the prefix cache on,
        the worst-case reservation counts only NEW blocks — prefix-hit
        blocks are already resident (the sharing dividend: without this
        the conservative math would refuse admissible requests).  Shared
        blocks are increfed BEFORE the alloc/evict so eviction can never
        recycle what this admission just matched; a failed alloc undoes
        the increfs and leaves the request queued (all-or-nothing)."""
        need = -(-(req.prompt_len + req.max_new_tokens)
                 // self.cfg.block_size)
        if self.prefix is None:
            return self.allocator.alloc(need)
        shared, cow, hit = self.prefix.match(req.tokens)
        self.allocator.incref(shared)
        need_new = need - len(shared)
        blocks = self.allocator.alloc(need_new)
        if blocks is None:
            short = need_new - self.allocator.free_count
            if self.prefix.evict(short) >= short:
                blocks = self.allocator.alloc(need_new)
        if blocks is None:
            self.allocator.free(shared)  # undo: tree refs keep them alive
            return None
        if cow is not None:
            # Divergence inside a cached block: clone it on device into
            # this request's first new block, then overwrite the suffix.
            # The source needs no extra ref: the copy runs at the START
            # of the next dispatch, and any later reuse of the source
            # block writes in the SAME step after the copy's gather
            # (functional semantics) or in a later, device-ordered one.
            src, cow_tokens = cow
            self.pending_copies.append((src, blocks[0]))
            hit = len(shared) * self.cfg.block_size + cow_tokens
            self.prefix.cow_copies += 1
        if hit:
            from ..utils import metrics as M
            self.prefix.hits += 1
            self.prefix.hit_tokens += hit
            self.prefix.blocks_shared += len(shared)
            M.SERVE_PREFIX_HITS.inc()
            if shared:
                M.SERVE_PREFIX_BLOCKS_SHARED.inc(len(shared))
        req.pos = req.ctx_len = hit
        return shared + blocks

    def take_copies(self) -> List[Tuple[int, int]]:
        copies, self.pending_copies = self.pending_copies, []
        return copies

    # ----------------------------------------------- disaggregated intake
    def queue_import(self, req: Request, payloads: List[Any],
                     first_token: int) -> None:
        """Decode-side intake of one prefill-rank handoff: the request,
        its prompt blocks' KV payloads (engine-decoded numpy dicts, one
        per full-or-partial prompt block), and the first output token
        the prefill rank already sampled.  Queued FCFS; ``plan()``
        installs it the tick a slot and blocks free up."""
        self.import_queue.append((req, payloads, int(first_token)))

    def _drain_imports(self) -> None:
        """Install queued handoffs straight into decode state: allocate
        the full worst-case row (prompt + max_new blocks), stage the KV
        payload writes for the next dispatch, emit the prefill rank's
        first token.  FCFS head-of-line like admission — an uninstallable
        handoff blocks the ones behind it (deterministic)."""
        while self.import_queue:
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            req, payloads, first = self.import_queue[0]
            need = -(-(req.prompt_len + req.max_new_tokens)
                     // self.cfg.block_size)
            blocks = self.allocator.alloc(need)
            if blocks is None and self.prefix is not None:
                short = need - self.allocator.free_count
                if self.prefix.evict(short) >= short:
                    blocks = self.allocator.alloc(need)
            if blocks is None:
                break
            self.import_queue.popleft()
            slot = free_slots[0]
            req.slot, req.blocks = slot, blocks
            req.state = "decode"
            req.pos = req.prompt_len
            req.ctx_len = req.prompt_len
            req.out_tokens = [first]
            req.admitted_t = time.perf_counter()
            req.first_token_t = req.admitted_t
            self.slots[slot] = req
            self.block_tables[slot, :] = -1
            self.block_tables[slot, :need] = blocks
            self.admissions += 1
            self.imports += 1
            self.import_emits.append((req, [first]))
            if (req.eos_id is not None and first == req.eos_id) or \
                    req.max_new_tokens <= 1:
                reason = ("eos" if req.eos_id is not None
                          and first == req.eos_id else "completed")
                self.finish(req, reason)
                continue  # done on arrival: no KV writes needed
            for b, payload in zip(blocks, payloads):
                self.pending_writes.append((b, payload))
            if self.prefix is not None:
                self.prefix.insert(req.tokens, blocks)

    def take_pending_writes(self) -> List[Tuple[int, Any]]:
        writes, self.pending_writes = self.pending_writes, []
        return writes

    def take_import_emits(self) -> List[Tuple[Request, List[int]]]:
        emits, self.import_emits = self.import_emits, []
        return emits

    def register_prefix(self, req: Request) -> None:
        """Engine callback at prefill completion: the slot's prompt
        blocks now hold fully-computed KV and become shareable."""
        if self.prefix is not None and req.slot is not None:
            self.prefix.insert(req.tokens, req.blocks)

    # ------------------------------------------------------------- evict
    def finish(self, req: Request, reason: str) -> None:
        req.state = "done"
        req.finish_reason = reason
        req.done_t = time.perf_counter()
        if req.slot is not None:
            self.block_tables[req.slot, :] = -1
            self.slots[req.slot] = None
        self.allocator.free(req.blocks)
        req.blocks = []
        req.slot = None
        self.completed += 1


# ------------------------------------------------------------ shardings
def cache_shardings(mesh, num_blocks: int, n_kv_heads: int):
    """NamedSharding for the paged pool [L, blocks, bs, kv_heads, hd]:
    kv heads over a model/tp axis when one exists and divides, blocks
    over the first remaining (data) axis that divides — the cache rides
    the training mesh's existing axes (docs/serving.md)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    head_axis = None
    for a in mesh.axis_names:
        if str(a).split(".")[-1] in ("model", "tp") and \
                n_kv_heads % mesh.shape[a] == 0:
            head_axis = a
            break
    block_axis = None
    for a in mesh.axis_names:
        if a != head_axis and num_blocks % mesh.shape[a] == 0:
            block_axis = a
            break
    return NamedSharding(mesh, P(None, block_axis, None, head_axis, None))


def _make_global(arr: np.ndarray, sharding):
    """Host array -> global jax.Array under ``sharding``.  Works in
    multi-controller runs (every process holds the full host value and
    contributes its addressable shards) — jax.device_put alone cannot
    target non-addressable devices."""
    import jax
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def _global_zeros(shape, dtype, sharding):
    import jax

    def cb(idx):
        slice_shape = tuple(
            len(range(*s.indices(d))) for s, d in zip(idx, shape))
        return np.zeros(slice_shape, dtype)  # ml_dtypes covers bf16
    return jax.make_array_from_callback(tuple(shape), sharding, cb)


def replicate_global(tree, mesh):
    """Replicate a host pytree over the whole (possibly multi-process)
    mesh — the serving twin of parallel/data_parallel.replicate, built
    on make_array_from_callback so it also works multi-controller."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: _make_global(np.asarray(x), sharding), tree)


# --------------------------------------------------- block payload codec
def encode_block_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """One pool block's KV (the ``_read_block`` numpy dict, e.g.
    {"k": [L, bs, kv_heads, hd], "v": ...}) as a JSON-safe record —
    dtype/shape plus hex bytes — for the prefill->decode handoff ride
    over the direct-stream path (serve/stream.py).  Hex doubles the
    bytes but keeps the record line-framed JSON like every other stream
    record; the payload is one block, not a sequence."""
    out: Dict[str, Any] = {}
    for k, a in payload.items():
        a = np.ascontiguousarray(a)
        out[k] = {"dtype": str(a.dtype), "shape": list(a.shape),
                  "hex": a.tobytes().hex()}
    return out


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # low-bit dtypes jax serves in (bf16 etc.)
        return np.dtype(getattr(ml_dtypes, name))


def decode_block_payload(enc: Dict[str, Any]) -> Dict[str, Any]:
    return {k: np.frombuffer(bytes.fromhex(v["hex"]),
                             dtype=_np_dtype(v["dtype"]))
            .reshape(v["shape"]).copy()
            for k, v in enc.items()}


# ---------------------------------------------------------------- engine
class ServeEngine:
    """The continuous-batching engine: host scheduler + one jit'd mixed
    prefill/decode step over the paged cache.

    ``model`` is a model module exposing ``init_cache`` / ``apply_cached``
    (models/llama.py, models/moe_llama.py); ``model_cfg`` its config
    dataclass; ``params`` the trained pytree (host or global arrays).
    """

    def __init__(self, model, model_cfg, params, cfg: ServeConfig,
                 mesh=None, role: str = "mixed"):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        cfg.validate(model_max_seq=model_cfg.max_seq)
        self.model = model
        self.model_cfg = model_cfg
        self.cfg = cfg
        if mesh is None:
            from .. import runtime as _rt
            mesh = _rt.get().mesh
        self.mesh = mesh
        self.scheduler = Scheduler(cfg, role=role)
        self._repl = NamedSharding(mesh, P())
        self._cache_shd = cache_shardings(mesh, cfg.cache_blocks,
                                          model_cfg.n_kv_heads)
        leaves = jax.tree_util.tree_leaves(params)
        if leaves and isinstance(leaves[0], jax.Array):
            self.params = params
        else:
            self.params = replicate_global(params, mesh)
        cache_struct = jax.eval_shape(
            lambda: model.init_cache(model_cfg, cfg.cache_blocks,
                                     cfg.block_size))
        self.cache = jax.tree_util.tree_map(
            lambda x: _global_zeros(x.shape, x.dtype, self._cache_shd),
            cache_struct)
        # Host-RAM spill tier behind the device pool
        # (docs/serving.md#replicated-tier): evicted-but-warm radix
        # blocks migrate to host instead of dying, reload on hit.
        self._spill: Optional[HostSpillPool] = None
        if cfg.spill_blocks > 0 and self.scheduler.prefix is not None:
            self._spill = HostSpillPool(cfg.spill_blocks,
                                        self._read_block,
                                        self._write_block)
            self.scheduler.prefix.spill = self._spill
        self._handoffs = 0
        self._step_fn = self._build_step()
        # One-deep tick pipeline (the loader.prefetch deque pattern):
        # holds (plan, device next-token array) until the next step()
        # harvests it, so host scheduling overlaps device compute.
        self._inflight: "collections.deque" = collections.deque()
        self.tick = 0
        self._tokens_prefill = 0
        self._tokens_decode = 0
        self._last_fill = 0.0
        self._prefill_chunks = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        # Rolling digest of every dispatch's scheduling decisions
        # (admission prefix hits, chunk boundaries, draft tokens, CoW
        # copies).  Rank 0 publishes it in the plan stream and followers
        # assert equality — lockstep divergence is caught at the tick it
        # happens, not when token digests drift (serve/worker.py).
        self.sched_digest = ""
        # The pool's true byte footprint: the preallocated cache pytree
        # itself (this rank's shards of it are the resident bytes the
        # memory plane attributes to the kv_pool plane).
        self._pool_bytes = sum(
            int(np.prod(x.shape)) * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(cache_struct))
        try:
            from ..perf.memstats import set_kv_pool_provider
            set_kv_pool_provider(self.kv_pool)
        except Exception:
            pass  # the memory plane must never block engine bring-up

    # ----------------------------------------------------------- compile
    def _build_step(self):
        import jax
        import jax.numpy as jnp

        model, mcfg = self.model, self.model_cfg

        def step_fn(params, cache, block_tables, lengths, n_new, tokens,
                    copy_src, copy_dst):
            # CoW prefix sharing: clone diverged blocks BEFORE this
            # tick's writes (padding entries route dst out of bounds and
            # drop).  The gather reads the pre-step pool, so a source
            # block recycled in this same tick still copies its old
            # content (functional semantics — see Scheduler._admit_blocks).
            cache = model.copy_blocks(cache, copy_src, copy_dst)
            out = model.apply_cached(params, tokens, mcfg, cache,
                                     block_tables, lengths, n_new)
            logits, cache = out[0], out[1]  # moe also returns aux
            # Greedy sampling ON DEVICE at EVERY chunk position: row
            # [s, j] is the greedy continuation after consuming tokens
            # [s, :j+1] — prefill reads its last valid position,
            # speculative decode verifies its whole draft row against
            # it.  Argmax ties break identically on every rank (SPMD
            # determinism).
            next_tokens = jnp.argmax(
                logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
            return cache, next_tokens

        return jax.jit(
            step_fn,
            donate_argnums=(1,),
            out_shardings=(
                jax.tree_util.tree_map(lambda _: self._cache_shd,
                                       self.cache),
                self._repl))

    # ------------------------------------------------------------ intake
    def submit(self, tokens, max_new_tokens: int,
               req_id: Optional[str] = None,
               eos_id: Optional[int] = None,
               trace: Optional[Dict[str, Any]] = None) -> Request:
        req = Request(tokens, max_new_tokens, req_id=req_id,
                      eos_id=eos_id if eos_id is not None
                      else self.cfg.eos_id)
        req.trace = trace
        return self.scheduler.submit(req)

    def has_work(self) -> bool:
        return self.scheduler.has_work() or bool(self._inflight)

    # ---------------------------------------------------- block transfer
    def _read_block(self, block: int) -> Dict[str, Any]:
        """One pool block across all layers as host numpy (the spill
        tier's read side and the prefill handoff's export side).  D2H
        copy of [L, bs, kv_heads, hd] per cache leaf — one block, not
        the pool."""
        import jax
        flat = {}
        leaves, treedef = jax.tree_util.tree_flatten_with_path(self.cache)
        for path, leaf in leaves:
            key = "/".join(str(getattr(p, "key", p)) for p in path)
            flat[key] = np.asarray(leaf[:, block])
        return flat

    def _write_block(self, block: int, payload: Dict[str, Any]) -> None:
        """Write one block's host payload back into the device pool
        (spill reload / handoff import).  Functional ``.at[].set`` per
        leaf — runs between steps, so the next dispatch reads it."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten_with_path(self.cache)
        new = []
        for path, leaf in leaves:
            key = "/".join(str(getattr(p, "key", p)) for p in path)
            arr = np.asarray(payload[key]).astype(leaf.dtype)
            new.append(leaf.at[:, block].set(arr))
        self.cache = jax.tree_util.tree_unflatten(treedef, new)

    # ------------------------------------------------------ disaggregation
    def export_handoff(self, req: Request, first_token: int
                       ) -> Dict[str, Any]:
        """Serialize one finished prefill for a decode engine: the
        request identity/budget, the first sampled token, and the
        prompt blocks' KV as encoded payloads.  Pure read — the caller
        decides when to finish the request."""
        bs = self.cfg.block_size
        n_blocks = -(-req.prompt_len // bs)
        return {
            "req_id": req.req_id,
            "tokens": list(req.tokens),
            "max_new_tokens": req.max_new_tokens,
            "eos_id": req.eos_id,
            "first_token": int(first_token),
            "trace": req.trace,
            "queue_s": (req.admitted_t - req.submitted_t
                        if req.admitted_t is not None else None),
            "prefill_s": (time.perf_counter() - req.admitted_t
                          if req.admitted_t is not None else None),
            # Wall clock, not perf_counter: the export/import stamps
            # cross process boundaries (the handoff component of the
            # per-request SLO attribution is their difference).
            "exported_t": time.time(),
            "blocks": [encode_block_payload(self._read_block(b))
                       for b in req.blocks[:n_blocks]],
        }

    def import_prefill(self, handoff: Dict[str, Any]) -> Request:
        """Decode-side intake of a prefill rank's handoff record: queue
        it for installation (Scheduler._drain_imports) — the request
        enters the slot table directly in decode state with its prompt
        KV written from the payload, skipping prefill entirely."""
        req = Request(handoff["tokens"], int(handoff["max_new_tokens"]),
                      req_id=handoff.get("req_id"),
                      eos_id=(handoff.get("eos_id")
                              if handoff.get("eos_id") is not None
                              else self.cfg.eos_id))
        req.trace = handoff.get("trace")
        req.upstream = {k: float(handoff[k])
                        for k in ("queue_s", "prefill_s")
                        if handoff.get(k) is not None} or None
        exported_t = handoff.get("exported_t")
        if exported_t is not None:
            req.handoff_s = max(0.0, time.time() - float(exported_t))
        payloads = [decode_block_payload(p) for p in handoff["blocks"]]
        self.scheduler.queue_import(req, payloads,
                                    int(handoff["first_token"]))
        from ..utils import metrics as M
        M.SERVE_IMPORTS.inc()
        self._span("HANDOFF", req, req.handoff_s,
                   end_t=time.perf_counter(),
                   extra={"blocks": len(handoff["blocks"])})
        return req

    def prefix_fps(self) -> Tuple[List[str], str]:
        """This engine's radix-tree advertisement for the replica
        router: (fingerprints, digest) — what rank 0 piggybacks on the
        stats publish (serve/replica.py)."""
        from .replica import prefix_fingerprints, fold_digest
        if self.scheduler.prefix is None:
            return [], fold_digest([])
        fps = prefix_fingerprints(self.scheduler.prefix)
        return fps, fold_digest(fps)

    # -------------------------------------------------------------- tick
    def step(self) -> Dict[str, Any]:
        """Run one engine tick.  Returns the COMPLETED tick's report
        (one tick of pipeline lag): {"tick", "processed", "emitted":
        {req_id: [new tokens]}, "finished": [Request]} — an idle report
        when nothing completed."""
        report = self._harvest()
        self._dispatch()
        # Handoff installs surface their first token (sampled by the
        # prefill rank) in this report — the emission order a mixed
        # engine would have produced at prefill completion.
        for req, toks in self.scheduler.take_import_emits():
            report["emitted"].setdefault(req.req_id, []).extend(toks)
            if req.state == "done":
                report["finished"].append(req)
        self._update_gauges()
        return report

    def flush(self) -> List[Dict[str, Any]]:
        """Drain until idle (no planned work, nothing in flight)."""
        out = []
        while self.has_work():
            out.append(self.step())
        return out

    def _dispatch(self) -> None:
        prefix = self.scheduler.prefix
        spill = prefix.spill if prefix is not None else None
        reloads0 = spill.reloaded_total if spill is not None else 0
        work = self.scheduler.plan()
        # Handoff imports staged by the plan: land the prompt KV in the
        # pool BEFORE this tick's step reads it (functional .at writes,
        # device-ordered ahead of the step call).
        for b, payload in self.scheduler.take_pending_writes():
            self._write_block(b, payload)
        for slot, req, n in work:
            if req.admitted_t is not None and not req.pos and \
                    req.state == "prefill" and req.ctx_len == 0:
                # queue-wait span, emitted once at admission
                self._span("NEGOTIATE", req,
                           req.admitted_t - req.submitted_t,
                           end_t=req.admitted_t)
        if spill is not None:
            delta = spill.reloaded_total - reloads0
            if delta > 0:
                for slot, req, n in work:
                    if req.state == "prefill":
                        self._span("SPILL_RELOAD", req, 0.0,
                                   end_t=time.perf_counter(),
                                   extra={"reloads": delta})
                        break
        if not work:
            return
        cfg = self.cfg
        S, C = cfg.max_slots, cfg.prefill_chunk
        tokens = np.zeros((S, C), np.int32)
        lengths = np.zeros(S, np.int32)
        n_new = np.zeros(S, np.int32)
        for slot, req, n in work:
            if req.state == "prefill":
                tokens[slot, :n] = req.tokens[req.pos:req.pos + n]
            else:
                # Speculative verify row: the last emitted token plus
                # the drafts — one multi-token apply_cached call scores
                # every draft position (n == 1 + len(draft)).
                tokens[slot, :n] = [req.out_tokens[-1]] + req.draft
            lengths[slot] = req.ctx_len
            n_new[slot] = n
        copies = self.scheduler.take_copies()
        copy_src = np.zeros(S, np.int32)
        copy_dst = np.full(S, cfg.cache_blocks, np.int32)  # no-op: dropped
        for j, (src, dst) in enumerate(copies):
            copy_src[j], copy_dst[j] = src, dst
        self._fold_sched(work, copies)
        # Async dispatch: device_put + jit return immediately; the next
        # step() harvests, so this tick's H2D staging and compute run
        # behind the caller's host work (the double-buffer pattern).
        dev = [_make_global(a, self._repl)
               for a in (np.asarray(self.scheduler.block_tables),
                         lengths, n_new, tokens, copy_src, copy_dst)]
        self.cache, next_tokens = self._step_fn(
            self.params, self.cache, *dev)
        used = int(n_new.sum())
        self._last_fill = used / cfg.max_batch_tokens
        self._inflight.append((self.tick, work, next_tokens, used))
        self.tick += 1

    def _fold_sched(self, work, copies) -> None:
        """Fold one dispatch's scheduling decisions into the rolling
        digest: slot/request/phase/width (width encodes chunk boundaries
        and draft length), the admission-resume positions (prefix hits),
        the draft tokens themselves, and the CoW copy pairs."""
        summary = [(slot, req.req_id, req.state, n,
                    req.pos if req.state == "prefill" else req.ctx_len,
                    [] if req.state == "prefill" else list(req.draft))
                   for slot, req, n in work]
        rec = json.dumps([summary, copies], separators=(",", ":"))
        self.sched_digest = hashlib.sha1(
            (self.sched_digest + rec).encode()).hexdigest()[:16]

    def _harvest(self) -> Dict[str, Any]:
        if not self._inflight:
            return {"tick": None, "processed": 0, "emitted": {},
                    "finished": [], "handoff": []}
        from ..utils import metrics as M
        tick, work, next_tokens, used = self._inflight.popleft()
        tokens_host = np.asarray(next_tokens)  # D2H fence for this tick
        now = time.perf_counter()
        emitted: Dict[str, List[int]] = {}
        finished: List[Request] = []
        handoffs: List[Dict[str, Any]] = []
        for slot, req, n in work:
            decode_row = req.state != "prefill"
            if not decode_row:
                req.pos += n
                req.ctx_len += n
                self._tokens_prefill += n
                self._prefill_chunks += 1
                M.SERVE_TOKENS.inc(n, phase="prefill")
                M.SERVE_PREFILL_CHUNKS.inc()
                if req.pos < req.prompt_len:
                    continue  # still prefilling next tick
                if self.scheduler.role == "prefill":
                    # Disaggregation: this rank's job ends at prefill
                    # completion — export the prompt KV + first token
                    # for a decode engine, keep the prefix warm in OUR
                    # tree (the next shared prompt still hits), free
                    # the slot.  The first token is NOT emitted here;
                    # the decode side emits it (exactly-once).
                    first = int(tokens_host[slot, n - 1])
                    self.scheduler.register_prefix(req)
                    handoffs.append(self.export_handoff(req, first))
                    self.scheduler.finish(req, "prefill_done")
                    finished.append(req)
                    self._handoffs += 1
                    M.SERVE_HANDOFFS.inc()
                    continue
                req.state = "decode"
                self.scheduler.register_prefix(req)
                new_toks = [int(tokens_host[slot, n - 1])]
            else:
                # Greedy verification: row[j] is the greedy continuation
                # after consuming input positions <= j, so draft[j] is
                # accepted iff it EQUALS the previous greedy token —
                # emitted output is bit-identical to plain greedy, only
                # the tokens-per-tick rate changes.
                row = tokens_host[slot]
                new_toks = [int(row[0])]
                for j, d in enumerate(req.draft):
                    if int(d) != new_toks[-1]:
                        break
                    new_toks.append(int(row[j + 1]))
                accepted = len(new_toks) - 1
                req.ctx_len += 1 + accepted
                if req.draft:
                    self._spec_drafted += len(req.draft)
                    self._spec_accepted += accepted
                    M.SERVE_SPEC_DRAFTED.inc(len(req.draft))
                    if accepted:
                        M.SERVE_SPEC_ACCEPTED.inc(accepted)
            emitted_n = 0
            for tok in new_toks:
                req.out_tokens.append(tok)
                emitted.setdefault(req.req_id, []).append(tok)
                emitted_n += 1
                if req.first_token_t is None:
                    req.first_token_t = now
                    M.SERVE_TTFT.observe(req.ttft())
                    self._span("PREFILL", req, now - req.admitted_t,
                               end_t=now, extra={"prompt": req.prompt_len})
                if (req.eos_id is not None and tok == req.eos_id) or \
                        len(req.out_tokens) >= req.max_new_tokens:
                    reason = ("eos" if req.eos_id is not None
                              and tok == req.eos_id else "completed")
                    self.scheduler.finish(req, reason)
                    finished.append(req)
                    tpot = req.tpot()
                    if tpot is not None:
                        M.SERVE_TPOT.observe(tpot)
                    M.SERVE_REQUESTS.inc(outcome=reason)
                    self._span("DECODE", req,
                               req.done_t - req.first_token_t,
                               end_t=req.done_t,
                               extra={"generated": len(req.out_tokens)})
                    break  # verified-but-post-EOS drafts are discarded
            if decode_row:
                self._tokens_decode += emitted_n
                M.SERVE_TOKENS.inc(emitted_n, phase="decode")
        from .. import postmortem as PM
        PM.record_step(tick)  # engine liveness on the /health plane
        return {"tick": tick, "processed": used, "emitted": emitted,
                "finished": finished, "handoff": handoffs}

    def _update_gauges(self) -> None:
        from ..utils import metrics as M
        M.SERVE_QUEUE_DEPTH.set(self.scheduler.queue_depth)
        M.SERVE_BATCH_FILL.set(self._last_fill)

    # ------------------------------------------------------------- spans
    def _span(self, phase: str, req: Request, duration_s: float,
              end_t: float, extra: Optional[dict] = None) -> None:
        """Per-request phase span on the merged timeline's 'serve' lane
        (utils/timeline.record_span); no-op without an active timeline."""
        try:
            from .. import runtime as _rt
            if not _rt.is_initialized():
                return
            tl = getattr(_rt.get(), "timeline", None)
            if tl is None:
                return
            from . import trace as _trace
            args = _trace.span_args(getattr(req, "trace", None), phase,
                                    rid=req.req_id, req=req.req_id)
            if extra:
                args.update(extra)
            lag_us = (time.perf_counter() - end_t) * 1e6
            tl.record_span("serve", phase, max(duration_s, 0.0) * 1e6,
                           args=args, ts_us=tl.now_us() - lag_us
                           - max(duration_s, 0.0) * 1e6)
        except Exception:
            pass  # tracing must never take serving down

    # -------------------------------------------------------------- view
    def kv_pool(self) -> Dict[str, Any]:
        """KV-cache pool occupancy for ``GET /serve/stats`` and the
        memory plane (memstats.set_kv_pool_provider registers this at
        construction; docs/memory.md#kv-pool):

          * the allocator's used/free/shared block split;
          * ``pool_bytes`` — the preallocated cache pytree's true size
            (blocks x block_bytes; resident whether or not blocks are
            used — a paged pool's cost is its reservation);
          * ``fragmentation`` — the worst-case-reservation waste: 1 -
            tokens actually written over tokens reserved across active
            requests (prefix-cache-held blocks excluded — they hold
            real KV);
          * ``eviction_pressure`` — prefix-cache evictions per
            admission: > 0 means admissions only succeed by evicting
            cached prefixes (the pool is effectively full).
        """
        s = self.scheduler
        occ = s.allocator.occupancy()
        nb = max(occ["num_blocks"], 1)
        block_bytes = self._pool_bytes // nb
        reserved_tokens = written_tokens = 0
        for req in s.slots:
            if req is not None:
                reserved_tokens += len(req.blocks) * self.cfg.block_size
                written_tokens += req.ctx_len
        frag = (1.0 - written_tokens / reserved_tokens
                if reserved_tokens else 0.0)
        evictions = s.prefix.evictions if s.prefix is not None else 0
        occ.update({
            "block_size": self.cfg.block_size,
            "block_bytes": block_bytes,
            "pool_bytes": self._pool_bytes,
            "used_bytes": occ["used_blocks"] * block_bytes,
            "fragmentation": round(frag, 4),
            "evictions": evictions,
            "eviction_pressure": (round(evictions / s.admissions, 4)
                                  if s.admissions else 0.0),
        })
        if self._spill is not None:
            spill = self._spill.counters()
            spill["held_bytes_est"] = \
                self._spill.blocks_held * block_bytes
            occ["spill"] = spill
        return occ

    def close(self) -> None:
        """Unregister the memory plane's KV-pool provider — a torn-down
        engine must not keep reporting a stale pool."""
        try:
            from ..perf import memstats
            if memstats._kv_pool_fn == self.kv_pool:
                memstats.set_kv_pool_provider(None)
        except Exception:
            pass

    def stats(self) -> Dict[str, Any]:
        s = self.scheduler
        prefix = s.prefix
        out = {
            "tick": self.tick,
            "role": s.role,
            "active": s.active,
            "waiting": s.queue_depth,
            "completed": s.completed,
            "imports": s.imports,
            "handoffs": self._handoffs,
            "free_blocks": s.allocator.free_count,
            "kv_pool": self.kv_pool(),
            "batch_fill": round(self._last_fill, 4),
            "tokens_prefill": self._tokens_prefill,
            "tokens_decode": self._tokens_decode,
            "prefill_chunks": self._prefill_chunks,
            "prefix_cache": {"enabled": prefix is not None},
            "spec": {
                "enabled": bool(self.cfg.spec_decode),
                "drafted_tokens": self._spec_drafted,
                "accepted_tokens": self._spec_accepted,
                "accept_rate": (
                    round(self._spec_accepted / self._spec_drafted, 4)
                    if self._spec_drafted else None),
            },
        }
        if prefix is not None:
            out["prefix_cache"].update({
                "hits": prefix.hits,
                "hit_tokens": prefix.hit_tokens,
                "blocks_shared": prefix.blocks_shared,
                "cached_blocks": prefix.size,
                "cow_copies": prefix.cow_copies,
                "evictions": prefix.evictions,
                "hit_rate": (round(prefix.hits / s.admissions, 4)
                             if s.admissions else None),
            })
        if self._spill is not None:
            out["spill"] = self._spill.counters()
        return out


# ----------------------------------------------------- servable loading
SERVE_MANIFEST = "serve.json"

_MODEL_MODULES = {"llama": "horovod_tpu.models.llama",
                  "moe_llama": "horovod_tpu.models.moe_llama"}


def save_servable(directory: str, model_name: str, config, params,
                  step: int = 0) -> None:
    """Write a servable directory: ``serve.json`` (model family +
    config) beside a sharded checkpoint (checkpoint.py) — what
    ``hvdrun --serve DIR`` consumes."""
    import dataclasses
    from .. import checkpoint as ckpt
    os.makedirs(directory, exist_ok=True)
    cfg_dict = {k: v for k, v in dataclasses.asdict(config).items()
                if not hasattr(v, "dtype")}
    cfg_dict.pop("dtype", None)
    with open(os.path.join(directory, SERVE_MANIFEST), "w") as f:
        json.dump({"model": model_name, "config": cfg_dict}, f)
    ckpt.save_checkpoint(directory, step, params=params)


def load_servable(directory: str, mesh) -> Tuple[Any, Any, Any]:
    """Read a servable directory -> (model module, model config, global
    replicated params).  ``serve.json``: {"model": "llama"|"moe_llama",
    "config": <name in CONFIGS or kwarg dict>, "seed": int?}.  Params
    come from the latest checkpoint under the directory (restored
    through checkpoint.py into replicated shardings); with no
    checkpoint present, a seeded random init serves — the CPU-virtual
    smoke path, loudly labeled."""
    import importlib
    import sys

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    with open(os.path.join(directory, SERVE_MANIFEST)) as f:
        manifest = json.load(f)
    name = manifest.get("model", "llama")
    if name not in _MODEL_MODULES:
        raise ValueError(f"serve.json model {name!r} unknown; expected "
                         f"one of {sorted(_MODEL_MODULES)}")
    model = importlib.import_module(_MODEL_MODULES[name])
    spec = manifest.get("config", "tiny")
    if isinstance(spec, str):
        model_cfg = model.CONFIGS[spec]
    else:
        model_cfg = type(model.CONFIGS["tiny"])(**spec)

    seed = int(manifest.get("seed", 0))
    host = model.init(jax.random.PRNGKey(seed), model_cfg)
    repl = NamedSharding(mesh, P())
    from .. import checkpoint as ckpt
    try:
        mgr = ckpt.CheckpointManager(directory, max_to_keep=10_000)
        try:
            latest = mgr.latest_step()
            if latest is not None:
                template = jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                   sharding=repl), host)
                params = mgr.restore(latest, params=template)["params"]
                return model, model_cfg, params
        finally:
            mgr.close()
    except FileNotFoundError:
        pass
    print(f"[hvd.serve] no checkpoint under {directory}; serving "
          f"seed={seed} random-init params (smoke mode)",
          file=sys.stderr, flush=True)
    return model, model_cfg, replicate_global(host, mesh)
